"""Recurrent layers.

Parity: reference `python/paddle/nn/layer/rnn.py` (RNNCellBase,
SimpleRNNCell/LSTMCell/GRUCell, RNN/BiRNN wrappers, multi-layer
SimpleRNN/LSTM/GRU over phi rnn kernels/cuDNN). TPU-first: the time loop
is `lax.scan` — one compiled fused step reused across time (no cuDNN
descriptor machinery), gates are single [.., 4h] / [.., 3h] MXU matmuls.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ...core.dispatch import apply
from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as init
from .layers import Layer


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        from ... import ops
        b = batch_ref.shape[batch_dim_idx]
        return ops.full([b, self.hidden_size], init_value,
                        dtype or "float32")


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / hidden_size ** 0.5
        u = init.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [hidden_size], attr=bias_ih_attr, default_initializer=u,
            is_bias=True)
        self.bias_hh = self.create_parameter(
            [hidden_size], attr=bias_hh_attr, default_initializer=u,
            is_bias=True)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else \
            (lambda a: jnp.maximum(a, 0))

        def fn(x, h, wi, wh, bi, bh):
            out = act(x @ wi.T + bi + h @ wh.T + bh)
            return out, out

        out, new = apply(fn, inputs, states, self.weight_ih,
                         self.weight_hh, self.bias_ih, self.bias_hh,
                         name="simple_rnn_cell")
        return out, new

    @property
    def state_shape(self):
        return (self.hidden_size,)


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / hidden_size ** 0.5
        u = init.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], attr=bias_ih_attr, default_initializer=u,
            is_bias=True)
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], attr=bias_hh_attr, default_initializer=u,
            is_bias=True)

    def forward(self, inputs, states=None):
        if states is None:
            h = self.get_initial_states(inputs)
            c = self.get_initial_states(inputs)
        else:
            h, c = states

        def fn(x, hh, cc, wi, wh, bi, bh):
            gates = x @ wi.T + bi + hh @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            c_new = f * cc + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new

        h_new, c_new = apply(fn, inputs, h, c, self.weight_ih,
                             self.weight_hh, self.bias_ih, self.bias_hh,
                             name="lstm_cell")
        return h_new, (h_new, c_new)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / hidden_size ** 0.5
        u = init.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], attr=bias_ih_attr, default_initializer=u,
            is_bias=True)
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], attr=bias_hh_attr, default_initializer=u,
            is_bias=True)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def fn(x, h, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = h @ wh.T + bh
            i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
            h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(i_r + h_r)
            z = jax.nn.sigmoid(i_z + h_z)
            n = jnp.tanh(i_n + r * h_n)
            return (1 - z) * n + z * h

        h_new = apply(fn, inputs, states, self.weight_ih, self.weight_hh,
                      self.bias_ih, self.bias_hh, name="gru_cell")
        return h_new, h_new

    @property
    def state_shape(self):
        return (self.hidden_size,)


def _scan_layer(cell_kind, x, h0, params, reverse=False):
    """One direction of one layer as lax.scan over time.
    x: [b, t, in]; params: dict of arrays; h0: tuple of [b, h]."""

    def lstm_step(carry, xt):
        h, c = carry
        gates = xt @ params["wi"].T + params["bi"] + \
            h @ params["wh"].T + params["bh"]
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return (h_new, c_new), h_new

    def gru_step(carry, xt):
        (h,) = carry
        gi = xt @ params["wi"].T + params["bi"]
        gh = h @ params["wh"].T + params["bh"]
        i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
        h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(i_r + h_r)
        z = jax.nn.sigmoid(i_z + h_z)
        n = jnp.tanh(i_n + r * h_n)
        h_new = (1 - z) * n + z * h
        return (h_new,), h_new

    def rnn_step(carry, xt):
        (h,) = carry
        h_new = jnp.tanh(xt @ params["wi"].T + params["bi"] +
                         h @ params["wh"].T + params["bh"])
        return (h_new,), h_new

    step = {"lstm": lstm_step, "gru": gru_step, "rnn": rnn_step}[cell_kind]
    xt = jnp.swapaxes(x, 0, 1)  # [t, b, in]
    carry, ys = lax.scan(step, h0, xt, reverse=reverse)
    return jnp.swapaxes(ys, 0, 1), carry


class _RNNBase(Layer):
    _kind = "rnn"
    _gates = 1

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirectional = direction in ("bidirect", "bidirectional")
        num_dir = 2 if self.bidirectional else 1
        self.num_directions = num_dir
        std = 1.0 / hidden_size ** 0.5
        u = init.Uniform(-std, std)
        g = self._gates
        from .container import ParameterList
        self._params = ParameterList()
        self._layout = []  # (layer, dir) per 4-param group
        for layer in range(num_layers):
            for d in range(num_dir):
                in_sz = input_size if layer == 0 else hidden_size * num_dir
                for shape in ([g * hidden_size, in_sz],
                              [g * hidden_size, hidden_size],
                              [g * hidden_size], [g * hidden_size]):
                    self._params.append(self.create_parameter(
                        shape, default_initializer=u,
                        is_bias=len(shape) == 1))
                self._layout.append((layer, d))

    def _group(self, layer, d):
        idx = self._layout.index((layer, d)) * 4
        p = list(self._params)[idx:idx + 4]
        return {"wi": p[0], "wh": p[1], "bi": p[2], "bh": p[3]}

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ... import ops
        x = inputs
        if self.time_major:
            x = ops.transpose(x, [1, 0, 2])
        kind = self._kind
        num_dir = self.num_directions
        b = x.shape[0]

        def run(xa, *flat):
            it = iter(flat)
            groups = [{k: next(it) for k in ("wi", "wh", "bi", "bh")}
                      for _ in range(self.num_layers * num_dir)]
            h_final, c_final = [], []
            cur = xa
            gi = 0
            for layer in range(self.num_layers):
                outs = []
                for d in range(num_dir):
                    params = groups[gi]
                    gi += 1
                    hsize = (b, self.hidden_size)
                    if kind == "lstm":
                        h0 = (jnp.zeros(hsize, xa.dtype),
                              jnp.zeros(hsize, xa.dtype))
                    else:
                        h0 = (jnp.zeros(hsize, xa.dtype),)
                    ys, carry = _scan_layer(kind, cur, h0, params,
                                            reverse=(d == 1))
                    outs.append(ys)
                    h_final.append(carry[0])
                    if kind == "lstm":
                        c_final.append(carry[1])
                cur = outs[0] if num_dir == 1 else \
                    jnp.concatenate(outs, axis=-1)
            h_stack = jnp.stack(h_final, 0)
            if kind == "lstm":
                return cur, h_stack, jnp.stack(c_final, 0)
            return cur, h_stack

        flat = []
        for layer in range(self.num_layers):
            for d in range(num_dir):
                gp = self._group(layer, d)
                flat += [gp["wi"], gp["wh"], gp["bi"], gp["bh"]]
        out = apply(run, x, *flat, name=self._kind)
        if self._kind == "lstm":
            y, h, c = out
            states = (h, c)
        else:
            y, h = out
            states = h
        if self.time_major:
            y = ops.transpose(y, [1, 0, 2])
        return y, states


class SimpleRNN(_RNNBase):
    _kind = "rnn"
    _gates = 1


class LSTM(_RNNBase):
    _kind = "lstm"
    _gates = 4


class GRU(_RNNBase):
    _kind = "gru"
    _gates = 3


class RNN(Layer):
    """Wrapper running a cell over time (reference rnn.py RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ... import ops
        x = inputs
        if self.time_major:
            x = ops.transpose(x, [1, 0, 2])
        t = x.shape[1]
        steps = range(t - 1, -1, -1) if self.is_reverse else range(t)
        states = initial_states
        outs = [None] * t
        for i in steps:
            out, states = self.cell(x[:, i], states)
            outs[i] = out
        y = ops.stack(outs, axis=1)
        if self.time_major:
            y = ops.transpose(y, [1, 0, 2])
        return y, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ... import ops
        sf = sb = None
        if initial_states is not None:
            sf, sb = initial_states
        yf, stf = self.rnn_fw(inputs, sf)
        yb, stb = self.rnn_bw(inputs, sb)
        return ops.concat([yf, yb], axis=-1), (stf, stb)
