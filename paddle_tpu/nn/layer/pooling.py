"""Pooling layers (reference: python/paddle/nn/layer/pooling.py)."""

from __future__ import annotations

from .. import functional as F
from .layers import Layer


class _Pool(Layer):
    def __init__(self, kernel_size=None, stride=None, padding=0,
                 ceil_mode=False, return_mask=False, exclusive=True,
                 divisor_override=None, output_size=None, data_format=None,
                 name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.return_mask = return_mask
        self.exclusive = exclusive
        self.divisor_override = divisor_override
        self.output_size = output_size
        self.data_format = data_format


class MaxPool1D(_Pool):
    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding,
                            self.return_mask, self.ceil_mode)


class MaxPool2D(_Pool):
    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            self.return_mask, self.ceil_mode)


class MaxPool3D(_Pool):
    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            self.return_mask, self.ceil_mode)


class AvgPool1D(_Pool):
    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding,
                            self.exclusive, self.ceil_mode)


class AvgPool2D(_Pool):
    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            self.ceil_mode, self.exclusive,
                            self.divisor_override)


class AvgPool3D(_Pool):
    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding,
                            self.ceil_mode, self.exclusive,
                            self.divisor_override)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size, self.return_mask)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size, self.return_mask)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size, self.return_mask)
