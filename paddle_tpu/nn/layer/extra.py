"""nn layer long tail (reference python/paddle/nn/layer/): wrappers over
nn.functional.extra + beam-search decoding.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...core.dispatch import as_index, unwrap
from ...core.tensor import Tensor
from .. import functional as F
from .layers import Layer

__all__ = [
    "Silu", "Softmax2D", "ZeroPad1D", "ZeroPad3D", "LPPool1D", "LPPool2D",
    "FractionalMaxPool2D", "FractionalMaxPool3D", "MaxUnPool1D",
    "MaxUnPool2D", "MaxUnPool3D", "MultiMarginLoss", "HSigmoidLoss",
    "AdaptiveLogSoftmaxWithLoss", "RNNTLoss",
    "TripletMarginWithDistanceLoss", "FeatureAlphaDropout",
    "BeamSearchDecoder", "dynamic_decode",
]


class Silu(Layer):
    def forward(self, x):
        return F.silu(x)


class Softmax2D(Layer):
    """Softmax over the channel dim of NCHW input."""

    def forward(self, x):
        return F.softmax(x, axis=-3)


class ZeroPad1D(Layer):
    def __init__(self, padding, data_format="NCL", name=None):
        super().__init__()
        self.padding = padding if isinstance(padding, (list, tuple)) \
            else [padding, padding]

    def forward(self, x):
        from ...ops import pad
        return pad(x, list(self.padding), mode="constant", value=0.0)


class ZeroPad3D(Layer):
    def __init__(self, padding, data_format="NCDHW", name=None):
        super().__init__()
        p = padding if isinstance(padding, (list, tuple)) \
            else [padding] * 6
        self.padding = list(p)

    def forward(self, x):
        from ...ops import pad
        return pad(x, self.padding, mode="constant", value=0.0)


class LPPool1D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self.args = (norm_type, kernel_size, stride, padding, ceil_mode)

    def forward(self, x):
        return F.lp_pool1d(x, *self.args)


class LPPool2D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self.args = (norm_type, kernel_size, stride, padding, ceil_mode)

    def forward(self, x):
        return F.lp_pool2d(x, *self.args)


class FractionalMaxPool2D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.random_u = random_u

    def forward(self, x):
        return F.fractional_max_pool2d(x, self.output_size,
                                       random_u=self.random_u)


class FractionalMaxPool3D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.random_u = random_u

    def forward(self, x):
        return F.fractional_max_pool3d(x, self.output_size,
                                       random_u=self.random_u)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding)
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, *self.args,
                              output_size=self.output_size)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding)
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool2d(x, indices, *self.args,
                              output_size=self.output_size)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding)
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, *self.args,
                              output_size=self.output_size)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.args = (p, margin, weight, reduction)

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, *self.args)


class HSigmoidLoss(Layer):
    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        from .. import initializer as I
        self.num_classes = num_classes
        n_nodes = num_classes - 1 if num_classes > 1 else 1
        self.weight = self.create_parameter(
            [n_nodes if not is_custom else num_classes, feature_size],
            attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0 / feature_size ** 0.5))
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [n_nodes if not is_custom else num_classes],
                attr=bias_attr, is_bias=True,
                default_initializer=I.Constant(0.0))

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self.num_classes,
                               self.weight, bias=self.bias,
                               path_table=path_table,
                               path_code=path_code)


class AdaptiveLogSoftmaxWithLoss(Layer):
    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        from .. import initializer as I
        self.cutoffs = list(cutoffs) + [n_classes]
        self.shortlist = self.cutoffs[0]
        n_clusters = len(self.cutoffs) - 1
        self.head_weight = self.create_parameter(
            [in_features, self.shortlist + n_clusters],
            default_initializer=I.XavierNormal())
        self.head_bias = None
        if head_bias:
            self.head_bias = self.create_parameter(
                [self.shortlist + n_clusters], is_bias=True,
                default_initializer=I.Constant(0.0))
        self.tail_weights = []
        for i in range(n_clusters):
            sz = self.cutoffs[i + 1] - self.cutoffs[i]
            w = self.create_parameter([in_features, sz],
                                      default_initializer=I.XavierNormal())
            self.tail_weights.append(w)
            setattr(self, f"tail_w_{i}", w)

    def forward(self, input, label):
        return F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tail_weights,
            self.cutoffs, head_bias=self.head_bias)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.0, reduction="mean",
                 name=None):
        super().__init__()
        self.args = (blank, fastemit_lambda, reduction)

    def forward(self, logits, labels, logit_lengths, label_lengths):
        return F.rnnt_loss(logits, labels, logit_lengths, label_lengths,
                           blank=self.args[0],
                           fastemit_lambda=self.args[1],
                           reduction=self.args[2])


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0,
                 swap=False, reduction="mean", name=None):
        super().__init__()
        self.distance_function = distance_function
        self.margin = margin
        self.swap = swap
        self.reduction = reduction

    def forward(self, anchor, positive, negative):
        from ...ops import maximum, mean, norm
        dist = self.distance_function or (
            lambda a, b: ((a - b) * (a - b)).sum(-1).sqrt())
        dp = dist(anchor, positive)
        dn = dist(anchor, negative)
        if self.swap:
            from ...ops import minimum
            dn = minimum(dn, dist(positive, negative))
        loss = maximum(dp - dn + self.margin,
                       Tensor(jnp.zeros_like(unwrap(dp))))
        if self.reduction == "mean":
            return loss.mean()
        if self.reduction == "sum":
            return loss.sum()
        return loss


class FeatureAlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.feature_alpha_dropout(x, self.p, training=self.training)


# ---------------------------------------------------------------------------
# beam search (reference nn/decode.py BeamSearchDecoder + dynamic_decode)
# ---------------------------------------------------------------------------

class BeamSearchDecoder:
    """Reference BeamSearchDecoder: wraps an RNN cell + output fn into a
    beam-stepping decoder driven by dynamic_decode."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = start_token
        self.end_token = end_token
        self.beam_size = beam_size
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def initialize(self, initial_cell_states):
        import jax

        states = initial_cell_states
        # tile cell states across the beam: [b, ...] -> [b*beam, ...]
        def tile(t):
            a = unwrap(t)
            a = jnp.repeat(a, self.beam_size, axis=0)
            return Tensor(a)
        states = jax.tree.map(tile, states,
                              is_leaf=lambda x: isinstance(x, Tensor))
        batch = None
        leaf = jax.tree.leaves(
            states, is_leaf=lambda x: isinstance(x, Tensor))[0]
        batch = leaf.shape[0] // self.beam_size
        ids = Tensor(jnp.full((batch * self.beam_size,),
                              self.start_token, jnp.int64))
        # log-probs: first beam 0, others -inf so step 1 is deterministic
        lp = jnp.tile(jnp.asarray(
            [0.0] + [-1e9] * (self.beam_size - 1), jnp.float32), (batch,))
        finished = jnp.zeros((batch * self.beam_size,), bool)
        return ids, (states, Tensor(lp), Tensor(finished))

    def step(self, time, inputs, states):
        cell_states, log_probs, finished = states
        emb = self.embedding_fn(inputs) if self.embedding_fn else inputs
        out, new_cell = self.cell(emb, cell_states)
        logits = self.output_fn(out) if self.output_fn else out
        lg = unwrap(logits).astype(jnp.float32)
        vocab = lg.shape[-1]
        beam = self.beam_size
        batch = lg.shape[0] // beam
        step_lp = jax.nn.log_softmax(lg, -1)
        # finished beams only extend with end_token at zero cost
        fin = unwrap(finished)
        keep = jnp.full((vocab,), -1e9).at[self.end_token].set(0.0)
        step_lp = jnp.where(fin[:, None], keep[None, :], step_lp)
        total = unwrap(log_probs)[:, None] + step_lp
        total = total.reshape(batch, beam * vocab)
        top_lp, top_idx = jax.lax.top_k(total, beam)
        src_beam = top_idx // vocab  # [batch, beam]
        tok = top_idx % vocab
        flat_src = (jnp.arange(batch)[:, None] * beam +
                    src_beam).reshape(-1)

        def regather(t):
            return Tensor(unwrap(t)[flat_src])
        import jax as _jax
        new_cell = _jax.tree.map(regather, new_cell,
                                 is_leaf=lambda x: isinstance(x, Tensor))
        new_fin = fin[flat_src] | (tok.reshape(-1) == self.end_token)
        ids = Tensor(tok.reshape(-1).astype(jnp.int64))
        return ids, (new_cell, Tensor(top_lp.reshape(-1)),
                     Tensor(new_fin)), Tensor(flat_src)

    def finished(self, states):
        return bool(np.asarray(unwrap(states[2])).all())


import jax  # noqa: E402  (used by decoder internals above)


def dynamic_decode(decoder, inits=None, max_step_num=32,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Reference dynamic_decode: run decoder.initialize/step until all
    beams finish or max_step_num; back-trace with gather_tree."""
    ids, states = decoder.initialize(inits)
    all_ids = []
    all_parents = []
    steps = 0
    for t in range(max_step_num):
        ids, states, parents = decoder.step(t, ids, states)
        all_ids.append(unwrap(ids))
        all_parents.append(unwrap(parents))
        steps += 1
        if decoder.finished(states):
            break
    beam = decoder.beam_size
    batch = all_ids[0].shape[0] // beam
    ids_t = jnp.stack(all_ids).reshape(steps, batch, beam)
    par_t = jnp.stack(all_parents).reshape(steps, batch, beam) % beam
    from ..functional.extra import gather_tree
    seqs = gather_tree(Tensor(ids_t), Tensor(par_t))
    out = seqs if output_time_major else Tensor(
        jnp.transpose(unwrap(seqs), (1, 2, 0)))
    if return_length:
        lens = Tensor(jnp.full((batch, beam), steps, jnp.int64))
        return out, lens
    return out
