"""Gradient clipping (reference: python/paddle/nn/clip.py).

Each clipper exposes ``_apply(params)`` mutating ``.grad`` in place (the
optimizer calls it before the update) — same hook point as the reference's
`_create_optimization_pass` grad-clip stage. Under hybrid parallelism the
distributed optimizer wraps ClipGradByGlobalNorm to take the norm across
mesh axes (reference hybrid_parallel_optimizer.py:255 semantics — with
GSPMD-sharded grads jnp.sum already reduces globally).
"""

from __future__ import annotations

import jax.numpy as jnp


class ClipGradBase:
    def _apply(self, params):
        raise NotImplementedError

    def _clip_arrays(self, grads, need_clip=None):
        """Pure form over jnp arrays (used by the compiled train step);
        ``need_clip`` is an optional bool list aligned with ``grads``."""
        raise NotImplementedError

    def __call__(self, params_grads):
        # functional form: list[(param, grad)] -> list[(param, grad)]
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _apply(self, params):
        for p in params:
            if p.grad is None or not getattr(p, "need_clip", True):
                continue
            p.grad._rebind(jnp.clip(p.grad._data, self.min, self.max))

    def _clip_arrays(self, grads, need_clip=None):
        need_clip = need_clip or [True] * len(grads)
        return [jnp.clip(g, self.min, self.max) if nc else g
                for g, nc in zip(grads, need_clip)]


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _apply(self, params):
        for p in params:
            if p.grad is None or not getattr(p, "need_clip", True):
                continue
            g = p.grad._data.astype(jnp.float32)
            norm = jnp.sqrt(jnp.sum(g * g))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12),
                                1.0)
            p.grad._rebind((g * scale).astype(p.grad.dtype))

    def _clip_arrays(self, grads, need_clip=None):
        need_clip = need_clip or [True] * len(grads)
        out = []
        for g, nc in zip(grads, need_clip):
            if not nc:
                out.append(g)
                continue
            g32 = g.astype(jnp.float32)
            norm = jnp.sqrt(jnp.sum(g32 * g32))
            scale = jnp.minimum(
                self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((g32 * scale).astype(g.dtype))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _apply(self, params):
        grads = [p.grad for p in params
                 if p.grad is not None and getattr(p, "need_clip", True)]
        if not grads:
            return
        sq = sum(jnp.sum(jnp.square(g._data.astype(jnp.float32)))
                 for g in grads)
        global_norm = jnp.sqrt(sq)
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        for g in grads:
            g._rebind((g._data.astype(jnp.float32) * scale).astype(g.dtype))

    def _clip_arrays(self, grads, need_clip=None):
        need_clip = need_clip or [True] * len(grads)
        active = [g for g, nc in zip(grads, need_clip) if nc]
        if not active:
            return list(grads)
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in active)
        global_norm = jnp.sqrt(sq)
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        return [(g.astype(jnp.float32) * scale).astype(g.dtype) if nc else g
                for g, nc in zip(grads, need_clip)]
