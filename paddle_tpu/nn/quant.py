"""Weight-only quantization (`paddle.nn.quant`).

Capability parity with the reference's
`python/paddle/nn/quant/quantized_linear.py` (`weight_quantize` /
`weight_dequantize` / `weight_only_linear`, int8 + int4, per-channel or
grouped scales) and the quantized decode path it feeds
(weight-only decode in the fused LLM ops).

TPU-first: quantized weights are stored int8 (int4 packed two-per-byte)
with per-channel (or per-group) f32 scales; the matmul dequantizes on the
fly into the source dtype — halving (or quartering) weight HBM traffic,
the thing decode is bound by. XLA fuses the convert+scale into the
matmul's operand load.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import apply, unwrap
from ..core.tensor import Tensor

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear",
           "quantize_for_inference"]


def _quant_arrays(w, algo, group_size):
    """w [in, out] -> (q int8 [in, out] or packed int4, scale f32)."""
    if group_size == -1:
        absmax = jnp.max(jnp.abs(w), axis=0)  # per output channel
        scale = (absmax / (7.0 if algo == "weight_only_int4" else 127.0)
                 ).astype(jnp.float32)
        scaled = w / jnp.maximum(scale, 1e-8)
    else:
        k, n = w.shape
        g = w.reshape(k // group_size, group_size, n)
        absmax = jnp.max(jnp.abs(g), axis=1)  # [k/gs, n]
        scale = (absmax / (7.0 if algo == "weight_only_int4" else 127.0)
                 ).astype(jnp.float32)
        scaled = (g / jnp.maximum(scale[:, None], 1e-8)).reshape(k, n)
    q = jnp.clip(jnp.round(scaled), -127, 127).astype(jnp.int8)
    if algo == "weight_only_int4":
        q = jnp.clip(q, -7, 7)
        # pack two int4 per byte along the input dim
        lo = q[0::2]
        hi = q[1::2]
        q = ((hi.astype(jnp.int32) << 4) |
             (lo.astype(jnp.int32) & 0xF)).astype(jnp.int8)
    return q, scale


def _dequant_arrays(q, scale, algo, group_size, dtype):
    if algo == "weight_only_int4":
        lo = ((q.astype(jnp.int32) & 0xF) << 28 >> 28).astype(jnp.int8)
        hi = (q.astype(jnp.int32) >> 4).astype(jnp.int8)
        full = jnp.stack([lo, hi], axis=1).reshape(-1, q.shape[-1])
    else:
        full = q
    if group_size == -1:
        return (full.astype(jnp.float32) * scale).astype(dtype)
    k, n = full.shape
    g = full.reshape(k // group_size, group_size, n).astype(jnp.float32)
    return (g * scale[:, None]).reshape(k, n).astype(dtype)


def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1):
    """Quantize a [in, out] weight; returns (int8 tensor, f32 scales).
    Reference quantized_linear.py:56 (arch is CUDA-specific: ignored)."""
    if algo not in ("weight_only_int8", "weight_only_int4", "llm.int8"):
        raise ValueError(f"unsupported algo {algo!r}")
    w = unwrap(x)
    q, scale = _quant_arrays(w.astype(jnp.float32),
                             algo, group_size)
    return Tensor(q), Tensor(scale)


def weight_dequantize(x, scale, algo="weight_only_int8", out_dtype=None,
                      group_size=-1):
    dt = jnp.bfloat16 if out_dtype is None else out_dtype
    return Tensor(_dequant_arrays(unwrap(x), unwrap(scale), algo,
                                  group_size, dt))


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """y = x @ dequant(weight) + bias (reference quantized_linear.py:183).
    The dequant feeds straight into the matmul so XLA keeps weights int8
    in HBM and upconverts in the operand pipeline."""
    algo = "weight_only_int4" if str(weight_dtype) == "int4" \
        else "weight_only_int8"

    def fn(a, q, scale, *maybe_bias):
        w = _dequant_arrays(q, scale, algo, group_size, a.dtype)
        out = a @ w
        if maybe_bias:
            out = out + maybe_bias[0]
        return out
    args = [x, weight, weight_scale] + ([bias] if bias is not None else [])
    return apply(fn, *args, name="weight_only_linear")


class WeightOnlyLinear:
    """Inference-only Linear over quantized storage; drop-in replacement
    installed by quantize_for_inference."""

    def __init__(self, linear, algo="weight_only_int8", group_size=-1):
        self.algo = algo
        self.group_size = group_size
        self.qweight, self.scale = weight_quantize(
            linear.weight, algo=algo, group_size=group_size)
        self.bias = linear.bias

    def __call__(self, x):
        return weight_only_linear(
            x, self.qweight, self.bias, self.scale,
            weight_dtype="int4" if self.algo == "weight_only_int4"
            else "int8", group_size=self.group_size)


def quantize_for_inference(model, algo="weight_only_int8", group_size=-1,
                           skip=("lm_head",)):
    """Replace every nn.Linear's forward with a weight-only-quantized
    version (decode-serving memory/bandwidth cut; the reference applies
    the same transform inside its fused-LLM weight-only path). Returns
    the number of layers quantized."""
    from .layer.common import Linear

    count = 0
    for name, layer in model.named_sublayers():
        if isinstance(layer, Linear) and \
                not any(s in name for s in skip):
            qlin = WeightOnlyLinear(layer, algo, group_size)
            layer.forward = qlin.__call__
            layer._weight_only = qlin
            count += 1
    return count
