"""`paddle.hub` (reference python/paddle/hub.py re-exports the hapi
hub entrypoint loaders)."""

from .hapi.hub import help, list, load  # noqa: F401,A004

__all__ = ["list", "help", "load"]
