"""MMapTokenDataset: native LLM-pretraining data pipeline.

Binding over csrc/token_dataset.cc (reference analogue: the C++ DataFeed/
Dataset path, paddle/fluid/framework/data_feed.cc). Yields [batch,
seq_len+1] int32 batches; the producer thread prefetches off-GIL so the
host pipeline overlaps device compute.
"""

from __future__ import annotations

import ctypes

import numpy as np

from ..csrc.build import load_library
from ..core.tensor import Tensor


def _lib():
    lib = load_library("pt_data")
    lib.pt_dataset_open.restype = ctypes.c_void_p
    lib.pt_dataset_open.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                    ctypes.c_int64, ctypes.c_int64,
                                    ctypes.c_uint64, ctypes.c_int]
    lib.pt_dataset_num_batches.restype = ctypes.c_int64
    lib.pt_dataset_num_batches.argtypes = [ctypes.c_void_p]
    lib.pt_dataset_num_tokens.restype = ctypes.c_int64
    lib.pt_dataset_num_tokens.argtypes = [ctypes.c_void_p]
    lib.pt_dataset_start_epoch.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.pt_dataset_next.restype = ctypes.c_int
    lib.pt_dataset_next.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_int32)]
    lib.pt_dataset_close.argtypes = [ctypes.c_void_p]
    return lib


class MMapTokenDataset:
    """Iterate [batch, seq_len+1] windows from a flat token .bin file.

    dtype: 'uint16' (GPT-2 BPE ids) | 'int32' | 'uint8'.
    """

    _DTYPE_BYTES = {"uint8": 1, "uint16": 2, "int32": 4}

    def __init__(self, path, batch_size, seq_len, dtype="uint16", seed=0,
                 prefetch=4, return_tensor=True):
        self._lib = _lib()
        self._handle = self._lib.pt_dataset_open(
            str(path).encode(), self._DTYPE_BYTES[dtype], batch_size,
            seq_len, seed, prefetch)
        if not self._handle:
            raise ValueError(f"cannot open token dataset {path!r} "
                             f"(too small for batch x seq?)")
        self.batch_size = batch_size
        self.seq_len = seq_len
        self._return_tensor = return_tensor
        self._epoch = 0

    @property
    def num_batches(self):
        return int(self._lib.pt_dataset_num_batches(self._handle))

    @property
    def num_tokens(self):
        return int(self._lib.pt_dataset_num_tokens(self._handle))

    def set_epoch(self, epoch):
        self._epoch = int(epoch)

    def __len__(self):
        return self.num_batches

    def __iter__(self):
        self._lib.pt_dataset_start_epoch(self._handle, self._epoch)
        out = np.empty((self.batch_size, self.seq_len + 1), np.int32)
        ptr = out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        while True:
            if self._lib.pt_dataset_next(self._handle, ptr) != 0:
                break
            batch = out.copy()
            if self._return_tensor:
                yield Tensor(batch[:, :-1].astype(np.int64)), \
                    Tensor(batch[:, 1:].astype(np.int64))
            else:
                yield batch
        self._epoch += 1

    def close(self):
        if getattr(self, "_handle", None):
            self._lib.pt_dataset_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
