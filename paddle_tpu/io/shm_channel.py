"""Shared-memory channel over the native ring (csrc/shm_ring.cc).

The reference dataloader's `use_shared_memory` path
(dataloader_iter.py + mmap_allocator.cc): worker batches travel through
one shm segment instead of a pickle pipe. Records are a pickled tree
with ndarray leaves replaced by placeholders + the raw buffers
concatenated after it — arrays are never pickled.
"""

from __future__ import annotations

import ctypes
import os
import pickle
import struct

import numpy as np

from ..csrc.build import load_library

__all__ = ["ShmChannel", "available"]


def _lib():
    lib = load_library("pt_shm")
    lib.shm_ring_create.restype = ctypes.c_void_p
    lib.shm_ring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.shm_ring_open.restype = ctypes.c_void_p
    lib.shm_ring_open.argtypes = [ctypes.c_char_p]
    lib.shm_ring_write.restype = ctypes.c_int
    lib.shm_ring_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_uint64, ctypes.c_long]
    lib.shm_ring_read_len.restype = ctypes.c_longlong
    lib.shm_ring_read_len.argtypes = [ctypes.c_void_p, ctypes.c_long]
    lib.shm_ring_read.restype = ctypes.c_longlong
    lib.shm_ring_read.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_uint64]
    lib.shm_ring_close.argtypes = [ctypes.c_void_p]
    lib.shm_ring_unlink.argtypes = [ctypes.c_char_p]
    return lib


def available():
    try:
        _lib()
        return True
    except Exception:
        return False


_ARRAY = "__pt_shm_ndarray__"


def _encode(obj):
    """(pickled-tree bytes, [raw buffers]) with arrays hoisted out."""
    buffers = []

    def strip(o):
        if isinstance(o, np.ndarray):
            a = np.ascontiguousarray(o)
            buffers.append(a)
            return (_ARRAY, len(buffers) - 1, a.dtype.str, a.shape)
        if isinstance(o, tuple):
            return tuple(strip(x) for x in o)
        if isinstance(o, list):
            return [strip(x) for x in o]
        if isinstance(o, dict):
            return {k: strip(v) for k, v in o.items()}
        return o

    tree = pickle.dumps(strip(obj))
    parts = [struct.pack("<II", len(tree), len(buffers)), tree]
    for a in buffers:
        raw = a.tobytes()
        parts.append(struct.pack("<Q", len(raw)))
        parts.append(raw)
    return b"".join(parts)


def _decode(payload):
    tlen, nbuf = struct.unpack_from("<II", payload, 0)
    off = 8
    tree = pickle.loads(payload[off:off + tlen])
    off += tlen
    buffers = []
    for _ in range(nbuf):
        (blen,) = struct.unpack_from("<Q", payload, off)
        off += 8
        buffers.append(payload[off:off + blen])
        off += blen

    def rebuild(o):
        if isinstance(o, tuple) and len(o) == 4 and o[0] == _ARRAY:
            _, i, dtype, shape = o
            return np.frombuffer(buffers[i], dtype=dtype).reshape(shape)
        if isinstance(o, tuple):
            return tuple(rebuild(x) for x in o)
        if isinstance(o, list):
            return [rebuild(x) for x in o]
        if isinstance(o, dict):
            return {k: rebuild(v) for k, v in o.items()}
        return o

    return rebuild(tree)


class ShmChannel:
    """MPSC channel: many writer processes, one reader (the parent)."""

    def __init__(self, capacity=64 << 20, name=None, create=True):
        self.name = name or f"/pt_shm_{os.getpid()}_{id(self)}"
        self._lib = _lib()
        if create:
            self._h = self._lib.shm_ring_create(self.name.encode(),
                                                capacity)
        else:
            self._h = self._lib.shm_ring_open(self.name.encode())
        if not self._h:
            raise OSError(f"shm ring {'create' if create else 'open'} "
                          f"failed for {self.name}")
        self._owner = create

    def attach(self):
        """Re-open in a child process (fork inherits the handle safely,
        but an explicit open keeps lifetimes independent)."""
        return ShmChannel(name=self.name, create=False)

    def put(self, obj, timeout_ms=60_000):
        payload = _encode(obj)
        rc = self._lib.shm_ring_write(self._h, payload, len(payload),
                                      timeout_ms)
        if rc == -1:
            raise TimeoutError("shm ring full")
        if rc != 0:
            raise OSError(f"shm ring write failed (record "
                          f"{len(payload)} bytes)")

    def get(self, timeout_ms=60_000):
        n = self._lib.shm_ring_read_len(self._h, timeout_ms)
        if n == -1:
            raise TimeoutError("shm ring empty")
        if n < 0:
            raise OSError("shm ring read_len failed")
        buf = ctypes.create_string_buffer(int(n))
        got = self._lib.shm_ring_read(self._h, buf, int(n))
        if got < 0:
            raise OSError("shm ring read failed")
        return _decode(buf.raw[:got])

    def close(self):
        if self._h:
            self._lib.shm_ring_close(self._h)
            self._h = None
        if self._owner:
            self._lib.shm_ring_unlink(self.name.encode())
            self._owner = False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
