"""`paddle.io`: datasets and DataLoader.

Parity: reference python/paddle/io/ — Dataset/IterableDataset,
`DataLoader` (reader.py:266) with worker PROCESSES
(dataloader/dataloader_iter.py: _DataLoaderIterMultiProcess), BatchSampler,
DistributedBatchSampler, pin-memory. TPU-first: num_workers>0 forks OS
worker processes so heavy Python transforms run off the GIL; workers ship
numpy over the result queue (they never touch jax — forking a process
with a live TPU backend deadlocks) and the parent converts to Tensors, so
the host→HBM transfer overlaps the previous step exactly like the
reference's pinned-memory + CUDA-stream pipeline.
"""

from __future__ import annotations

import itertools
import multiprocessing as mp
import os
import queue as queue_mod
import threading
import traceback

import numpy as np

from ..core.tensor import Tensor

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "Subset", "ConcatDataset", "random_split", "Sampler",
    "SequenceSampler", "RandomSampler", "WeightedRandomSampler",
    "BatchSampler", "DistributedBatchSampler", "DataLoader",
    "SubsetRandomSampler",
    "get_worker_info", "default_collate_fn",
]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset is not subscriptable")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        lengths = {len(t) for t in tensors}
        assert len(lengths) == 1, "tensors must share dim 0"
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        lengths = {len(d) for d in self.datasets}
        assert len(lengths) == 1

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)

    def __len__(self):
        return len(self.datasets[0])


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        return itertools.chain(*self.datasets)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self._offsets = np.cumsum([0] + [len(d) for d in self.datasets])

    def __len__(self):
        return int(self._offsets[-1])

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds = int(np.searchsorted(self._offsets, idx, side="right") - 1)
        return self.datasets[ds][idx - int(self._offsets[ds])]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    lengths = list(lengths)
    if all(isinstance(l, float) for l in lengths):
        counts = [int(np.floor(total * f)) for f in lengths]
        for i in range(total - sum(counts)):
            counts[i % len(counts)] += 1
        lengths = counts
    assert sum(lengths) == total
    perm = np.random.permutation(total)
    out, start = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[start:start + n].tolist()))
        start += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    """Random order over a fixed index subset (reference
    io/SubsetRandomSampler)."""

    def __init__(self, indices, generator=None):
        self.indices = list(indices)

    def __iter__(self):
        return iter(np.random.permutation(
            np.asarray(self.indices)).tolist())

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """reference python/paddle/io/dataloader/batch_sampler.py:
    DistributedBatchSampler — per-rank strided subset. On the single-
    controller runtime the global batch is mesh-sharded instead, so
    num_replicas defaults to 1; kept for API/launch parity."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import get_rank, get_world_size
        self.dataset = dataset
        self.nranks = num_replicas if num_replicas is not None else \
            get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.batch_size = batch_size
        self.epoch = 0

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices = indices[self.local_rank::self.nranks].tolist()
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __len__(self):
        per = (len(self.dataset) + self.nranks - 1) // self.nranks
        if self.drop_last:
            return per // self.batch_size
        return (per + self.batch_size - 1) // self.batch_size


class _WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = threading.local()
_mp_worker_info = [None]  # set in forked worker processes


def get_worker_info():
    if _mp_worker_info[0] is not None:
        return _mp_worker_info[0]
    return getattr(_worker_info, "info", None)


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, Tensor):
        import jax.numpy as jnp
        return Tensor(jnp.stack([b._data for b in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, np.float32))
    if isinstance(sample, (tuple, list)):
        return [default_collate_fn([b[i] for b in batch])
                for i in range(len(sample))]
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch])
                for k in sample}
    return batch


class DataLoader:
    """reference python/paddle/io/reader.py:266. Threaded prefetch
    pipeline (num_workers threads + bounded queue)."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, use_process_workers=True):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        self.use_shared_memory = use_shared_memory
        # process workers (reference behavior) by default; threads remain
        # as an explicit opt-out for un-forkable setups
        self.use_process_workers = use_process_workers
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_size = batch_size
            self.drop_last = drop_last
            self.batch_sampler = None
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    def _iter_iterable(self):
        batch = []
        for item in self.dataset:
            batch.append(item)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)

    def _iter_map(self):
        for indices in self.batch_sampler:
            yield self.collate_fn([self.dataset[i] for i in indices])

    def _iter_threaded(self):
        q = queue_mod.Queue(maxsize=self.prefetch_factor * self.num_workers)
        sentinel = object()
        batches = enumerate(iter(self.batch_sampler))
        lock = threading.Lock()

        def worker(wid):
            _worker_info.info = _WorkerInfo(wid, self.num_workers,
                                            self.dataset)
            if self.worker_init_fn is not None:
                self.worker_init_fn(wid)
            while True:
                with lock:
                    try:
                        seq, indices = next(batches)
                    except StopIteration:
                        break
                q.put((seq, self.collate_fn(
                    [self.dataset[i] for i in indices])))
            q.put(sentinel)

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(self.num_workers)]
        for t in threads:
            t.start()
        # deliver in sampler order (paddle preserves batch order even with
        # out-of-order workers)
        done, next_seq, hold = 0, 0, {}
        while done < self.num_workers:
            item = q.get()
            if item is sentinel:
                done += 1
                continue
            seq, data = item
            hold[seq] = data
            while next_seq in hold:
                yield hold.pop(next_seq)
                next_seq += 1
        while next_seq in hold:
            yield hold.pop(next_seq)
            next_seq += 1

    # -- multiprocess workers (reference dataloader_iter.py) ---------------

    @staticmethod
    def _np_leaves(obj):
        """Tensor leaves -> numpy for the cross-process pipe."""
        if isinstance(obj, Tensor):
            return np.asarray(obj.numpy())
        if isinstance(obj, (tuple, list)):
            return [DataLoader._np_leaves(o) for o in obj]
        if isinstance(obj, dict):
            return {k: DataLoader._np_leaves(v) for k, v in obj.items()}
        return obj

    @staticmethod
    def _tensor_leaves(obj):
        if isinstance(obj, np.ndarray):
            return Tensor(obj)
        if isinstance(obj, (tuple, list)):
            return [DataLoader._tensor_leaves(o) for o in obj]
        if isinstance(obj, dict):
            return {k: DataLoader._tensor_leaves(v) for k, v in obj.items()}
        return obj

    def _worker_loop(self, wid, index_q, result_q, shm_name):
        _mp_worker_info[0] = _WorkerInfo(wid, self.num_workers,
                                         self.dataset)
        if self.worker_init_fn is not None:
            self.worker_init_fn(wid)
        chan = None
        if shm_name is not None:
            from .shm_channel import ShmChannel
            chan = ShmChannel(name=shm_name, create=False)
        collate = self.collate_fn

        def emit(msg):
            if chan is not None:
                chan.put(msg)
            else:
                result_q.put(msg)

        while True:
            job = index_q.get()
            if job is None:
                emit(("done", wid, None))
                return
            seq, indices = job
            try:
                batch = collate([self.dataset[i] for i in indices])
                emit(("ok", seq, self._np_leaves(batch)))
            except Exception:
                emit(("error", seq, traceback.format_exc()))
                return

    def _iter_multiprocess(self):
        ctx = mp.get_context("fork")
        index_q = ctx.Queue()
        result_q = ctx.Queue()
        chan = None
        shm_name = None
        if self.use_shared_memory:
            # worker batches travel through the native shm ring
            # (csrc/shm_ring.cc) instead of the pickle pipe — the
            # reference's mmap_allocator shared-memory path
            try:
                from .shm_channel import ShmChannel
                chan = ShmChannel()
                shm_name = chan.name
            except Exception:
                chan = None
        procs = [ctx.Process(target=self._worker_loop,
                             args=(w, index_q, result_q, shm_name),
                             daemon=True)
                 for w in range(self.num_workers)]
        for p in procs:
            p.start()
        n_batches = 0
        for seq, indices in enumerate(iter(self.batch_sampler)):
            index_q.put((seq, list(indices)))
            n_batches += 1
        for _ in procs:
            index_q.put(None)
        timeout = self.timeout or None

        def fetch():
            if chan is not None:
                return chan.get(timeout_ms=int((timeout or 600) * 1000))
            return result_q.get(timeout=timeout)

        try:
            done, next_seq, hold = 0, 0, {}
            received = 0
            while received < n_batches and done < self.num_workers:
                kind, seq, payload = fetch()
                if kind == "done":
                    done += 1
                    continue
                if kind == "error":
                    raise RuntimeError(
                        f"DataLoader worker failed:\n{payload}")
                received += 1
                hold[seq] = payload
                while next_seq in hold:  # sampler-order delivery
                    yield self._tensor_leaves(hold.pop(next_seq))
                    next_seq += 1
            while next_seq in hold:
                yield self._tensor_leaves(hold.pop(next_seq))
                next_seq += 1
        finally:
            for p in procs:
                if p.is_alive():
                    p.terminate()
            for p in procs:
                p.join(timeout=5)
            if chan is not None:
                chan.close()

    def __iter__(self):
        if self._iterable:
            return self._iter_iterable()
        if self.num_workers and self.num_workers > 0:
            if self.use_process_workers and hasattr(os, "fork"):
                return self._iter_multiprocess()
            return self._iter_threaded()
        return self._iter_map()
