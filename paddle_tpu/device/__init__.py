"""`paddle.device`: device control.

Parity: reference python/paddle/device/ (set_device :277, Stream :633,
Event :457, synchronize, cuda memory stats). TPU-first: XLA owns stream
scheduling, so Stream/Event are ordering no-ops that preserve the API;
memory stats come from the PJRT device (`jax.local_devices()[0]
.memory_stats()` — the reference's phi/core/memory/stats.h equivalent).
"""

from __future__ import annotations

import jax

from ..core import place as place_mod

__all__ = ["set_device", "get_device", "get_all_custom_device_type",
           "is_compiled_with_cuda", "is_compiled_with_xpu",
           "is_compiled_with_ipu", "is_compiled_with_custom_device",
           "device_count", "synchronize",
           "Stream", "Event", "current_stream", "stream_guard", "cuda",
           "max_memory_allocated", "max_memory_reserved",
           "memory_allocated", "memory_reserved", "empty_cache",
           "XPUPlace", "IPUPlace", "get_available_device",
           "get_available_custom_device", "get_cudnn_version",
           "set_stream"]


def get_available_device():
    """List every device string usable with set_device (reference
    device/__init__.py get_available_device)."""
    out = ["cpu"]
    for i, d in enumerate(jax.devices()):
        if d.platform != "cpu":
            out.append(f"{d.platform}:{i}")
    return out


def get_available_custom_device():
    """Custom (plugin) devices; PJRT plugins register as first-class jax
    platforms here, so this mirrors get_available_device sans cpu."""
    return [d for d in get_available_device() if not d.startswith("cpu")]


def get_cudnn_version():
    """No cuDNN in a TPU stack (reference returns the dynloaded cuDNN
    version)."""
    return None


def is_compiled_with_ipu():
    return False


def XPUPlace(device_id=0):
    from ..core.place import Place
    return Place("tpu", device_id)


def IPUPlace():
    raise RuntimeError("IPU is not a supported backend in paddle_tpu")


def set_stream(stream=None):
    """Bind the 'current stream' (reference device.set_stream). XLA owns
    scheduling; the Stream object is bookkeeping for API parity."""
    global _current
    prev = current_stream()
    if stream is not None:
        _current = stream
    return prev


def set_device(device):
    return place_mod.set_device(device)


def get_device():
    return place_mod.get_device()


def device_count():
    return jax.device_count()


def synchronize(device=None):
    place_mod.synchronize()


def is_compiled_with_cuda():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_custom_device(device_type=None):
    return device_type in (None, "tpu")


def get_all_custom_device_type():
    return ["tpu"]


def get_all_device_type():
    return ["cpu", "tpu"]


class Stream:
    """API-parity stream: XLA schedules async execution itself."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()

    def query(self):
        return True


class Event:
    def __init__(self, device=None, enable_timing=False, blocking=False,
                 interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        synchronize()


_current = Stream()


def current_stream(device=None):
    return _current


class stream_guard:
    def __init__(self, stream):
        self.stream = stream

    def __enter__(self):
        return self.stream

    def __exit__(self, *exc):
        return False


def _mem_stats():
    dev = jax.local_devices()[0]
    try:
        return dev.memory_stats() or {}
    except Exception:
        return {}


def memory_allocated(device=None):
    return int(_mem_stats().get("bytes_in_use", 0))


def max_memory_allocated(device=None):
    return int(_mem_stats().get("peak_bytes_in_use", 0))


def memory_reserved(device=None):
    s = _mem_stats()
    return int(s.get("bytes_reserved", s.get("bytes_in_use", 0)))


def max_memory_reserved(device=None):
    return max_memory_allocated(device)


def empty_cache():
    pass


class _CudaShim:
    """`paddle.device.cuda` names mapped onto the accelerator."""

    Stream = Stream
    Event = Event

    @staticmethod
    def device_count():
        return device_count()

    @staticmethod
    def synchronize(device=None):
        synchronize()

    @staticmethod
    def max_memory_allocated(device=None):
        return max_memory_allocated()

    @staticmethod
    def max_memory_reserved(device=None):
        return max_memory_reserved()

    @staticmethod
    def memory_allocated(device=None):
        return memory_allocated()

    @staticmethod
    def memory_reserved(device=None):
        return memory_reserved()

    @staticmethod
    def empty_cache():
        pass


cuda = _CudaShim()


def is_compiled_with_cinn():
    """XLA fills the CINN role in this build (SURVEY §7)."""
    return False


def is_compiled_with_distribute():
    return True


class _XpuNamespace:
    """paddle.device.xpu surface (no XPU in a TPU build)."""

    @staticmethod
    def synchronize(device=None):
        return None

    @staticmethod
    def device_count():
        return 0


xpu = _XpuNamespace()
