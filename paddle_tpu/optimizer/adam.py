"""Adam-family optimizers.

Parity: reference `python/paddle/optimizer/{adam,adamw,adamax,lamb,nadam,
radam}.py` and the fused GPU kernels (`paddle/phi/kernels/gpu/
fused_adam_kernel.cu`, `adamw_kernel.cu`). On TPU the whole update is one
XLA fusion per parameter (and one program total under the compiled step),
so there is no separate "fused" variant to maintain. All update rules are
trace-safe: the step count `self._t` may be a jnp scalar, so bias
corrections use `jnp.power` and branching uses `jnp.where`.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from .optimizer import Optimizer


def _pow(base, t):
    return jnp.power(jnp.float32(base), t)


class Adam(Optimizer):
    _slot_names = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=True,
                 use_multi_tensor=False, amsgrad=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._beta1 = self._scalar(beta1)
        self._beta2 = self._scalar(beta2)
        self._epsilon = epsilon
        self._amsgrad = amsgrad
        if amsgrad:
            self._slot_names = self._slot_names + ("moment2_max",)

    @staticmethod
    def _scalar(v):
        return float(v._data) if isinstance(v, Tensor) else float(v)

    def _update(self, p, g, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        t = self._t
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * g * g
        state["moment1"] = m
        state["moment2"] = v
        m_hat = m / (1 - _pow(b1, t))
        if self._amsgrad:
            vmax = jnp.maximum(state["moment2_max"], v)
            state["moment2_max"] = vmax
            v_hat = vmax / (1 - _pow(b2, t))
        else:
            v_hat = v / (1 - _pow(b2, t))
        return p - lr * m_hat / (jnp.sqrt(v_hat) + eps), state


class AdamW(Adam):
    """Decoupled weight decay (reference python/paddle/optimizer/adamw.py).
    ``weight_decay`` defaults to 0.01; `apply_decay_param_fun` filters which
    params decay (paddle semantics)."""

    _decoupled = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=True, amsgrad=False,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         amsgrad=amsgrad)
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _wants_decay(self, param):
        if self._apply_decay_param_fun is None or param is None:
            return True
        return bool(self._apply_decay_param_fun(param.name or ""))


class Adamax(Optimizer):
    _slot_names = ("moment", "inf_norm")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._beta1 = float(beta1)
        self._beta2 = float(beta2)
        self._epsilon = epsilon

    def _update(self, p, g, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m = b1 * state["moment"] + (1 - b1) * g
        u = jnp.maximum(b2 * state["inf_norm"], jnp.abs(g))
        state["moment"] = m
        state["inf_norm"] = u
        return p - (lr / (1 - _pow(b1, self._t))) * m / (u + eps), state


class NAdam(Optimizer):
    _slot_names = ("moment1", "moment2", "mu_product")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=True,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._beta1 = float(beta1)
        self._beta2 = float(beta2)
        self._epsilon = epsilon
        self._psi = momentum_decay

    def _init_slot(self, name, pdata):
        if name == "mu_product":
            return jnp.ones([], jnp.float32)
        return jnp.zeros(pdata.shape, jnp.float32)

    def _update(self, p, g, state, lr):
        b1, b2, eps, psi = self._beta1, self._beta2, self._epsilon, self._psi
        t = jnp.asarray(self._t, jnp.float32)
        mu_t = b1 * (1 - 0.5 * _pow(0.96, t * psi))
        mu_t1 = b1 * (1 - 0.5 * _pow(0.96, (t + 1) * psi))
        mu_product = state["mu_product"] * mu_t
        state["mu_product"] = mu_product
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * g * g
        state["moment1"] = m
        state["moment2"] = v
        m_hat = (mu_t1 * m / (1 - mu_product * mu_t1) +
                 (1 - mu_t) * g / (1 - mu_product))
        v_hat = v / (1 - _pow(b2, t))
        return p - lr * m_hat / (jnp.sqrt(v_hat) + eps), state


class RAdam(Optimizer):
    _slot_names = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._beta1 = float(beta1)
        self._beta2 = float(beta2)
        self._epsilon = epsilon

    def _update(self, p, g, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        t = jnp.asarray(self._t, jnp.float32)
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * g * g
        state["moment1"] = m
        state["moment2"] = v
        b1t, b2t = _pow(b1, t), _pow(b2, t)
        m_hat = m / (1 - b1t)
        rho_inf = 2.0 / (1 - b2) - 1
        rho_t = rho_inf - 2 * t * b2t / (1 - b2t)
        rect = jnp.sqrt(jnp.maximum(
            (rho_t - 4) * (rho_t - 2) * rho_inf /
            jnp.maximum((rho_inf - 4) * (rho_inf - 2) * rho_t, 1e-12), 0.0))
        var = jnp.sqrt(jnp.maximum(v / (1 - b2t), 0.0)) + eps
        step_rect = lr * rect * m_hat / var
        step_plain = lr * m_hat
        return p - jnp.where(rho_t > 5.0, step_rect, step_plain), state


class Lamb(Optimizer):
    """reference python/paddle/optimizer/lamb.py (layer-adaptive Adam for
    large-batch; the reference also ships distributed_fused_lamb —
    under GSPMD sharding the same math is automatically distributed)."""

    _slot_names = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision)
        self._wd = lamb_weight_decay
        self._beta1 = float(beta1)
        self._beta2 = float(beta2)
        self._epsilon = epsilon
        self._exclude_fn = exclude_from_weight_decay_fn
        self._cur_param = None

    def _apply_param(self, p32, g, st, lr_p, group, param=None):
        self._cur_param = param
        return super()._apply_param(p32, g, st, lr_p, group, param)

    def _update(self, p, g, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        t = self._t
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * g * g
        state["moment1"] = m
        state["moment2"] = v
        m_hat = m / (1 - _pow(b1, t))
        v_hat = v / (1 - _pow(b2, t))
        r = m_hat / (jnp.sqrt(v_hat) + eps)
        wd = self._wd
        if self._exclude_fn is not None and self._cur_param is not None \
                and self._exclude_fn(self._cur_param):
            wd = 0.0
        upd = r + wd * p
        w_norm = jnp.linalg.norm(p)
        u_norm = jnp.linalg.norm(upd)
        trust = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
        return p - lr * trust * upd, state
