"""Optimizer base + classic optimizers.

Parity: reference `python/paddle/optimizer/optimizer.py` (base class,
accumulator management, `_create_optimization_pass`) and the per-optimizer
files (sgd.py, momentum.py, adagrad.py, ...). TPU-first: every update rule
is a pure function ``_update(p, g, state, lr) -> (new_p, new_state)`` over
jax arrays, so the compiled train step (`paddle_tpu.jit`) can trace the
exact same math into one fused XLA program (the analogue of the reference's
fused_adam/multi_tensor kernels — XLA does the fusion).
"""

from __future__ import annotations

from collections import OrderedDict

import jax
import jax.numpy as jnp

from ..core import dtype as dtype_mod
from ..core.tensor import Tensor
from .lr import LRScheduler


def _as_float_or_none(wd):
    """Normalize weight_decay (float | L1Decay | L2Decay | None)."""
    if wd is None:
        return None, None
    from ..regularizer import L1Decay, L2Decay
    if isinstance(wd, L1Decay):
        return "l1", float(wd.coeff)
    if isinstance(wd, L2Decay):
        return "l2", float(wd.coeff)
    return "l2", float(wd)


def _lr_mult(p):
    """Per-parameter LR multiplier; plain Tensors (the reference accepts
    them in parameter lists) have no optimize_attr and default to 1."""
    return getattr(p, "optimize_attr", {}).get("learning_rate", 1.0)


class Optimizer:
    """Base optimizer.

    ``parameters`` may be a list of Parameters or a list of dicts
    (param groups, paddle semantics: each dict has 'params' plus overrides
    like 'learning_rate' multiplier or 'weight_decay').
    """

    # names of per-param slot arrays, e.g. ("moment1", "moment2")
    _slot_names: tuple = ()

    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=True,
                 name=None):
        if parameters is None:
            raise ValueError(
                "parameters is required (eager mode, reference "
                "python/paddle/optimizer/optimizer.py:262 semantics)")
        self._lr = learning_rate
        if isinstance(learning_rate, LRScheduler):
            self._lr_scheduler = learning_rate
        else:
            self._lr_scheduler = None
        self._groups = self._build_groups(parameters, weight_decay)
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        # id(param) -> dict(slot name -> jnp array); master weights too
        self._state: dict[int, dict] = {}
        self._global_step = 0
        # step counter visible to _update: python int eagerly, traced
        # jnp scalar inside the compiled train step
        self._t = 0

    def _build_groups(self, parameters, weight_decay):
        wd_mode, wd = _as_float_or_none(weight_decay)
        params = list(parameters)
        if params and isinstance(params[0], dict):
            groups = []
            for g in params:
                gm, gw = _as_float_or_none(g.get("weight_decay"))
                groups.append({
                    "params": list(g["params"]),
                    "lr_mult": float(g.get("learning_rate", 1.0)),
                    "wd_mode": gm if g.get("weight_decay") is not None
                    else wd_mode,
                    "weight_decay": gw if g.get("weight_decay") is not None
                    else wd,
                })
            return groups
        return [{"params": params, "lr_mult": 1.0, "wd_mode": wd_mode,
                 "weight_decay": wd}]

    # -- lr ----------------------------------------------------------------
    def get_lr(self):
        if self._lr_scheduler is not None:
            return float(self._lr_scheduler.get_lr())
        return float(self._lr)

    def set_lr(self, value):
        if self._lr_scheduler is not None:
            raise RuntimeError(
                "cannot set_lr when the learning rate is an LRScheduler")
        self._lr = float(value)

    def set_lr_scheduler(self, scheduler):
        self._lr_scheduler = scheduler

    # -- state -------------------------------------------------------------
    def _slots_for(self, p):
        key = id(p)
        if key not in self._state:
            st = {}
            pdata = p._data
            needs_master = (self._multi_precision and
                            pdata.dtype in (jnp.bfloat16, jnp.float16))
            master = pdata.astype(jnp.float32) if needs_master else None
            st["master"] = master
            for nm in self._slot_names:
                st[nm] = self._init_slot(nm, pdata)
            self._state[key] = st
        return self._state[key]

    def _init_slot(self, name, pdata):
        return jnp.zeros(pdata.shape, jnp.float32)

    # -- the update rule (override) ---------------------------------------
    def _update(self, p, g, state, lr):
        """Pure update: (fp32 param, fp32 grad, slot dict, lr) ->
        (new fp32 param, new slot dict)."""
        raise NotImplementedError

    def _decay_grad(self, p, g, group):
        """Apply regularization-style decay into the gradient (L1/L2 coupled
        decay, paddle regularizer semantics). Decoupled decay (AdamW)
        overrides _decoupled_decay instead."""
        if group["wd_mode"] == "l2" and not self._decoupled:
            return g + group["weight_decay"] * p
        if group["wd_mode"] == "l1" and not self._decoupled:
            return g + group["weight_decay"] * jnp.sign(p)
        return g

    _decoupled = False

    def _apply_param(self, p32, g, st, lr_p, group, param=None):
        """Pure single-param update (shared by eager step() and the traced
        compiled step — `self._t` is a python int eagerly, a traced scalar
        under jit)."""
        if group["weight_decay"]:
            g = self._decay_grad(p32, g, group)
        new_st = dict(st)
        new_p, new_st = self._update(p32, g, new_st, lr_p)
        if group["weight_decay"] and self._decoupled and \
                self._wants_decay(param):
            new_p = new_p - lr_p * group["weight_decay"] * p32
        return new_p, new_st

    def _wants_decay(self, param):
        return True

    # -- driver ------------------------------------------------------------
    @property
    def _parameter_list(self):
        return [p for g in self._groups for p in g["params"]]

    def _param_groups_flat(self):
        """[(param, group)] in stable order."""
        return [(p, g) for g in self._groups for p in g["params"]]

    def step(self):
        self._global_step += 1
        self._t = self._global_step
        if self._fused_eager_step():
            return
        if self._grad_clip is not None:
            self._grad_clip._apply(self._parameter_list)
        for group in self._groups:
            lr = self.get_lr() * group["lr_mult"]
            for p in group["params"]:
                if p.grad is None or p.stop_gradient:
                    continue
                lr_p = lr * _lr_mult(p)
                st = self._slots_for(p)
                p32 = st["master"] if st["master"] is not None \
                    else p._data.astype(jnp.float32)
                g = p.grad._data.astype(jnp.float32)
                new_p, new_st = self._apply_param(p32, g, st, lr_p, group,
                                                  param=p)
                if st["master"] is not None:
                    new_st["master"] = new_p
                p._rebind(new_p.astype(p._data.dtype))
                self._state[id(p)] = new_st

    def _fused_eager_step(self):
        """Multi-tensor fused update for the eager loop: ALL param
        updates (plus global-norm clip) trace into ONE donated jitted
        call — the TPU answer to the reference's fused_adam multi-tensor
        kernel (`paddle/phi/kernels/gpu/fused_adam_kernel.cu`). Returns
        False (caller runs the per-param python loop) for
        param/grad-set shapes the fused path doesn't cover; any build
        failure also falls back before any state is touched."""
        items = []
        for group in self._groups:
            for p in group["params"]:
                if p.grad is None or p.stop_gradient:
                    continue
                if p.grad._data.shape != p._data.shape:
                    return False
                items.append((p, group))
        if not items:
            return False
        sig = tuple(
            (id(g), g["lr_mult"], g["weight_decay"], g["wd_mode"],
             _lr_mult(p), getattr(p, "need_clip", True),
             self._wants_decay(p), str(p._data.dtype))
            for p, g in items) + (id(self._grad_clip),)
        cached = getattr(self, "_fused_cache", None)
        if cached is not None and cached[0] == sig:
            fused = cached[1]
        else:
            groups_s = [g for _, g in items]
            params_s = [p for p, _ in items]
            lr_mults = [g["lr_mult"] *
                        _lr_mult(p)
                        for p, g in items]
            need_clip = [getattr(p, "need_clip", True)
                         for p, _ in items]
            dtypes = [p._data.dtype for p, _ in items]
            clip = self._grad_clip
            opt = self

            def fused(params, grads, slots, lr, t):
                prev_t = opt._t
                opt._t = t
                try:
                    g32 = [g.astype(jnp.float32) for g in grads]
                    if clip is not None:
                        g32 = clip._clip_arrays(g32, need_clip)
                    new_params, new_slots = [], []
                    for i, (p_arr, g, st) in enumerate(
                            zip(params, g32, slots)):
                        p32 = st["master"] if st.get("master") is not None \
                            else p_arr.astype(jnp.float32)
                        np_, nst = opt._apply_param(
                            p32, g, st, lr * lr_mults[i], groups_s[i],
                            param=params_s[i])
                        if st.get("master") is not None:
                            nst["master"] = np_
                        new_params.append(np_.astype(dtypes[i]))
                        new_slots.append(nst)
                    # clipped grads go back out so p.grad matches the
                    # python path's in-place _grad_clip._apply semantics
                    clipped = [g.astype(orig.dtype)
                               for g, orig in zip(g32, grads)] \
                        if clip is not None else None
                    return new_params, new_slots, clipped
                finally:
                    opt._t = prev_t

            try:
                # NO donation: eager code legitimately aliases p._data /
                # slot arrays (Lookahead slow weights, state_dict
                # snapshots) — donating would delete them under the
                # aliases' feet. The compiled TrainStep (which owns its
                # buffers) is the donating path.
                fused = jax.jit(fused)
            except Exception as e:  # pragma: no cover
                self._fused_err = e
                return False
            self._fused_cache = (sig, fused)

        param_arrays = [p._data for p, _ in items]
        grad_arrays = [p.grad._data for p, _ in items]
        slot_states = [self._slots_for(p) for p, _ in items]
        lr = jnp.asarray(self.get_lr(), jnp.float32)
        t = jnp.asarray(self._t, jnp.float32)
        try:
            new_params, new_slots, clipped = fused(
                param_arrays, grad_arrays, slot_states, lr, t)
        except Exception as e:  # noqa: BLE001 — trace failure: py loop
            self._fused_cache = None
            self._fused_err = e  # introspection: why the fused path bailed
            return False
        for i, ((p, _), arr, st) in enumerate(zip(items, new_params,
                                                  new_slots)):
            p._rebind(arr)
            self._state[id(p)] = st
            if clipped is not None:
                p.grad._rebind(clipped[i])
        return True

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()

    # -- serialization -----------------------------------------------------
    def state_dict(self):
        sd = OrderedDict()
        for i, p in enumerate(self._parameter_list):
            key = p.name or f"param_{i}"
            st = self._state.get(id(p))
            if st is None:
                continue
            for nm, arr in st.items():
                if arr is not None:
                    sd[f"{key}.{nm}"] = Tensor(arr)
        sd["global_step"] = self._global_step
        if self._lr_scheduler is not None:
            sd["LR_Scheduler"] = self._lr_scheduler.state_dict()
        return sd

    def set_state_dict(self, state_dict):
        self._global_step = int(state_dict.get("global_step", 0))
        if self._lr_scheduler is not None and "LR_Scheduler" in state_dict:
            self._lr_scheduler.set_state_dict(state_dict["LR_Scheduler"])
        for i, p in enumerate(self._parameter_list):
            key = p.name or f"param_{i}"
            st = self._slots_for(p)
            for nm in list(st.keys()):
                k = f"{key}.{nm}"
                if k in state_dict:
                    v = state_dict[k]
                    st[nm] = v._data if isinstance(v, Tensor) else \
                        jnp.asarray(v)


class SGD(Optimizer):
    """reference python/paddle/optimizer/sgd.py"""

    def _update(self, p, g, state, lr):
        return p - lr * g, state


class Momentum(Optimizer):
    """reference python/paddle/optimizer/momentum.py (use_nesterov opt)."""

    _slot_names = ("velocity",)

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _update(self, p, g, state, lr):
        v = self._momentum * state["velocity"] + g
        state["velocity"] = v
        if self._nesterov:
            return p - lr * (g + self._momentum * v), state
        return p - lr * v, state


class Adagrad(Optimizer):
    """reference python/paddle/optimizer/adagrad.py"""

    _slot_names = ("moment",)

    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._epsilon = epsilon
        self._init_val = initial_accumulator_value

    def _init_slot(self, name, pdata):
        return jnp.full(pdata.shape, self._init_val, jnp.float32)

    def _update(self, p, g, state, lr):
        m = state["moment"] + g * g
        state["moment"] = m
        return p - lr * g / (jnp.sqrt(m) + self._epsilon), state


class Adadelta(Optimizer):
    """reference python/paddle/optimizer/adadelta.py"""

    _slot_names = ("avg_squared_grad", "avg_squared_update")

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._epsilon = epsilon
        self._rho = rho

    def _update(self, p, g, state, lr):
        rho, eps = self._rho, self._epsilon
        sg = rho * state["avg_squared_grad"] + (1 - rho) * g * g
        upd = g * jnp.sqrt(state["avg_squared_update"] + eps) / \
            jnp.sqrt(sg + eps)
        su = rho * state["avg_squared_update"] + (1 - rho) * upd * upd
        state["avg_squared_grad"] = sg
        state["avg_squared_update"] = su
        return p - lr * upd, state


class RMSProp(Optimizer):
    """reference python/paddle/optimizer/rmsprop.py"""

    _slot_names = ("mean_square", "mean_grad", "momentum_acc")

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _update(self, p, g, state, lr):
        rho, eps = self._rho, self._epsilon
        ms = rho * state["mean_square"] + (1 - rho) * g * g
        state["mean_square"] = ms
        if self._centered:
            mg = rho * state["mean_grad"] + (1 - rho) * g
            state["mean_grad"] = mg
            denom = jnp.sqrt(ms - mg * mg + eps)
        else:
            denom = jnp.sqrt(ms + eps)
        step = lr * g / denom
        if self._momentum > 0:
            acc = self._momentum * state["momentum_acc"] + step
            state["momentum_acc"] = acc
            step = acc
        return p - step, state


class Rprop(Optimizer):
    """reference python/paddle/optimizer/rprop.py"""

    _slot_names = ("prev_grad", "lr_per_elem")

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision)
        self._lr_range = learning_rate_range
        self._etas = etas

    def _init_slot(self, name, pdata):
        if name == "lr_per_elem":
            return jnp.full(pdata.shape, float(self._lr), jnp.float32)
        return jnp.zeros(pdata.shape, jnp.float32)

    def _update(self, p, g, state, lr):
        eta_minus, eta_plus = self._etas
        lo, hi = self._lr_range
        sign = jnp.sign(g * state["prev_grad"])
        factor = jnp.where(sign > 0, eta_plus,
                           jnp.where(sign < 0, eta_minus, 1.0))
        lrs = jnp.clip(state["lr_per_elem"] * factor, lo, hi)
        g_eff = jnp.where(sign < 0, 0.0, g)
        state["lr_per_elem"] = lrs
        state["prev_grad"] = g_eff
        return p - lrs * jnp.sign(g_eff), state


class ASGD(Optimizer):
    """reference python/paddle/optimizer/asgd.py (averaged SGD)."""

    _slot_names = ("d", "ys", "avg")

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=True,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)

    def _update(self, p, g, state, lr):
        new_p = p - lr * g
        n = float(self._global_step)
        state["avg"] = state["avg"] + (new_p - state["avg"]) / n
        return new_p, state
