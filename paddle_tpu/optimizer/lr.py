"""Learning-rate schedulers.

Parity: reference `python/paddle/optimizer/lr.py` (LRScheduler base + ~15
schedules). Host-side scalar math — the scheduler value enters the compiled
train step as a scalar input, so changing LR never retriggers compilation.
"""

from __future__ import annotations

import math


class LRScheduler:
    def __init__(self, learning_rate=0.1, last_epoch=-1, verbose=False):
        self.base_lr = float(learning_rate)
        self.last_epoch = last_epoch
        self.verbose = verbose
        self.last_lr = self.base_lr
        self.step()

    def get_lr(self):
        raise NotImplementedError

    def step(self, epoch=None):
        if epoch is None:
            self.last_epoch += 1
        else:
            self.last_epoch = epoch
        self.last_lr = self.get_lr()
        if self.verbose:
            print(f"Epoch {self.last_epoch}: set learning rate to "
                  f"{self.last_lr}.")

    def state_dict(self):
        return {k: v for k, v in self.__dict__.items()
                if not k.startswith("_") and isinstance(
                    v, (int, float, bool, str, list))}

    def set_state_dict(self, state_dict):
        self.__dict__.update(state_dict)

    set_dict = set_state_dict
    state_keys = state_dict

    def __call__(self):
        return self.last_lr


class NoamDecay(LRScheduler):
    def __init__(self, d_model, warmup_steps, learning_rate=1.0,
                 last_epoch=-1, verbose=False):
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        # last_epoch counts from 0 at creation; the Noam step number is
        # 1-based (otherwise the first step() leaves the LR unchanged)
        step = self.last_epoch + 1
        return (self.base_lr * self.d_model ** -0.5 *
                min(step ** -0.5, step * self.warmup_steps ** -1.5))


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries, values, last_epoch=-1, verbose=False):
        self.boundaries = list(boundaries)
        self.values = list(values)
        super().__init__(values[0], last_epoch, verbose)

    def get_lr(self):
        for b, v in zip(self.boundaries, self.values):
            if self.last_epoch < b:
                return v
        return self.values[len(self.boundaries)]


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * math.exp(-self.gamma * self.last_epoch)


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr / (1 + self.gamma * self.last_epoch)


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0,
                 cycle=False, last_epoch=-1, verbose=False):
        self.decay_steps = decay_steps
        self.end_lr = end_lr
        self.power = power
        self.cycle = cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = self.last_epoch
        decay_steps = self.decay_steps
        if self.cycle:
            div = math.ceil(step / decay_steps) if step > 0 else 1
            decay_steps = decay_steps * div
        else:
            step = min(step, decay_steps)
        return ((self.base_lr - self.end_lr) *
                (1 - step / decay_steps) ** self.power + self.end_lr)


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr,
                 last_epoch=-1, verbose=False):
        self.lr_after = learning_rate  # float or LRScheduler
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr
        super().__init__(start_lr, last_epoch, verbose)

    def get_lr(self):
        if self.last_epoch < self.warmup_steps:
            return (self.end_lr - self.start_lr) * \
                self.last_epoch / self.warmup_steps + self.start_lr
        if isinstance(self.lr_after, LRScheduler):
            self.lr_after.step(self.last_epoch - self.warmup_steps)
            return self.lr_after.last_lr
        return float(self.lr_after)

    def state_dict(self):
        sd = super().state_dict()
        if isinstance(self.lr_after, LRScheduler):
            sd["lr_after"] = self.lr_after.state_dict()
        return sd

    def set_state_dict(self, state_dict):
        nested = state_dict.pop("lr_after", None) \
            if isinstance(state_dict.get("lr_after"), dict) else None
        super().set_state_dict(state_dict)
        if nested is not None and isinstance(self.lr_after, LRScheduler):
            self.lr_after.set_state_dict(nested)


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** self.last_epoch


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.milestones = list(milestones)
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        n = sum(1 for m in self.milestones if self.last_epoch >= m)
        return self.base_lr * self.gamma ** n


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.step_size = step_size
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** (self.last_epoch // self.step_size)


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1,
                 verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.lr_lambda(self.last_epoch)


class MultiplicativeDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1,
                 verbose=False):
        self.lr_lambda = lr_lambda
        self._cur = float(learning_rate)
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        if self.last_epoch > 0:
            self._cur = self._cur * self.lr_lambda(self.last_epoch)
        return self._cur


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0, last_epoch=-1,
                 verbose=False):
        self.T_max = T_max
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return (self.eta_min + (self.base_lr - self.eta_min) *
                (1 + math.cos(math.pi * self.last_epoch / self.T_max)) / 2)


class CosineAnnealingWarmRestarts(LRScheduler):
    def __init__(self, learning_rate, T_0, T_mult=1, eta_min=0,
                 last_epoch=-1, verbose=False):
        self.T_0 = T_0
        self.T_mult = T_mult
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        epoch = max(self.last_epoch, 0)
        t_i = self.T_0
        t_cur = epoch
        while t_cur >= t_i:
            t_cur -= t_i
            t_i *= self.T_mult
        return (self.eta_min + (self.base_lr - self.eta_min) *
                (1 + math.cos(math.pi * t_cur / t_i)) / 2)


class OneCycleLR(LRScheduler):
    def __init__(self, max_learning_rate, total_steps, divide_factor=25.0,
                 end_learning_rate=0.0001, phase_pct=0.3,
                 anneal_strategy="cos", three_phase=False, last_epoch=-1,
                 verbose=False):
        self.max_lr = max_learning_rate
        self.total_steps = total_steps
        self.initial_lr = max_learning_rate / divide_factor
        self.end_lr = end_learning_rate
        self.phase_pct = phase_pct
        self.anneal = anneal_strategy
        self.three_phase = three_phase
        super().__init__(self.initial_lr, last_epoch, verbose)

    def _interp(self, frac, start, end):
        if self.anneal == "cos":
            return end + (start - end) * (1 + math.cos(math.pi * frac)) / 2
        return start + (end - start) * frac

    def get_lr(self):
        step = min(self.last_epoch, self.total_steps)
        up = self.phase_pct * self.total_steps
        if step <= up:
            return self._interp(step / max(up, 1), self.initial_lr,
                                self.max_lr)
        frac = (step - up) / max(self.total_steps - up, 1)
        return self._interp(frac, self.max_lr, self.end_lr)


class CyclicLR(LRScheduler):
    def __init__(self, base_learning_rate, max_learning_rate,
                 step_size_up=2000, step_size_down=None, mode="triangular",
                 exp_gamma=1.0, scale_fn=None, scale_mode="cycle",
                 last_epoch=-1, verbose=False):
        self.max_lr = max_learning_rate
        self.step_up = step_size_up
        self.step_down = step_size_down or step_size_up
        self.mode = mode
        self.exp_gamma = exp_gamma
        self.scale_fn = scale_fn
        self.scale_mode = scale_mode
        super().__init__(base_learning_rate, last_epoch, verbose)

    def get_lr(self):
        total = self.step_up + self.step_down
        cycle = math.floor(1 + self.last_epoch / total)
        x = self.last_epoch - (cycle - 1) * total
        frac = x / self.step_up if x <= self.step_up else \
            1 - (x - self.step_up) / self.step_down
        ampl = (self.max_lr - self.base_lr) * frac
        if self.scale_fn is not None:
            arg = cycle if self.scale_mode == "cycle" else self.last_epoch
            ampl *= self.scale_fn(arg)
        elif self.mode == "triangular2":
            ampl *= 1 / (2 ** (cycle - 1))
        elif self.mode == "exp_range":
            ampl *= self.exp_gamma ** self.last_epoch
        return self.base_lr + ampl


class ReduceOnPlateau(LRScheduler):
    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10,
                 threshold=1e-4, threshold_mode="rel", cooldown=0, min_lr=0,
                 epsilon=1e-8, verbose=False):
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.epsilon = epsilon
        self.best = None
        self.num_bad = 0
        self.cooldown_counter = 0
        self._current = float(learning_rate)
        super().__init__(learning_rate, -1, verbose)

    def get_lr(self):
        return self._current

    def step(self, metrics=None, epoch=None):
        self.last_epoch += 1
        if metrics is None:
            self.last_lr = self._current
            return
        cur = float(metrics) if not hasattr(metrics, "item") else \
            float(metrics.item())
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.num_bad = 0
        else:
            self.num_bad += 1
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad = 0
        elif self.num_bad > self.patience:
            new_lr = max(self._current * self.factor, self.min_lr)
            if self._current - new_lr > self.epsilon:
                self._current = new_lr
            self.cooldown_counter = self.cooldown
            self.num_bad = 0
        self.last_lr = self._current

    def _better(self, a, best):
        if self.mode == "min":
            if self.threshold_mode == "rel":
                return a < best * (1 - self.threshold)
            return a < best - self.threshold
        if self.threshold_mode == "rel":
            return a > best * (1 + self.threshold)
        return a > best + self.threshold


class LinearLR(LRScheduler):
    """Linear interpolation from start_factor to end_factor over
    total_steps (reference optimizer/lr.py LinearLR)."""

    def __init__(self, learning_rate, total_steps, start_factor=1.0 / 3,
                 end_factor=1.0, last_epoch=-1, verbose=False):
        assert total_steps > 0
        self.total_steps = total_steps
        self.start_factor = start_factor
        self.end_factor = end_factor
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        t = min(max(self.last_epoch, 0), self.total_steps)
        frac = self.start_factor + (self.end_factor - self.start_factor) \
            * t / self.total_steps
        return self.base_lr * frac
