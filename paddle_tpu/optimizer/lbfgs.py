"""L-BFGS optimizer (reference: python/paddle/optimizer/lbfgs.py).

Closure-based full-batch quasi-Newton: two-loop recursion over an
(s, y) history + strong-Wolfe line search. Runs eagerly on flattened
parameter vectors — every inner evaluation re-runs the closure (forward
+ tape backward), exactly the reference's `step(closure)` contract.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from .optimizer import Optimizer

__all__ = ["LBFGS"]


class LBFGS(Optimizer):
    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 history_size=100, line_search_fn=None, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate=learning_rate,
                         parameters=parameters, weight_decay=weight_decay,
                         grad_clip=grad_clip)
        self._params = list(self._parameter_list)
        self.max_iter = max_iter
        self.max_eval = max_eval if max_eval is not None \
            else max_iter * 5 // 4
        self.tolerance_grad = tolerance_grad
        self.tolerance_change = tolerance_change
        self.history_size = history_size
        self.line_search_fn = line_search_fn
        self._s_hist = []
        self._y_hist = []
        self._rho = []
        self._prev_flat_grad = None
        self._n_evals = 0

    # -- flat-vector helpers ----------------------------------------------

    def _gather_flat_grad(self):
        outs = []
        for p in self._params:
            g = p.grad._data if p.grad is not None else \
                jnp.zeros_like(p._data)
            outs.append(jnp.ravel(g.astype(jnp.float32)))
        return jnp.concatenate(outs)

    def _add_to_params(self, step_size, direction):
        off = 0
        for p in self._params:
            n = p.size
            upd = direction[off:off + n].reshape(p._data.shape)
            p._rebind((p._data.astype(jnp.float32)
                       + step_size * upd).astype(p._data.dtype))
            off += n

    def _clone_params(self):
        return [p._data for p in self._params]

    def _restore_params(self, saved):
        for p, arr in zip(self._params, saved):
            p._rebind(arr)

    def _eval(self, closure):
        self._n_evals += 1
        self.clear_grad()
        loss = closure()
        return float(loss), self._gather_flat_grad()

    # -- the step ---------------------------------------------------------

    def step(self, closure=None):
        assert closure is not None, \
            "LBFGS.step requires a closure re-evaluating the model"
        lr = self._lr

        loss, flat_grad = self._eval(closure)
        if float(jnp.max(jnp.abs(flat_grad))) <= self.tolerance_grad:
            return Tensor(jnp.float32(loss))

        for _ in range(self.max_iter):
            # two-loop recursion
            q = -flat_grad
            alphas = []
            for s, y, rho in zip(reversed(self._s_hist),
                                 reversed(self._y_hist),
                                 reversed(self._rho)):
                a = rho * jnp.dot(s, q)
                q = q - a * y
                alphas.append(a)
            if self._y_hist:
                y = self._y_hist[-1]
                s = self._s_hist[-1]
                gamma = jnp.dot(s, y) / jnp.maximum(jnp.dot(y, y), 1e-20)
                q = q * gamma
            for (s, y, rho), a in zip(
                    zip(self._s_hist, self._y_hist, self._rho),
                    reversed(alphas)):
                b = rho * jnp.dot(y, q)
                q = q + (a - b) * s
            direction = q

            gtd = float(jnp.dot(flat_grad, direction))
            if gtd > -self.tolerance_change:
                break

            if self.line_search_fn == "strong_wolfe":
                t, loss, new_grad = self._strong_wolfe(
                    closure, loss, flat_grad, direction, lr, gtd)
            else:
                t = lr
                self._add_to_params(t, direction)
                loss, new_grad = self._eval(closure)

            s = t * direction
            ydiff = new_grad - flat_grad
            sy = float(jnp.dot(s, ydiff))
            if sy > 1e-10:
                self._s_hist.append(s)
                self._y_hist.append(ydiff)
                self._rho.append(1.0 / sy)
                if len(self._s_hist) > self.history_size:
                    self._s_hist.pop(0)
                    self._y_hist.pop(0)
                    self._rho.pop(0)
            flat_grad = new_grad

            if float(jnp.max(jnp.abs(flat_grad))) <= self.tolerance_grad:
                break
            if float(jnp.max(jnp.abs(s))) <= self.tolerance_change:
                break
            if self._n_evals >= self.max_eval:
                break

        return Tensor(jnp.float32(loss))

    def _strong_wolfe(self, closure, f0, g0, d, t, gtd, c1=1e-4, c2=0.9,
                      max_ls=25):
        """Strong-Wolfe line search (bracket + zoom, reference
        lbfgs.py _strong_wolfe)."""
        saved = self._clone_params()

        def phi(alpha):
            self._restore_params(saved)
            self._add_to_params(alpha, d)
            f, g = self._eval(closure)
            return f, g, float(jnp.dot(g, d))

        alpha_prev, f_prev, dg_prev = 0.0, f0, gtd
        alpha = t
        result = None
        for _ in range(max_ls):
            f_new, g_new, dg_new = phi(alpha)
            if f_new > f0 + c1 * alpha * gtd or \
                    (result is not None and f_new >= f_prev):
                result = self._zoom(phi, alpha_prev, alpha, f0, gtd,
                                    f_prev, c1, c2)
                break
            if abs(dg_new) <= -c2 * gtd:
                result = (alpha, f_new, g_new)
                break
            if dg_new >= 0:
                result = self._zoom(phi, alpha, alpha_prev, f0, gtd,
                                    f_new, c1, c2)
                break
            alpha_prev, f_prev = alpha, f_new
            alpha *= 2.0
            result = (alpha_prev, f_prev, g_new)
        if result is None:
            result = (alpha, f_new, g_new)
        a, f, g = result
        self._restore_params(saved)
        self._add_to_params(a, d)
        f, g = self._eval(closure)
        return a, f, g

    def _zoom(self, phi, lo, hi, f0, gtd, f_lo, c1, c2, iters=10):
        g_best = None
        for _ in range(iters):
            mid = 0.5 * (lo + hi)
            f_mid, g_mid, dg_mid = phi(mid)
            g_best = (mid, f_mid, g_mid)
            if f_mid > f0 + c1 * mid * gtd or f_mid >= f_lo:
                hi = mid
            else:
                if abs(dg_mid) <= -c2 * gtd:
                    return mid, f_mid, g_mid
                if dg_mid * (hi - lo) >= 0:
                    hi = lo
                lo, f_lo = mid, f_mid
        return g_best
