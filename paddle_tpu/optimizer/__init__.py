"""`paddle.optimizer` surface (reference: python/paddle/optimizer/)."""

from . import lr  # noqa: F401
from .adam import Adam, Adamax, AdamW, Lamb, NAdam, RAdam  # noqa: F401
from .lbfgs import LBFGS  # noqa: F401
from .optimizer import (  # noqa: F401
    ASGD, Adadelta, Adagrad, Momentum, Optimizer, RMSProp, Rprop, SGD,
)
