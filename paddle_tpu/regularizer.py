"""Weight-decay regularizers (reference: python/paddle/regularizer.py)."""


class WeightDecayRegularizer:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self.coeff})"


class L1Decay(WeightDecayRegularizer):
    pass


class L2Decay(WeightDecayRegularizer):
    pass
