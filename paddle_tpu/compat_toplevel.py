"""Top-level export fills (reference python/paddle/__init__.py names not
covered by the ops/ modules): place classes, dtype info, RNG state,
printoptions, misc helpers.
"""

from __future__ import annotations

import numpy as np

from .core import random as random_mod
from .core.place import Place
from .core.tensor import Parameter, Tensor

__all__ = [
    "CPUPlace", "CUDAPlace", "CUDAPinnedPlace", "XPUPlace", "TPUPlace",
    "LazyGuard", "batch", "check_shape", "create_parameter",
    "disable_signal_handler", "finfo", "iinfo", "pdist", "reverse",
    "set_printoptions", "get_rng_state", "set_rng_state",
    "get_cuda_rng_state", "set_cuda_rng_state",
]


def CPUPlace():
    return Place("cpu")


def CUDAPlace(device_id=0):
    """Accelerator place (reference CUDAPlace; the accelerator here is
    the TPU)."""
    return Place("tpu", device_id)


def CUDAPinnedPlace():
    return Place("cpu")


def XPUPlace(device_id=0):
    return Place("tpu", device_id)


def TPUPlace(device_id=0):
    return Place("tpu", device_id)


class LazyGuard:
    """Reference paddle.LazyGuard: delays parameter materialization. XLA
    initializes parameters through compiled programs already, so eager
    init cost is one fused program — the guard is a compatibility
    context."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def batch(reader, batch_size, drop_last=False):
    """Legacy paddle.batch: wrap a sample reader into a batch reader."""

    def batch_reader():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batch_reader


def check_shape(shape):
    """Validate a shape argument (reference static check_shape)."""
    if isinstance(shape, (list, tuple)):
        for s in shape:
            if not isinstance(s, (int, np.integer)) and s is not None:
                raise TypeError(f"shape element {s!r} is not an int")
            if isinstance(s, (int, np.integer)) and s < -1:
                raise ValueError(f"shape element {s} < -1")
        return True
    raise TypeError("shape must be a list/tuple")


def create_parameter(shape, dtype, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Reference paddle.create_parameter."""
    from . import nn

    init = default_initializer or (
        attr.initializer if attr is not None and
        getattr(attr, "initializer", None) is not None else
        (nn.initializer.Constant(0.0) if is_bias
         else nn.initializer.XavierNormal()))
    from .core.dtype import convert_dtype
    p = Parameter(init(list(shape), convert_dtype(dtype)))
    if name:
        p.name = name
    return p


def disable_signal_handler():
    """Reference disable_signal_handler (the C++ core installs fault
    handlers; this build leaves Python's handlers alone)."""
    return None


class _DTypeInfo:
    def __repr__(self):
        fields = ", ".join(f"{k}={v}" for k, v in self.__dict__.items())
        return f"{type(self).__name__}({fields})"


def finfo(dtype):
    from .core.dtype import convert_dtype
    import jax.numpy as jnp

    fi = jnp.finfo(convert_dtype(dtype))
    out = _DTypeInfo()
    out.bits = fi.bits
    out.eps = float(fi.eps)
    out.min = float(fi.min)
    out.max = float(fi.max)
    out.tiny = float(fi.tiny)
    out.smallest_normal = float(fi.tiny)
    out.resolution = float(fi.resolution)
    out.dtype = str(fi.dtype)
    return out


def iinfo(dtype):
    from .core.dtype import convert_dtype
    import jax.numpy as jnp

    ii = jnp.iinfo(convert_dtype(dtype))
    out = _DTypeInfo()
    out.bits = ii.bits
    out.min = int(ii.min)
    out.max = int(ii.max)
    out.dtype = str(ii.dtype)
    return out


def pdist(x, p=2.0, name=None):
    """Condensed pairwise distances (reference paddle.pdist): upper
    triangle of cdist(x, x)."""
    from .ops.special import cdist

    full = cdist(x, x, p=p)
    n = x.shape[0]
    iu = np.triu_indices(n, k=1)
    return _take_triu(full, iu)


def _take_triu(full, iu):
    import jax.numpy as jnp

    from .core.dispatch import apply
    rows = jnp.asarray(iu[0], jnp.int32)
    cols = jnp.asarray(iu[1], jnp.int32)
    return apply(lambda a: a[rows, cols], full, name="pdist_gather")


def reverse(x, axis, name=None):
    """Legacy paddle.reverse == flip."""
    from .ops.manipulation import flip

    return flip(x, axis)


_PRINTOPTIONS = {}


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Reference paddle.set_printoptions — numpy rendering backs Tensor
    repr, so the options pass through."""
    kwargs = {}
    if precision is not None:
        kwargs["precision"] = precision
    if threshold is not None:
        kwargs["threshold"] = threshold
    if edgeitems is not None:
        kwargs["edgeitems"] = edgeitems
    if linewidth is not None:
        kwargs["linewidth"] = linewidth
    if sci_mode is not None:
        kwargs["suppress"] = not sci_mode
    _PRINTOPTIONS.update(kwargs)
    np.set_printoptions(**kwargs)


def get_rng_state(device=None):
    """RNG state as a list of generator states (reference returns one
    per device; the key-splitting Generator is global here)."""
    return [random_mod.default_generator().get_state()]


def set_rng_state(state_list, device=None):
    random_mod.default_generator().set_state(state_list[0])


def get_cuda_rng_state():
    return get_rng_state()


def set_cuda_rng_state(state_list):
    set_rng_state(state_list)
