"""DLPack interop (reference: python/paddle/utils/dlpack.py)."""

from __future__ import annotations

from ..core.tensor import Tensor


def to_dlpack(x):
    import jax
    arr = x._data if isinstance(x, Tensor) else x
    return jax.dlpack.to_dlpack(arr) if hasattr(jax.dlpack, "to_dlpack") \
        else arr.__dlpack__()


def from_dlpack(capsule):
    import jax
    arr = jax.dlpack.from_dlpack(capsule)
    return Tensor(arr)
