"""FLOPs counter (reference: python/paddle/hapi/dynamic_flops.py,
exposed as paddle.flops)."""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Estimate forward FLOPs by hooking leaf layers."""
    from .. import nn

    counts = {}

    def conv_flops(layer, inp, out):
        arr = out[0] if isinstance(out, (tuple, list)) else out
        k = int(np.prod(layer._kernel_size))
        cin = layer._in_channels // layer._groups
        return 2 * k * cin * arr.size

    def linear_flops(layer, inp, out):
        arr = out[0] if isinstance(out, (tuple, list)) else out
        return 2 * layer.weight.shape[0] * arr.size

    table = []
    hooks = []
    total = [0]

    def make_hook(name, fn):
        def hook(layer, inputs, outputs):
            n = fn(layer, inputs, outputs)
            total[0] += n
            table.append((name, n))
        return hook

    for name, layer in net.named_sublayers():
        if isinstance(layer, (nn.Conv1D, nn.Conv2D, nn.Conv3D)):
            hooks.append(layer.register_forward_post_hook(
                make_hook(name, conv_flops)))
        elif isinstance(layer, nn.Linear):
            hooks.append(layer.register_forward_post_hook(
                make_hook(name, linear_flops)))
        if custom_ops and type(layer) in custom_ops:
            hooks.append(layer.register_forward_post_hook(
                make_hook(name, custom_ops[type(layer)])))

    x = Tensor(np.zeros(input_size, np.float32))
    net.eval()
    net(x)
    for h in hooks:
        h.remove()
    if print_detail:
        for name, n in table:
            print(f"{name:<40} {n:,}")
    print(f"Total Flops: {total[0]:,}  Total Params: "
          f"{sum(p.size for p in net.parameters()):,}")
    return total[0]
