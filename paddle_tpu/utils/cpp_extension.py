"""Out-of-tree custom-op build system.

Parity: reference `paddle.utils.cpp_extension` (cpp_extension/
cpp_extension.py:86 `setup`, JIT `load`) compiling user C++/CUDA ops
against the phi C++ API (PD_BUILD_OP). TPU-native equivalent: user C++
builds against a plain C ABI (no framework headers needed) and the op is
registered as a host callback or pure-python jnp composition; `load`
compiles with g++ and returns a ctypes module. For device-side custom
kernels users write Pallas (the Pallas guide is the CUDA-kernel
replacement), which needs no build system at all.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess

__all__ = ["load", "setup", "CppExtension", "CUDAExtension",
           "get_build_directory"]

_BUILD_ROOT = os.path.expanduser("~/.cache/paddle_tpu/extensions")


def get_build_directory():
    os.makedirs(_BUILD_ROOT, exist_ok=True)
    return _BUILD_ROOT


def load(name, sources, extra_cxx_cflags=None, extra_cuda_cflags=None,
         extra_ldflags=None, extra_include_paths=None, build_directory=None,
         verbose=False):
    """JIT-compile C++ sources into a shared library; returns the loaded
    ctypes.CDLL. Functions use a plain C ABI."""
    build_dir = build_directory or get_build_directory()
    os.makedirs(build_dir, exist_ok=True)
    h = hashlib.sha256()
    for s in sources:
        with open(s, "rb") as f:
            h.update(f.read())
    so_path = os.path.join(build_dir, f"{name}-{h.hexdigest()[:12]}.so")
    if not os.path.exists(so_path):
        cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
               "-o", so_path]
        for inc in extra_include_paths or []:
            cmd.append(f"-I{inc}")
        cmd += list(extra_cxx_cflags or [])
        cmd += list(sources)
        cmd += list(extra_ldflags or [])
        if verbose:
            print(" ".join(cmd))
        subprocess.run(cmd, check=True, capture_output=not verbose)
    return ctypes.CDLL(so_path)


class CppExtension:
    def __init__(self, sources, *args, **kwargs):
        self.sources = sources
        self.kwargs = kwargs


CUDAExtension = CppExtension  # accepted for parity; no CUDA on TPU hosts


def setup(name=None, ext_modules=None, **kwargs):
    """Build-at-install parity: compiles each extension immediately and
    drops the .so next to the build dir (a full setuptools flow is
    unnecessary for the C-ABI contract)."""
    exts = ext_modules if isinstance(ext_modules, (list, tuple)) else \
        [ext_modules]
    libs = []
    for i, ext in enumerate(exts):
        if ext is None:
            continue
        libs.append(load(f"{name or 'ext'}_{i}", ext.sources,
                         **{k: v for k, v in ext.kwargs.items()
                            if k.startswith("extra_")}))
    return libs
