"""Out-of-tree custom-op build system.

Parity: reference `paddle.utils.cpp_extension`
(cpp_extension/cpp_extension.py:86 `setup`, JIT `load`) compiling user
C++/CUDA ops against the phi C++ API (PD_BUILD_OP macros in
paddle/phi/api/ext/op_meta_info.h). TPU-native equivalents, three
tiers:

1. ``load_op`` / ``CustomOpLibrary`` — the PD_BUILD_OP path: user C++
   written against ``paddle_ext.h`` (XLA FFI handlers, csrc/include/)
   compiles to a shared library; every exported ``pd_op_*`` symbol is
   registered as an XLA custom-call target and exposed as a
   Tensor-in/Tensor-out callable that works eagerly AND under jit.
   Exporting ``pd_op_<name>_grad`` wires the backward automatically.
2. ``load`` — plain C-ABI JIT build returning a ctypes.CDLL (the
   runtime's own native pieces use this).
3. ``setup`` — the setuptools packaging contract: with a command-line
   command it drives a real ``setuptools.setup`` (build_ext with the
   framework + XLA FFI include dirs injected); called bare (no argv
   command) it builds in place and returns the libraries, the
   convenience the previous revision shipped.

Device-side custom kernels are Pallas (no build system needed) — the
CUDA-kernel seam the reference compiles with nvcc.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sys

__all__ = ["load", "load_op", "setup", "CppExtension", "CUDAExtension",
           "CustomOpLibrary", "get_build_directory", "include_paths"]

_BUILD_ROOT = os.path.expanduser("~/.cache/paddle_tpu/extensions")


def get_build_directory():
    os.makedirs(_BUILD_ROOT, exist_ok=True)
    return _BUILD_ROOT


def include_paths():
    """Framework + XLA FFI header dirs for custom-op builds."""
    import jax

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return [os.path.join(here, "csrc", "include"), jax.ffi.include_dir()]


def _compile(name, sources, extra_cxx_cflags=None, extra_ldflags=None,
             extra_include_paths=None, build_directory=None,
             verbose=False):
    build_dir = build_directory or get_build_directory()
    os.makedirs(build_dir, exist_ok=True)
    h = hashlib.sha256()
    for s in sources:
        with open(s, "rb") as f:
            h.update(f.read())
    for fl in (extra_cxx_cflags or []) + (extra_ldflags or []):
        h.update(str(fl).encode())
    so_path = os.path.join(build_dir, f"{name}-{h.hexdigest()[:12]}.so")
    if not os.path.exists(so_path):
        cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
               "-o", so_path]
        for inc in (extra_include_paths or []) + include_paths():
            cmd.append(f"-I{inc}")
        cmd += list(extra_cxx_cflags or [])
        cmd += list(sources)
        cmd += list(extra_ldflags or [])
        if verbose:
            print(" ".join(cmd))
        subprocess.run(cmd, check=True, capture_output=not verbose)
    return so_path


def load(name, sources, extra_cxx_cflags=None, extra_cuda_cflags=None,
         extra_ldflags=None, extra_include_paths=None, build_directory=None,
         verbose=False):
    """JIT-compile C++ sources into a shared library; returns the loaded
    ctypes.CDLL. Functions use a plain C ABI."""
    so_path = _compile(name, sources, extra_cxx_cflags, extra_ldflags,
                       extra_include_paths, build_directory, verbose)
    return ctypes.CDLL(so_path)


class CustomOpLibrary:
    """A loaded PD_BUILD_OP library: each discovered op is an attribute
    taking Tensors and returning Tensors; ops with a registered
    ``<name>_grad`` handler are differentiable (tape + jit)."""

    def __init__(self, so_path):
        import jax

        self._so_path = so_path
        self._cdll = ctypes.CDLL(so_path)
        self._ops = {}
        nm = subprocess.run(["nm", "-D", "--defined-only", so_path],
                            check=True, capture_output=True, text=True)
        syms = [line.split()[-1] for line in nm.stdout.splitlines()
                if " T " in line or " t " in line]
        names = {s[len("pd_op_"):] for s in syms
                 if s.startswith("pd_op_")}
        grads = {n[:-len("_grad")] for n in names if n.endswith("_grad")}
        fwd_names = {n for n in names if not n.endswith("_grad")}
        tag = hashlib.sha256(so_path.encode()).hexdigest()[:8]
        for n in fwd_names:
            target = f"pd.{tag}.{n}"
            jax.ffi.register_ffi_target(
                target,
                jax.ffi.pycapsule(getattr(self._cdll, f"pd_op_{n}")),
                platform="cpu")
            grad_target = None
            if n in grads:
                grad_target = f"pd.{tag}.{n}_grad"
                jax.ffi.register_ffi_target(
                    grad_target,
                    jax.ffi.pycapsule(getattr(self._cdll,
                                              f"pd_op_{n}_grad")),
                    platform="cpu")
            self._ops[n] = self._make_op(n, target, grad_target)

    def op_names(self):
        return sorted(self._ops)

    def __getattr__(self, name):
        ops = self.__dict__.get("_ops") or {}
        if name in ops:
            return ops[name]
        raise AttributeError(
            f"custom-op library has no op {name!r}; available: "
            f"{sorted(ops)}")

    def _make_op(self, name, target, grad_target):
        import jax

        from ..core.dispatch import apply

        def raw(out_specs, *arrays):
            return jax.ffi.ffi_call(target, out_specs)(*arrays)

        def op(*tensors, out_specs=None):
            """out_specs: jax.ShapeDtypeStruct (or list of) for the
            output(s); defaults to the first input's shape/dtype (the
            elementwise contract)."""
            from ..core.tensor import Tensor

            arrays = [t._data if isinstance(t, Tensor) else t
                      for t in tensors]
            specs = out_specs or jax.ShapeDtypeStruct(
                arrays[0].shape, arrays[0].dtype)
            multi = isinstance(specs, (list, tuple))

            if grad_target is None:
                def fn(*a):
                    return raw(specs, *a)
                return apply(fn, *tensors, name=f"custom_op:{name}")

            in_specs = [jax.ShapeDtypeStruct(a.shape, a.dtype)
                        for a in arrays]

            @jax.custom_vjp
            def fn(*a):
                return raw(specs, *a)

            def fwd(*a):
                return raw(specs, *a), a

            def bwd(res, ct):
                cts = list(ct) if multi else [ct]
                grads = jax.ffi.ffi_call(grad_target, in_specs)(
                    *res, *cts)
                if not isinstance(grads, (list, tuple)):
                    grads = (grads,)
                return tuple(grads)

            fn.defvjp(fwd, bwd)
            return apply(fn, *tensors, name=f"custom_op:{name}")

        op.__name__ = name
        op._ffi_target = target  # jit users can jax.ffi.ffi_call it
        op._ffi_grad_target = grad_target
        return op


def load_op(name, sources, **kwargs):
    """Build a PD_BUILD_OP library (paddle_ext.h / XLA FFI handlers) and
    return a :class:`CustomOpLibrary` — the reference's custom-op
    ``load`` for ops rather than raw CDLLs."""
    so_path = _compile(name, sources,
                       kwargs.get("extra_cxx_cflags"),
                       kwargs.get("extra_ldflags"),
                       kwargs.get("extra_include_paths"),
                       kwargs.get("build_directory"),
                       kwargs.get("verbose", False))
    return CustomOpLibrary(so_path)


class CppExtension:
    """Extension spec; converts to a setuptools.Extension for the
    packaging flow (reference CppExtension helper)."""

    def __init__(self, sources, name=None, *args, **kwargs):
        self.sources = list(sources)
        self.name = name
        self.kwargs = kwargs

    def as_setuptools(self, fallback_name):
        from setuptools import Extension

        kw = dict(self.kwargs)
        inc = list(kw.pop("include_dirs", [])) + include_paths()
        kw.pop("extra_include_paths", None)
        extra = list(kw.pop("extra_compile_args",
                            kw.pop("extra_cxx_cflags", []) or []))
        return Extension(self.name or fallback_name,
                         sources=self.sources, include_dirs=inc,
                         extra_compile_args=["-std=c++17"] + extra,
                         language="c++",
                         **{k: v for k, v in kw.items()
                            if not k.startswith("extra_")})


CUDAExtension = CppExtension  # accepted for parity; no CUDA on TPU hosts

_SETUPTOOLS_COMMANDS = {
    "build", "build_ext", "bdist_wheel", "install", "develop", "sdist",
    "editable_wheel", "egg_info", "clean",
}


def setup(name=None, ext_modules=None, **kwargs):
    """The reference setup contract: with a setuptools command on the
    command line (``python setup.py install`` / ``bdist_wheel`` /
    ``build_ext``) this drives a REAL setuptools build of the
    extensions (framework + XLA FFI includes injected). Called without
    a command (programmatically) it JIT-builds in place and returns
    the CDLLs — the behavior scripts already rely on."""
    exts = ext_modules if isinstance(ext_modules, (list, tuple)) else \
        [ext_modules]
    exts = [e for e in exts if e is not None]

    if any(a in _SETUPTOOLS_COMMANDS for a in sys.argv[1:]):
        import setuptools

        st_exts = [
            e.as_setuptools(f"{name or 'ext'}_{i}")
            if isinstance(e, CppExtension) else e
            for i, e in enumerate(exts)
        ]
        return setuptools.setup(name=name, ext_modules=st_exts,
                                **kwargs)

    libs = []
    for i, ext in enumerate(exts):
        libs.append(load(f"{name or 'ext'}_{i}", ext.sources,
                         **{k: v for k, v in ext.kwargs.items()
                            if k.startswith("extra_")}))
    return libs
