"""`paddle.utils` (reference: python/paddle/utils/)."""

from . import dlpack  # noqa: F401
from . import cpp_extension  # noqa: F401
from .flops import flops  # noqa: F401


def try_import(module_name, err_msg=None):
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(err_msg or f"{module_name} is required") from e


def run_check():
    """paddle.utils.run_check parity: verify the runtime works."""
    import jax

    import paddle_tpu as paddle
    x = paddle.randn([4, 4])
    y = paddle.matmul(x, x)
    y.numpy()
    n = jax.device_count()
    print(f"paddle_tpu works. devices: {n} ({jax.default_backend()})")
    return True
