"""`paddle.utils` (reference: python/paddle/utils/)."""

from . import dlpack  # noqa: F401
from . import cpp_extension  # noqa: F401
from .flops import flops  # noqa: F401


def try_import(module_name, err_msg=None):
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(err_msg or f"{module_name} is required") from e


def run_check():
    """paddle.utils.run_check parity: verify the runtime works."""
    import jax

    import paddle_tpu as paddle
    x = paddle.randn([4, 4])
    y = paddle.matmul(x, x)
    y.numpy()
    n = jax.device_count()
    print(f"paddle_tpu works. devices: {n} ({jax.default_backend()})")
    return True


def deprecated(update_to="", since="", reason="", level=0):
    """Deprecation decorator (reference utils/deprecated.py): warns on
    call (level 0/1), raises on call for removed APIs (level 2), and
    prefixes the docstring with the deprecation notice."""
    import functools
    import warnings

    def wrap(fn):
        msg = f"API '{fn.__module__}.{fn.__name__}' is deprecated"
        if since:
            msg += f" since {since}"
        if update_to:
            msg += f", use '{update_to}' instead"
        if reason:
            msg += f". Reason: {reason}"

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            if level == 2:
                # removed API: refuse at CALL time (decoration must not
                # crash the defining module's import)
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        inner.__doc__ = f"Warning: {msg}\n\n{fn.__doc__ or ''}"
        return inner
    return wrap


def require_version(min_version, max_version=None):
    """Check the installed framework version against [min_version,
    max_version] (reference utils/install_check-style contract):
    raises on mismatch, returns True otherwise."""
    from .. import version as _version

    def key(v):
        """(numeric tuple, is_release): '0.1.0rc1' < '0.1.0' — a
        component's LEADING digits count; a pre-release suffix anywhere
        ranks below the plain release with the same numbers."""
        import re as _re
        nums, pre = [], 1
        for p in str(v).split("."):
            m = _re.match(r"(\d*)(.*)", p)
            nums.append(int(m.group(1)) if m.group(1) else 0)
            if m.group(2):
                pre = 0
        return tuple(nums + [0] * (4 - len(nums))), pre

    if not isinstance(min_version, str) or (
            max_version is not None and not isinstance(max_version, str)):
        raise TypeError("require_version expects version strings")
    cur = key(_version.full_version)
    if cur < key(min_version):
        raise Exception(
            f"installed version {_version.full_version} < required "
            f"minimum {min_version}")
    if max_version is not None and cur > key(max_version):
        raise Exception(
            f"installed version {_version.full_version} > allowed "
            f"maximum {max_version}")
    return True
