"""Random ops backed by the global Generator (parity: reference
`python/paddle/tensor/random.py`). Every draw splits the global PRNG key
(`paddle_tpu/core/random.py`), so results are deterministic under `seed()`
and trace-safe under the compiled train step (which scopes a per-step key).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import unwrap
from ..core.dtype import convert_dtype, get_default_dtype
from ..core.random import next_key
from ..core.tensor import Tensor
from .creation import _norm_shape

__all__ = [
    "rand", "randn", "randint", "randint_like", "randperm", "uniform",
    "normal", "standard_normal", "bernoulli", "multinomial", "poisson",
    "exponential_", "uniform_", "normal_", "rand_like", "randn_like",
    "standard_gamma", "binomial", "log_normal", "bernoulli_", "cauchy_",
    "geometric_", "log_normal_",
]


def _dt(dtype, default=None):
    return convert_dtype(dtype) if dtype is not None else \
        (default or get_default_dtype())


def rand(shape, dtype=None):
    return uniform(shape, dtype=dtype, min=0.0, max=1.0)


def randn(shape, dtype=None):
    return standard_normal(shape, dtype)


def standard_normal(shape, dtype=None):
    shape = _norm_shape(shape)
    return Tensor(jax.random.normal(next_key(), shape, _dt(dtype)))


def normal(mean=0.0, std=1.0, shape=None, dtype=None):
    m, s = unwrap(mean), unwrap(std)
    if shape is None:
        shape = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
    else:
        shape = _norm_shape(shape)
    draw = jax.random.normal(next_key(), shape, _dt(dtype))
    return Tensor(draw * s + m)


def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    return normal(mean, std, shape).exp()


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0):
    shape = _norm_shape(shape)
    key = jax.random.PRNGKey(seed) if seed else next_key()
    return Tensor(jax.random.uniform(key, shape, _dt(dtype),
                                     minval=unwrap(min), maxval=unwrap(max)))


def randint(low=0, high=None, shape=(1,), dtype="int64"):
    if high is None:
        low, high = 0, low
    shape = _norm_shape(shape)
    return Tensor(jax.random.randint(next_key(), shape, int(unwrap(low)),
                                     int(unwrap(high)),
                                     dtype=convert_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None):
    dt = convert_dtype(dtype) if dtype else x.dtype
    out = randint(low, high, x.shape, dtype="int64")
    return Tensor(out._data.astype(dt))


def randperm(n, dtype="int64"):
    return Tensor(jax.random.permutation(next_key(), int(n))
                  .astype(convert_dtype(dtype)))


def bernoulli(x, name=None):
    p = unwrap(x)
    draw = jax.random.uniform(next_key(), p.shape, p.dtype
                              if jnp.issubdtype(p.dtype, jnp.floating)
                              else jnp.float32)
    return Tensor((draw < p).astype(p.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    p = unwrap(x)
    logits = jnp.log(jnp.maximum(p, 1e-30))
    if replacement:
        out = jax.random.categorical(next_key(), logits,
                                     shape=(p.shape[:-1] or ()) +
                                     (num_samples,) if p.ndim > 1 else
                                     (num_samples,), axis=-1)
        return Tensor(out.astype(jnp.int64))
    # without replacement: Gumbel top-k trick
    g = jax.random.gumbel(next_key(), p.shape)
    scores = logits + g
    _, idx = jax.lax.top_k(scores, num_samples)
    return Tensor(idx.astype(jnp.int64))


def poisson(x, name=None):
    lam = unwrap(x)
    return Tensor(jax.random.poisson(next_key(), lam).astype(lam.dtype))


def standard_gamma(x, name=None):
    alpha = unwrap(x)
    return Tensor(jax.random.gamma(next_key(), alpha))


def binomial(count, prob, name=None):
    n, p = unwrap(count), unwrap(prob)
    return Tensor(jax.random.binomial(next_key(), n.astype(jnp.float32),
                                      p).astype(jnp.int64))


def rand_like(x, dtype=None):
    dt = convert_dtype(dtype) if dtype else x.dtype
    return Tensor(jax.random.uniform(next_key(), tuple(x.shape), dt))


def randn_like(x, dtype=None):
    dt = convert_dtype(dtype) if dtype else x.dtype
    return Tensor(jax.random.normal(next_key(), tuple(x.shape), dt))


def exponential_(x, lam=1.0, name=None):
    draw = jax.random.exponential(next_key(), tuple(x.shape),
                                  x.dtype) / lam
    return x._rebind(draw)


def uniform_(x, min=-1.0, max=1.0, name=None):
    draw = jax.random.uniform(next_key(), tuple(x.shape), x.dtype,
                              minval=min, maxval=max)
    return x._rebind(draw)


def normal_(x, mean=0.0, std=1.0, name=None):
    draw = jax.random.normal(next_key(), tuple(x.shape), x.dtype) * std + mean
    return x._rebind(draw)


def bernoulli_(x, p=0.5, name=None):
    draw = jax.random.bernoulli(next_key(), p, tuple(x.shape))
    return x._rebind(draw.astype(x.dtype))


def cauchy_(x, loc=0, scale=1, name=None):
    draw = jax.random.cauchy(next_key(), tuple(x.shape), x.dtype)
    return x._rebind(draw * scale + loc)


def geometric_(x, probs, name=None):
    u = jax.random.uniform(next_key(), tuple(x.shape), jnp.float32,
                           minval=jnp.finfo(jnp.float32).tiny, maxval=1.0)
    # number of Bernoulli(p) trials until first success (support 1, 2, ...)
    draw = jnp.ceil(jnp.log(u) / jnp.log1p(-probs))
    return x._rebind(draw.astype(x.dtype))


def log_normal_(x, mean=1.0, std=2.0, name=None):
    draw = jnp.exp(
        jax.random.normal(next_key(), tuple(x.shape), x.dtype) * std + mean)
    return x._rebind(draw)
