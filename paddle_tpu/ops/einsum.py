"""einsum (parity: reference `python/paddle/tensor/einsum.py`, 1.3k lines of
manual planning — on TPU we defer to jnp.einsum, which XLA lowers to fused
MXU contractions)."""

from __future__ import annotations

from ..core.dispatch import apply

__all__ = ["einsum"]


def einsum(equation, *operands):
    import jax.numpy as jnp

    from .math import mm_precision

    ops = operands[0] if len(operands) == 1 and isinstance(
        operands[0], (list, tuple)) else operands
    return apply(lambda *arrs: jnp.einsum(
        equation, *arrs, precision=mm_precision(*[a.dtype for a in arrs])),
        *ops, name="einsum")
