"""Remaining op-surface gaps (audited against reference
python/paddle/tensor exports)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import apply, unwrap
from ..core.tensor import Tensor

__all__ = ["addmm", "bincount", "increment", "index_fill", "inverse",
           "is_complex", "is_floating_point", "renorm", "scatter_nd",
           "scatter_nd_add", "signbit", "take", "tolist", "unfold"]


def tolist(x):
    return x.tolist() if isinstance(x, Tensor) else list(x)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply(lambda i, a, b: beta * i + alpha * (a @ b), input, x, y,
                 name="addmm")


def bincount(x, weights=None, minlength=0, name=None):
    arr = unwrap(x)
    n = max(int(arr.max()) + 1 if arr.size else 0, minlength)
    if weights is None:
        return apply(lambda a: jnp.bincount(a, length=n), x,
                     name="bincount")
    return apply(lambda a, w: jnp.bincount(a, weights=w, length=n), x,
                 weights, name="bincount")


def increment(x, value=1.0, name=None):
    from . import _inplace_from
    out = apply(lambda a: a + value, x, name="increment")
    return _inplace_from(x, out)


def index_fill(x, index, axis, value, name=None):
    def fn(a, idx):
        moved = jnp.moveaxis(a, axis, 0)
        filled = moved.at[idx].set(value)
        return jnp.moveaxis(filled, 0, axis)
    return apply(fn, x, index, name="index_fill")


def inverse(x, name=None):
    return apply(jnp.linalg.inv, x, name="inverse")


def is_complex(x):
    return jnp.issubdtype(unwrap(x).dtype, jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(unwrap(x).dtype, jnp.floating)


def renorm(x, p, axis, max_norm, name=None):
    def fn(a):
        moved = jnp.moveaxis(a, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        norms = jnp.linalg.norm(flat, ord=p, axis=1)
        scale = jnp.where(norms > max_norm,
                          max_norm / jnp.maximum(norms, 1e-12), 1.0)
        out = flat * scale[:, None]
        return jnp.moveaxis(out.reshape(moved.shape), 0, axis)
    return apply(fn, x, name="renorm")


def scatter_nd(index, updates, shape, name=None):
    def fn(idx, upd):
        out = jnp.zeros(tuple(shape), upd.dtype)
        return out.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd)
    return apply(fn, index, updates, name="scatter_nd")


def scatter_nd_add(x, index, updates, name=None):
    def fn(a, idx, upd):
        return a.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd)
    return apply(fn, x, index, updates, name="scatter_nd_add")


def signbit(x, name=None):
    return apply(jnp.signbit, x, name="signbit")


def take(x, index, mode="raise", name=None):
    def fn(a, idx):
        flat = a.reshape(-1)
        n = flat.shape[0]
        if mode == "wrap":
            idx = idx % n
        elif mode == "clip":
            idx = jnp.clip(idx, 0, n - 1)
        else:
            idx = jnp.where(idx < 0, idx + n, idx)
        return flat[idx]
    return apply(fn, x, index, name="take")


def unfold(x, axis, size, step, name=None):
    """Sliding windows along ``axis`` (paddle.Tensor.unfold)."""
    def fn(a):
        length = a.shape[axis]
        n = (length - size) // step + 1
        starts = jnp.arange(n) * step
        idx = starts[:, None] + jnp.arange(size)[None, :]
        moved = jnp.moveaxis(a, axis, 0)
        win = moved[idx]  # [n, size, ...rest]
        win = jnp.moveaxis(win, 1, -1)  # size to the end
        return jnp.moveaxis(win, 0, axis)
    return apply(fn, x, name="unfold")
