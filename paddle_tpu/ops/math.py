"""Elementwise and general math ops.

Parity target: `python/paddle/tensor/math.py` + `ops.yaml` elementwise section
of the reference. All lower straight to jnp/lax; XLA fuses chains of these
into single kernels, replacing the reference's hand-fused CUDA elementwise
machinery (`paddle/phi/kernels/funcs/elementwise_base.h`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply, unwrap
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor

__all__ = [
    "add", "subtract", "multiply", "divide", "floor_divide", "mod",
    "remainder", "pow", "float_power", "matmul", "sqrt", "rsqrt", "exp",
    "expm1", "log", "log2", "log10", "log1p", "abs", "neg", "sign", "floor",
    "ceil", "round", "trunc", "frac", "sin", "cos", "tan", "asin", "acos",
    "atan", "atan2", "sinh", "cosh", "tanh", "asinh", "acosh", "atanh",
    "reciprocal", "square", "maximum", "minimum", "fmax", "fmin", "clip",
    "scale", "add_n", "lerp", "erf", "erfinv", "logit", "isnan", "isinf",
    "isfinite", "nan_to_num", "cumsum", "cumprod", "cummax", "cummin",
    "logsumexp", "logcumsumexp", "logaddexp", "deg2rad", "rad2deg", "angle",
    "conj", "real", "imag", "digamma", "lgamma", "gammaln", "multiply_",
    "heaviside", "hypot", "ldexp", "copysign", "nextafter", "sgn",
    "stanh", "softplus_math", "rsqrt_", "sigmoid", "i0", "i1",
    "diff", "trapezoid", "cumulative_trapezoid", "vander", "gcd", "lcm",
    "broadcast_shape", "inner", "outer", "kron",
]


def _int_kind(dt):
    return dt is not None and (jnp.issubdtype(dt, jnp.integer)
                               or dt == jnp.bool_)


def _float_scalar(v):
    return isinstance(v, (float, np.floating))


def _int_like(v):
    if isinstance(v, (bool, int, np.integer, np.bool_)):
        return True
    # Tensor.dtype reads chain meta — never materializes a deferred chain
    dt = getattr(v, "dtype", None)
    return _int_kind(dt)


def _ref_promote(x, y, divide_op=False):
    """Reference scalar/arith type promotion (the eager math-op patch,
    eager_math_op_patch.cc:113 _supported_int_dtype_ incl. BOOL): an
    int/bool tensor meeting a python/numpy FLOAT scalar is cast to
    float32 (NOT f64 — jnp's weak-f64 rule diverges here under x64);
    true division (:740) additionally casts to float32 whenever both
    operands are int-kind."""
    def dt(v):
        # Tensor.dtype is meta-only (no deferred-chain materialization)
        return getattr(v, "dtype", None)

    def cast32(v):
        if isinstance(v, Tensor):
            return v.astype(jnp.float32)
        return v.astype(jnp.float32) if hasattr(v, "astype") else float(v)

    def weak(v):
        # np.float64(1.5) is a STRONG f64 for jnp and would promote
        # the freshly-cast f32 tensor right back up; the reference
        # reads the scalar as a double and applies it at the
        # tensor's dtype — a weak python float does the same
        return float(v) if isinstance(v, np.floating) else v

    xd, yd = dt(x), dt(y)
    if (_int_kind(xd) and _float_scalar(y)) or \
            (_int_kind(yd) and _float_scalar(x)):
        return (cast32(x) if _int_kind(xd) else weak(x),
                cast32(y) if _int_kind(yd) else weak(y))
    if divide_op and _int_like(x) and _int_like(y):
        return cast32(x), cast32(y)
    return x, y


def _binop(fn, name, defer=False):
    # defer=True: shape/dtype-preserving float elementwise — eligible
    # for the deferred-chain dispatch (core/deferred.py); the runtime
    # conditions (no grad, same shape+float dtype, no tracer) are
    # checked per call in dispatch.apply
    divide_op = name == "divide"

    def op(x, y, name_=None):
        x, y = _ref_promote(x, y, divide_op=divide_op)
        return apply(fn, x, y, name=name, defer=defer)
    op.__name__ = name
    return op


add = _binop(jnp.add, "add", defer=True)
subtract = _binop(jnp.subtract, "subtract", defer=True)
multiply = _binop(jnp.multiply, "multiply", defer=True)
divide = _binop(jnp.divide, "divide", defer=True)
floor_divide = _binop(jnp.floor_divide, "floor_divide")
mod = _binop(jnp.mod, "mod")
remainder = mod
maximum = _binop(jnp.maximum, "maximum", defer=True)
minimum = _binop(jnp.minimum, "minimum", defer=True)
fmax = _binop(jnp.fmax, "fmax")
fmin = _binop(jnp.fmin, "fmin")
atan2 = _binop(jnp.arctan2, "atan2")
logaddexp = _binop(jnp.logaddexp, "logaddexp")
hypot = _binop(jnp.hypot, "hypot")
copysign = _binop(jnp.copysign, "copysign")
nextafter = _binop(jnp.nextafter, "nextafter")
gcd = _binop(jnp.gcd, "gcd")
lcm = _binop(jnp.lcm, "lcm")


def _pow_fn(a, b):
    return jnp.power(a, b)


def pow(x, y, name=None):
    x, y = _ref_promote(x, y)
    # module-level wrapper: jnp.power itself carries an unhashable
    # closure cell, which would reject the op from the deferred-chain /
    # lazy-backward caches; the wrapper keys cleanly (jnp by module
    # identity)
    return apply(_pow_fn, x, y, name="pow", defer=True)


def float_power(x, y, name=None):
    """Not a reference name (torch-ism kept for convenience): always
    computes in float64, torch.float_power's contract."""
    return apply(lambda a, b: jnp.power(jnp.asarray(a, jnp.float64),
                                        jnp.asarray(b, jnp.float64)),
                 x, y, name="float_power")


def _unop(fn, name, defer=False):
    def op(x, name_=None):
        return apply(fn, x, name=name, defer=defer)
    op.__name__ = name
    return op


sqrt = _unop(jnp.sqrt, "sqrt", defer=True)
def _rsqrt_fn(a):
    return jax.lax.rsqrt(a)


# jax.lax.rsqrt (like jnp.power) carries closure state _fn_key rejects;
# the module wrapper keys cleanly so rsqrt can join deferred chains
rsqrt = _unop(_rsqrt_fn, "rsqrt", defer=True)
exp = _unop(jnp.exp, "exp", defer=True)
expm1 = _unop(jnp.expm1, "expm1", defer=True)
log = _unop(jnp.log, "log", defer=True)
log2 = _unop(jnp.log2, "log2", defer=True)
log10 = _unop(jnp.log10, "log10", defer=True)
log1p = _unop(jnp.log1p, "log1p", defer=True)
abs = _unop(jnp.abs, "abs", defer=True)
neg = _unop(jnp.negative, "neg", defer=True)
sign = _unop(jnp.sign, "sign", defer=True)
floor = _unop(jnp.floor, "floor", defer=True)
ceil = _unop(jnp.ceil, "ceil", defer=True)
round = _unop(jnp.round, "round", defer=True)
trunc = _unop(jnp.trunc, "trunc", defer=True)
sin = _unop(jnp.sin, "sin", defer=True)
cos = _unop(jnp.cos, "cos", defer=True)
tan = _unop(jnp.tan, "tan", defer=True)
asin = _unop(jnp.arcsin, "asin", defer=True)
acos = _unop(jnp.arccos, "acos", defer=True)
atan = _unop(jnp.arctan, "atan", defer=True)
sinh = _unop(jnp.sinh, "sinh", defer=True)
cosh = _unop(jnp.cosh, "cosh", defer=True)
tanh = _unop(jnp.tanh, "tanh", defer=True)
asinh = _unop(jnp.arcsinh, "asinh")
acosh = _unop(jnp.arccosh, "acosh")
atanh = _unop(jnp.arctanh, "atanh")
reciprocal = _unop(jnp.reciprocal, "reciprocal", defer=True)
square = _unop(jnp.square, "square", defer=True)
def _erf_fn(a):
    return jax.scipy.special.erf(a)


def _erfinv_fn(a):
    return jax.scipy.special.erfinv(a)


# jax.scipy.special fns carry closure state _fn_key rejects; module
# wrappers key cleanly so the erf family joins deferred chains
erf = _unop(_erf_fn, "erf", defer=True)
erfinv = _unop(_erfinv_fn, "erfinv", defer=True)
isnan = _unop(jnp.isnan, "isnan")
isinf = _unop(jnp.isinf, "isinf")
isfinite = _unop(jnp.isfinite, "isfinite")
deg2rad = _unop(jnp.deg2rad, "deg2rad")
rad2deg = _unop(jnp.rad2deg, "rad2deg")
angle = _unop(jnp.angle, "angle")
conj = _unop(jnp.conj, "conj")
real = _unop(jnp.real, "real")
imag = _unop(jnp.imag, "imag")
digamma = _unop(jax.scipy.special.digamma, "digamma")
lgamma = _unop(jax.scipy.special.gammaln, "lgamma")
gammaln = lgamma
sigmoid = _unop(jax.nn.sigmoid, "sigmoid", defer=True)
i0 = _unop(jax.scipy.special.i0, "i0")
i1 = _unop(jax.scipy.special.i1, "i1")


def frac(x, name=None):
    return apply(lambda a: a - jnp.trunc(a), x, name="frac", defer=True)


def sgn(x, name=None):
    def _sgn(a):
        if jnp.issubdtype(a.dtype, jnp.complexfloating):
            mag = jnp.abs(a)
            return jnp.where(mag == 0, 0, a / jnp.where(mag == 0, 1, mag))
        return jnp.sign(a)
    return apply(_sgn, x, name="sgn")


def logit(x, eps=None, name=None):
    def _logit(a):
        if eps is not None:
            a = jnp.clip(a, eps, 1.0 - eps)
        return jnp.log(a / (1.0 - a))
    return apply(_logit, x, name="logit")


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply(lambda a: scale_b * jnp.tanh(scale_a * a), x, name="stanh")


def softplus_math(x, beta=1.0, threshold=20.0):
    return apply(
        lambda a: jnp.where(a * beta > threshold, a,
                            jnp.log1p(jnp.exp(beta * a)) / beta),
        x, name="softplus")


def clip(x, min=None, max=None, name=None):
    lo = unwrap(min)
    hi = unwrap(max)
    # bounds ride as POSITIONAL args: on the deferred path scalars become
    # ("const", v) chain-argspec entries, i.e. 0-d jit ARGUMENTS whose
    # values stay out of the chain jit key — a loop-varying clip
    # threshold reuses one compiled program instead of recompiling per
    # value and churning _JIT_CACHE (ADVICE r5). jnp.clip is itself the
    # maximum/minimum composition, so numerics (and vjp tie behavior)
    # are unchanged; tensor bounds are array args -> try_defer rejects,
    # eager path as before.
    if lo is not None and hi is not None:
        return apply(_clip_both, x, lo, hi, name="clip", defer=True)
    if lo is not None:
        return apply(jnp.maximum, x, lo, name="clip", defer=True)
    if hi is not None:
        return apply(jnp.minimum, x, hi, name="clip", defer=True)
    # no bounds: still a fresh tensor, like jnp.clip(a)
    return apply(jnp.positive, x, name="clip", defer=True)


def _clip_both(a, lo, hi):
    return jnp.clip(a, lo, hi)


def _scale_after(a, s, b):
    return a * s + b


def _scale_before(a, s, b):
    return (a + b) * s


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s, b = unwrap(scale), unwrap(bias)
    # s/b as positional args, same reasoning as clip: loop-varying
    # scale/bias dedupe into deferred-chain jit arguments, no recompile
    fn = _scale_after if bias_after_scale else _scale_before
    return apply(fn, x, s, b, name="scale", defer=True)


def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        return inputs
    def _add_n(*arrs):
        out = arrs[0]
        for a in arrs[1:]:
            out = out + a
        return out
    return apply(_add_n, *inputs, name="add_n")


def lerp(x, y, weight, name=None):
    return apply(lambda a, b, w: a + w * (b - a), x, y, weight, name="lerp")


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply(lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf,
                                          neginf=neginf), x, name="nan_to_num")


def cumsum(x, axis=None, dtype=None, name=None):
    return apply(lambda a: jnp.cumsum(a, axis=axis,
                                      dtype=convert_dtype(dtype) if dtype
                                      else None),
                 x, name="cumsum")


def cumprod(x, dim=None, dtype=None, name=None):
    return apply(lambda a: jnp.cumprod(a, axis=dim,
                                       dtype=convert_dtype(dtype) if dtype
                                       else None),
                 x, name="cumprod")


def cummax(x, axis=None, dtype="int64", name=None):
    def _cummax(a):
        ax = axis if axis is not None else 0
        arr = a.reshape(-1) if axis is None else a
        vals = jax.lax.associative_scan(jnp.maximum, arr, axis=ax)
        n = arr.shape[ax]
        iota = jax.lax.broadcasted_iota(jnp.int64, arr.shape, ax)
        is_new = arr == vals
        idx = jnp.where(is_new, iota, -1)
        inds = jax.lax.associative_scan(jnp.maximum, idx, axis=ax)
        return vals, inds.astype(convert_dtype(dtype))
    out = apply(_cummax, x, name="cummax")
    return out[0], out[1]


def cummin(x, axis=None, dtype="int64", name=None):
    def _cummin(a):
        ax = axis if axis is not None else 0
        arr = a.reshape(-1) if axis is None else a
        vals = jax.lax.associative_scan(jnp.minimum, arr, axis=ax)
        iota = jax.lax.broadcasted_iota(jnp.int64, arr.shape, ax)
        is_new = arr == vals
        idx = jnp.where(is_new, iota, -1)
        inds = jax.lax.associative_scan(jnp.maximum, idx, axis=ax)
        return vals, inds.astype(convert_dtype(dtype))
    out = apply(_cummin, x, name="cummin")
    return out[0], out[1]


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jax.scipy.special.logsumexp(a, axis=axis,
                                                       keepdims=keepdim),
                 x, name="logsumexp")


def logcumsumexp(x, axis=None, name=None):
    def _lcse(a):
        arr = a.reshape(-1) if axis is None else a
        ax = 0 if axis is None else axis
        return jax.lax.associative_scan(jnp.logaddexp, arr, axis=ax)
    return apply(_lcse, x, name="logcumsumexp")


def heaviside(x, y, name=None):
    return apply(lambda a, b: jnp.heaviside(a, b), x, y, name="heaviside")


def ldexp(x, y, name=None):
    return apply(lambda a, b: a * jnp.power(2.0, b.astype(jnp.float32)),
                 x, y, name="ldexp")


def mm_precision(*dtypes):
    """float32 contractions run at full fp32 precision (paddle parity);
    bf16/fp16 keep the fast MXU path."""
    if any(jnp.dtype(d) == jnp.float32 for d in dtypes):
        return jax.lax.Precision.HIGHEST
    return None


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def _matmul(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b, precision=mm_precision(a.dtype, b.dtype))
    return apply(_matmul, x, y, name="matmul")


def inner(x, y, name=None):
    return apply(jnp.inner, x, y, name="inner")


def outer(x, y, name=None):
    return apply(lambda a, b: jnp.outer(a, b), x, y, name="outer")


def kron(x, y, name=None):
    return apply(jnp.kron, x, y, name="kron")


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    pre = unwrap(prepend)
    app = unwrap(append)
    return apply(lambda a: jnp.diff(a, n=n, axis=axis, prepend=pre,
                                    append=app), x, name="diff")


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    xa = unwrap(x)
    def _trap(a):
        if xa is not None:
            return jax.scipy.integrate.trapezoid(a, x=xa, axis=axis)
        return jax.scipy.integrate.trapezoid(a, dx=dx or 1.0, axis=axis)
    return apply(_trap, y, name="trapezoid")


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    xa = unwrap(x)

    def _ctrap(a):
        d = jnp.diff(xa, axis=axis) if xa is not None else (dx or 1.0)
        left = jax.lax.slice_in_dim(a, 0, a.shape[axis] - 1, axis=axis)
        right = jax.lax.slice_in_dim(a, 1, a.shape[axis], axis=axis)
        if xa is not None and jnp.ndim(d) == 1 and a.ndim > 1:
            shape = [1] * a.ndim
            shape[axis] = -1
            d = d.reshape(shape)
        return jnp.cumsum((left + right) * d / 2.0, axis=axis)
    return apply(_ctrap, y, name="cumulative_trapezoid")


def vander(x, n=None, increasing=False, name=None):
    return apply(lambda a: jnp.vander(a, N=n, increasing=increasing),
                 x, name="vander")


def broadcast_shape(x_shape, y_shape):
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def multiply_(x, y):
    from . import _inplace_from
    return _inplace_from(x, multiply(x, y))


def rsqrt_(x):
    from . import _inplace_from
    return _inplace_from(x, rsqrt(x))
