"""Comparison / logical / bitwise ops (parity: reference
`python/paddle/tensor/logic.py`)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply, unwrap
from ..core.tensor import Tensor

__all__ = [
    "equal", "not_equal", "less_than", "less_equal", "greater_than",
    "greater_equal", "logical_and", "logical_or", "logical_not",
    "logical_xor", "bitwise_and", "bitwise_or", "bitwise_not", "bitwise_xor",
    "bitwise_left_shift", "bitwise_right_shift", "allclose", "isclose",
    "equal_all", "is_empty",
]


def _cmp(fn, name):
    def op(x, y, name_=None):
        return apply(fn, x, y, name=name)
    op.__name__ = name
    return op


equal = _cmp(jnp.equal, "equal")
not_equal = _cmp(jnp.not_equal, "not_equal")
less_than = _cmp(jnp.less, "less_than")
less_equal = _cmp(jnp.less_equal, "less_equal")
greater_than = _cmp(jnp.greater, "greater_than")
greater_equal = _cmp(jnp.greater_equal, "greater_equal")
logical_and = _cmp(jnp.logical_and, "logical_and")
logical_or = _cmp(jnp.logical_or, "logical_or")
logical_xor = _cmp(jnp.logical_xor, "logical_xor")
bitwise_and = _cmp(jnp.bitwise_and, "bitwise_and")
bitwise_or = _cmp(jnp.bitwise_or, "bitwise_or")
bitwise_xor = _cmp(jnp.bitwise_xor, "bitwise_xor")
bitwise_left_shift = _cmp(jnp.left_shift, "bitwise_left_shift")
bitwise_right_shift = _cmp(jnp.right_shift, "bitwise_right_shift")


def logical_not(x, name=None):
    return apply(jnp.logical_not, x, name="logical_not")


def bitwise_not(x, name=None):
    return apply(jnp.bitwise_not, x, name="bitwise_not")


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                                           equal_nan=equal_nan),
                 x, y, name="allclose")


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                          equal_nan=equal_nan),
                 x, y, name="isclose")


def equal_all(x, y, name=None):
    def _equal_all(a, b):
        if a.shape != b.shape:
            return jnp.asarray(False)
        return jnp.all(a == b)
    return apply(_equal_all, x, y, name="equal_all")


def is_empty(x, name=None):
    return Tensor(jnp.asarray(int(np.prod(x.shape)) == 0))
