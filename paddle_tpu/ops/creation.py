"""Tensor creation ops.

Parity target: `python/paddle/tensor/creation.py` in the reference.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply, unwrap
from ..core.dtype import convert_dtype, get_default_dtype
from ..core.tensor import Tensor

__all__ = [
    "to_tensor", "zeros", "ones", "full", "empty", "zeros_like", "ones_like",
    "full_like", "empty_like", "arange", "linspace", "logspace", "eye",
    "diag", "diagflat", "tril", "triu", "meshgrid", "assign", "clone",
    "tril_indices", "triu_indices", "complex", "polar", "one_hot",
]


def _dt(dtype, default=None):
    if dtype is None:
        return default
    return convert_dtype(dtype)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    if isinstance(data, Tensor):
        t = Tensor(data._data, dtype=dtype, place=place,
                   stop_gradient=stop_gradient)
        return t
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)


def zeros(shape, dtype=None):
    shape = _norm_shape(shape)
    return Tensor(jnp.zeros(shape, _dt(dtype, get_default_dtype())))


def ones(shape, dtype=None):
    shape = _norm_shape(shape)
    return Tensor(jnp.ones(shape, _dt(dtype, get_default_dtype())))


def full(shape, fill_value, dtype=None):
    shape = _norm_shape(shape)
    fill_value = unwrap(fill_value)
    if dtype is None:
        arr = jnp.full(shape, fill_value)
        if arr.dtype == jnp.float64:
            arr = arr.astype(get_default_dtype())
    else:
        arr = jnp.full(shape, fill_value, convert_dtype(dtype))
    return Tensor(arr)


def empty(shape, dtype=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None):
    return apply(jnp.zeros_like, x, dtype=_dt(dtype), name="zeros_like")


def ones_like(x, dtype=None):
    return apply(jnp.ones_like, x, dtype=_dt(dtype), name="ones_like")


def full_like(x, fill_value, dtype=None):
    return Tensor(jnp.full_like(unwrap(x), unwrap(fill_value),
                                dtype=_dt(dtype)))


def empty_like(x, dtype=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None):
    start, end, step = unwrap(start), unwrap(end), unwrap(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        if any(isinstance(v, float) or (hasattr(v, "dtype") and
               jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating))
               for v in (start, end, step)):
            dtype = get_default_dtype()
        else:
            dtype = jnp.int64
    return Tensor(jnp.arange(start, end, step, dtype=convert_dtype(dtype)))


def linspace(start, stop, num, dtype=None):
    return Tensor(jnp.linspace(unwrap(start), unwrap(stop), int(unwrap(num)),
                               dtype=_dt(dtype, get_default_dtype())))


def logspace(start, stop, num, base=10.0, dtype=None):
    return Tensor(jnp.logspace(unwrap(start), unwrap(stop), int(unwrap(num)),
                               base=base,
                               dtype=_dt(dtype, get_default_dtype())))


def eye(num_rows, num_columns=None, dtype=None):
    return Tensor(jnp.eye(num_rows, num_columns,
                          dtype=_dt(dtype, get_default_dtype())))


def diag(x, offset=0, padding_value=0):
    def _diag(a):
        if a.ndim == 1:
            out = jnp.diag(a, k=offset)
            if padding_value != 0:
                mask = jnp.diag(jnp.ones_like(a, dtype=bool), k=offset)
                out = jnp.where(mask, out, padding_value)
            return out
        return jnp.diagonal(a, offset=offset)
    return apply(_diag, x, name="diag")


def diagflat(x, offset=0):
    return apply(lambda a: jnp.diagflat(a, k=offset), x, name="diagflat")


def tril(x, diagonal=0):
    return apply(lambda a: jnp.tril(a, k=diagonal), x, name="tril")


def triu(x, diagonal=0):
    return apply(lambda a: jnp.triu(a, k=diagonal), x, name="triu")


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = col if col is not None else row
    r, c = np.triu_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=convert_dtype(dtype)))


def meshgrid(*args):
    arrays = [unwrap(a) for a in (args[0] if len(args) == 1 and
              isinstance(args[0], (list, tuple)) else args)]
    outs = jnp.meshgrid(*arrays, indexing="ij")
    return [Tensor(o) for o in outs]


def assign(x, output=None):
    src = unwrap(x)
    if output is None:
        return apply(lambda a: a, x, name="assign") if isinstance(x, Tensor) \
            else Tensor(jnp.asarray(src))
    output.set_value(src)
    return output


def clone(x):
    return apply(lambda a: a + jnp.zeros((), a.dtype), x, name="clone")


def complex(real, imag):
    return apply(jax.lax.complex, real, imag, name="complex")


def polar(abs_, angle):
    return apply(lambda a, t: a * jnp.exp(1j * t.astype(jnp.complex64)),
                 abs_, angle, name="polar")


def one_hot(x, num_classes):
    return apply(
        lambda a: jax.nn.one_hot(a, num_classes, dtype=get_default_dtype()),
        x, name="one_hot")


def _norm_shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in np.asarray(shape._data))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(unwrap(s)) if not isinstance(s, int) else s for s in shape)
