"""Op library: the `paddle.*` tensor-op surface.

Parity target: reference `python/paddle/tensor/` (~700 wrappers over
`_C_ops`). Here every op is a thin jnp/lax closure dispatched through
`core.dispatch.apply`, which handles autograd recording; there is no
per-op kernel registry because XLA performs backend kernel selection.

`bind_tensor_methods` attaches the method/dunder surface to Tensor —
the analogue of the generated `paddle/fluid/pybind/eager_method.cc`.
"""

from __future__ import annotations

import builtins

import jax.numpy as jnp

from ..core.dispatch import apply, unwrap
from ..core.tensor import Tensor

from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .reduction import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .einsum import *  # noqa: F401,F403
from .extras import *  # noqa: F401,F403
from .special import *  # noqa: F401,F403

from . import creation, math, reduction, manipulation, logic, search
from . import random, linalg, einsum as einsum_mod
from . import special


# inplace families with reference-sanctioned dtype behavior
# (python/paddle/tensor/logic.py:627 `equal_` and siblings write the bool
# result back into the receiver's buffer — the receiver KEEPS its dtype and
# holds 0/1 values; `cast_` is the one op whose receiver legitimately
# retypes).
_INPLACE_CAST_RESULT = frozenset({
    "equal", "not_equal", "greater_equal", "greater_than", "less_equal",
    "less_than", "logical_and", "logical_not", "logical_or", "logical_xor",
})
_INPLACE_RETYPES = frozenset({"cast"})
# inplace ops whose receiver legitimately changes shape (reshape_ etc.);
# every other generated inplace op must preserve the receiver's shape —
# the reference raises ValueError when broadcasting would grow the
# inplace tensor (python/paddle/tensor/logic.py equal_ shape check).
_INPLACE_RESHAPES = frozenset({
    "reshape", "squeeze", "unsqueeze", "flatten", "t", "transpose",
    # axis=None cumsum_/cumprod_ is an in-place flatten in the reference
    # (python/paddle/tensor/math.py:4221 cumsum_ flatten=True)
    "cumsum", "cumprod",
})


def _inplace_from(t: Tensor, out: Tensor, *, cast_result: bool = False,
                  allow_retype: bool = False) -> Tensor:
    """Give ``t`` the value (and tape position) of ``out`` — the functional
    realization of the reference's inplace ops (`x.add_(y)` etc.).

    Rebinding is safe for the tape because every Node snapshots its
    parents' (producer, out_idx) at record time (core/autograd.Node —
    the eager analogue of the reference's TensorWrapper inplace-version
    snapshot): backward routes through the graph as it stood when the
    value was consumed, not through this mutation."""
    if t.is_leaf and not t.stop_gradient and t._node is None and \
            out._node is not None:
        raise RuntimeError(
            "in-place operation on a leaf tensor that requires grad")
    if out.dtype != t.dtype and not allow_retype:
        # (Tensor.dtype reads chain meta — an inplace rebind must not
        # materialize a deferred elementwise chain, or inplace loops
        # would pay one dispatch per op)
        if cast_result:
            # comparison/logical family: the bool result is written back
            # into the receiver's existing dtype (reference logic.py:627)
            out = manipulation.cast(out, t.dtype)
        else:
            # the reference's inplace promotion whitelist casts only the
            # NON-inplaced operand (eager_gen.py type_promote_inplace_
            # white_list); an arithmetic op whose result dtype differs
            # from x cannot write back in place — int_x.add_(1.5) errors,
            # never silently retypes x
            raise TypeError(
                f"in-place operation would change dtype from "
                f"{t._data.dtype} to {out._data.dtype}; cast explicitly")
    # adopt out's payload WITHOUT materializing a deferred chain: an
    # inplace loop (x.add_(y) per step) then batches like its
    # out-of-place form, flushing only on a real read
    if t._pending is not None and t._pending is not out._pending:
        # the replaced pending Expr would otherwise keep its owner
        # weakref on the (live) receiver, and later flushes of chains
        # sharing it would compute an output no one can ever read
        from ..core.deferred import release_owner
        release_owner(t._pending, t)
    t._buf = out._buf
    t._pending = out._pending
    if t._pending is not None:
        from ..core.deferred import bind_owner
        bind_owner(t._pending, t)
    t._node = out._node
    t._out_idx = out._out_idx
    t.stop_gradient = out.stop_gradient and t.stop_gradient
    return t


def _getitem(self, idx):
    idx_u = _unwrap_index(idx)
    return apply(lambda a: a[idx_u], self, name="getitem")


def _setitem(self, idx, value):
    idx_u = _unwrap_index(idx)
    if isinstance(value, Tensor):
        out = apply(lambda a, v: a.at[idx_u].set(v.astype(a.dtype)), self,
                    value, name="setitem")
    else:
        out = apply(lambda a: a.at[idx_u].set(value), self, name="setitem")
    _inplace_from(self, out)


def _unwrap_index(idx):
    if isinstance(idx, tuple):
        return tuple(_unwrap_index(i) for i in idx)
    if isinstance(idx, Tensor):
        return idx._data
    if isinstance(idx, list):
        return jnp.asarray(idx)
    if isinstance(idx, builtins.slice):
        return builtins.slice(unwrap(idx.start), unwrap(idx.stop),
                              unwrap(idx.step))
    return idx


_BINARY_DUNDERS = {
    "__add__": add, "__sub__": subtract, "__mul__": multiply,
    "__truediv__": divide, "__floordiv__": floor_divide, "__mod__": mod,
    "__pow__": math.pow, "__matmul__": matmul,
    "__eq__": equal, "__ne__": not_equal, "__lt__": less_than,
    "__le__": less_equal, "__gt__": greater_than, "__ge__": greater_equal,
    "__and__": bitwise_and, "__or__": bitwise_or, "__xor__": bitwise_xor,
    "__lshift__": bitwise_left_shift, "__rshift__": bitwise_right_shift,
}

_REFLECTED = {
    "__radd__": add, "__rmul__": multiply,
    "__rsub__": lambda x, y: subtract(y, x),
    "__rtruediv__": lambda x, y: divide(y, x),
    "__rfloordiv__": lambda x, y: floor_divide(y, x),
    "__rmod__": lambda x, y: mod(y, x),
    "__rpow__": lambda x, y: math.pow(y, x),
    "__rmatmul__": lambda x, y: matmul(y, x),
}

_METHODS = {
    # math
    "add": add, "subtract": subtract, "multiply": multiply, "divide": divide,
    "floor_divide": floor_divide, "mod": mod, "remainder": mod,
    "pow": math.pow, "matmul": matmul, "sqrt": sqrt, "rsqrt": rsqrt,
    "exp": exp, "expm1": expm1, "log": log, "log2": log2, "log10": log10,
    "log1p": log1p, "abs": math.abs, "neg": neg, "sign": sign,
    "floor": floor, "ceil": ceil, "round": math.round, "trunc": trunc,
    "frac": frac, "sin": sin, "cos": cos, "tan": tan, "asin": asin,
    "acos": acos, "atan": atan, "atan2": atan2, "sinh": sinh, "cosh": cosh,
    "tanh": tanh, "asinh": asinh, "acosh": acosh, "atanh": atanh,
    "reciprocal": reciprocal, "square": square, "maximum": maximum,
    "minimum": minimum, "fmax": fmax, "fmin": fmin, "clip": clip,
    "scale": scale, "lerp": lerp, "erf": erf, "erfinv": erfinv,
    "isnan": isnan, "isinf": isinf, "isfinite": isfinite,
    "nan_to_num": nan_to_num, "cumsum": cumsum, "cumprod": cumprod,
    "logsumexp": logsumexp, "logcumsumexp": logcumsumexp, "logit": logit,
    "digamma": digamma, "lgamma": lgamma, "sigmoid": sigmoid,
    "heaviside": heaviside, "hypot": hypot, "diff": diff, "sgn": sgn,
    "inner": inner, "outer": outer, "kron": kron, "conj": conj,
    "deg2rad": deg2rad, "rad2deg": rad2deg, "angle": angle,
    "cummax": cummax, "cummin": cummin, "gcd": gcd, "lcm": lcm,
    # reduction
    "sum": reduction.sum, "mean": mean, "max": reduction.max,
    "min": reduction.min, "amax": amax, "amin": amin, "prod": prod,
    "all": reduction.all, "any": reduction.any,
    "count_nonzero": count_nonzero, "median": median, "nanmedian": nanmedian,
    "nansum": nansum, "nanmean": nanmean, "var": var, "std": std,
    "quantile": quantile, "nanquantile": nanquantile,
    # manipulation
    "reshape": reshape, "transpose": manipulation.transpose, "cast": cast,
    "astype": cast, "split": split, "chunk": chunk, "squeeze": squeeze,
    "unsqueeze": unsqueeze, "flatten": manipulation.flatten, "tile": tile,
    "expand": expand, "expand_as": expand_as, "broadcast_to": broadcast_to,
    "flip": flip, "rot90": rot90, "roll": roll, "gather": gather,
    "gather_nd": gather_nd, "scatter": scatter,
    "scatter_nd_add": scatter_nd_add, "index_select": index_select,
    "index_add": index_add, "index_put": index_put,
    "masked_select": manipulation.masked_select, "masked_fill": masked_fill,
    "where": manipulation.where, "pad": pad, "unbind": unbind,
    "unstack": unstack, "repeat_interleave": repeat_interleave,
    "take_along_axis": take_along_axis, "put_along_axis": put_along_axis,
    "moveaxis": moveaxis, "swapaxes": swapaxes, "tensordot": tensordot,
    "unflatten": unflatten, "view": view, "view_as": view_as,
    "diagonal": diagonal, "diag_embed": diag_embed, "numel_t": numel,
    "tensor_split": tensor_split, "as_real": as_real, "as_complex": as_complex,
    # logic
    "equal": equal, "not_equal": not_equal, "less_than": less_than,
    "less_equal": less_equal, "greater_than": greater_than,
    "greater_equal": greater_equal, "logical_and": logical_and,
    "logical_or": logical_or, "logical_not": logical_not,
    "logical_xor": logical_xor, "bitwise_and": bitwise_and,
    "bitwise_or": bitwise_or, "bitwise_not": bitwise_not,
    "bitwise_xor": bitwise_xor, "allclose": allclose, "isclose": isclose,
    "equal_all": equal_all,
    # search
    "argmax": argmax, "argmin": argmin, "argsort": argsort, "sort": sort,
    "topk": topk, "nonzero": nonzero, "kthvalue": kthvalue, "mode": mode,
    "index_sample": index_sample, "bucketize": bucketize, "unique": unique,
    "unique_consecutive": unique_consecutive,
    # linalg
    "dot": dot, "bmm": bmm, "mm": mm, "mv": mv, "norm": linalg.norm,
    "dist": dist, "cross": cross, "cholesky": cholesky, "qr": qr,
    "svd": svd, "inv": inv, "pinv": pinv, "solve": solve,
    "matrix_power": matrix_power, "det": det, "slogdet": slogdet,
    "trace": linalg.trace, "eigvals": eigvals, "cov": cov,
    "corrcoef": corrcoef, "histogram": histogram, "lu": lu,
    # extras
    "renorm": renorm,
    # creation-ish
    "clone": clone, "tril": tril, "triu": triu, "diag": diag,
    "diagflat": diagflat,
    # random inplace
    "exponential_": random.exponential_, "uniform_": random.uniform_,
    "normal_": random.normal_, "bernoulli_": random.bernoulli_,
    "cauchy_": random.cauchy_, "geometric_": random.geometric_,
    "log_normal_": random.log_normal_,
}

# ops whose first arg is the tensor and have natural inplace variants
_INPLACE_BASES = [
    "add", "subtract", "multiply", "divide", "floor_divide", "mod",
    "remainder", "pow", "sqrt", "rsqrt", "exp", "log", "abs", "neg",
    "floor", "ceil", "round", "trunc", "sin", "cos", "tan", "tanh",
    "sigmoid", "reciprocal", "square", "clip", "scale", "lerp", "erf",
    "erfinv", "nan_to_num", "logit", "cumsum", "cast", "reshape",
    "squeeze", "unsqueeze", "flatten", "flip", "scatter", "masked_fill",
    "index_put", "put_along_axis", "tril", "triu", "digamma", "lgamma",
    "frac", "asin", "acos", "atan", "sinh", "cosh", "asinh", "acosh",
    "atanh", "expm1", "log2", "log10", "log1p", "sign",
]


def _make_method(fn):
    def method(self, *args, **kwargs):
        return fn(self, *args, **kwargs)
    method.__name__ = fn.__name__
    method.__doc__ = fn.__doc__
    return method


def _make_inplace(fn, base=None):
    base = base or fn.__name__
    cast_result = base in _INPLACE_CAST_RESULT
    allow_retype = base in _INPLACE_RETYPES
    keep_shape = base not in _INPLACE_RESHAPES

    def method(self, *args, **kwargs):
        out = fn(self, *args, **kwargs)
        if keep_shape and tuple(out.shape) != tuple(self.shape):
            # reference parity: broadcasting may not grow the inplace
            # receiver (tensor/logic.py equal_ raises ValueError)
            raise ValueError(
                f"{base}_: broadcast output shape {tuple(out.shape)} "
                f"differs from the inplace tensor shape "
                f"{tuple(self.shape)}")
        return _inplace_from(self, out, cast_result=cast_result,
                             allow_retype=allow_retype)
    method.__name__ = base + "_"
    return method


def bind_tensor_methods(cls=Tensor):
    for dunder, fn in {**_BINARY_DUNDERS, **_REFLECTED}.items():
        setattr(cls, dunder, _make_method(fn))
    cls.__neg__ = _make_method(neg)
    cls.__abs__ = _make_method(math.abs)
    cls.__invert__ = _make_method(logical_not)
    cls.__getitem__ = _getitem
    cls.__setitem__ = _setitem
    for name, fn in _METHODS.items():
        if not hasattr(cls, name):
            setattr(cls, name, _make_method(fn))
    for base in _INPLACE_BASES:
        fn = _METHODS.get(base)
        if fn is not None and not hasattr(cls, base + "_"):
            setattr(cls, base + "_", _make_inplace(fn, base))

    def _t_property(self):
        # numpy-style full reverse (paddle Tensor.T semantics)
        return manipulation.transpose(self, list(range(self.ndim))[::-1])
    cls.T = property(_t_property)

    def _mT(self):
        return swapaxes(self, -1, -2)
    cls.mT = property(_mT)


bind_tensor_methods()


# Module-level inplace variants (`paddle.add_(x, y)` etc. — reference
# exports one `<op>_` wrapper per inplace-capable op from
# python/paddle/tensor/__init__.py). Generated from the out-of-place fns.
_MODULE_INPLACE_BASES = _INPLACE_BASES + [
    "addmm", "bitwise_and", "bitwise_left_shift", "bitwise_not",
    "bitwise_or", "bitwise_right_shift", "bitwise_xor", "copysign",
    "cumprod", "equal", "floor_mod", "gammainc", "gammaincc", "gammaln",
    "gcd", "greater_equal", "greater_than", "hypot", "i0", "index_add",
    "index_fill", "lcm", "ldexp", "less_equal", "less_than", "logical_and",
    "logical_not", "logical_or", "logical_xor", "masked_scatter",
    "multigammaln", "not_equal", "polygamma", "renorm", "sinc", "t",
    "transpose",
]


def where_(condition, x, y, name=None):
    """In-place `where`: writes the select result into ``x`` (the
    reference's inplace variant mutates x, NOT the condition —
    python/paddle/tensor/search.py where_)."""
    out = manipulation.where(condition, x, y)
    if tuple(out.shape) != tuple(x.shape):
        raise ValueError(
            f"where_: broadcast output shape {tuple(out.shape)} differs "
            f"from the inplace tensor shape {tuple(x.shape)}")
    return _inplace_from(x, out)


def _make_module_inplace(fn, iname):
    f = _make_inplace(fn, iname[:-1])
    f.__doc__ = f"In-place variant of `{fn.__name__}`."
    return f


def _bind_module_inplace():
    g = globals()
    for base in _MODULE_INPLACE_BASES:
        fn = g.get(base) or _METHODS.get(base)
        if fn is None:
            continue
        iname = base + "_"
        if iname not in g:
            g[iname] = _make_module_inplace(fn, iname)
        if not hasattr(Tensor, iname):
            setattr(Tensor, iname, _make_module_inplace(fn, iname))


_bind_module_inplace()
# Tensor method form keeps the reference receiver convention:
# `cond.where_(x, y)` selects into (and returns) x.
Tensor.where_ = where_


def _bind_reference_method_surface():
    """The reference attaches ~385 op wrappers as Tensor methods
    (python/paddle/tensor/__init__.py tensor_method_func). Bind every
    public op in this namespace whose name appears there and that is not
    already a method."""
    import re as _re

    ref = "/root/reference/python/paddle/tensor/__init__.py"
    try:
        src = open(ref).read()
    except OSError:
        return
    m = _re.search(r"tensor_method_func\s*=\s*\[(.*?)\]", src, _re.S)
    if not m:
        return
    names = set(_re.findall(r"['\"]([^'\"]+)['\"]", m.group(1)))
    g = globals()
    from . import special as _special
    for name in names:
        if hasattr(Tensor, name):
            continue
        fn = g.get(name) or getattr(_special, name, None)
        if fn is None:
            from .. import signal as _signal  # stft/istft ride along
            fn = getattr(_signal, name, None)
        if callable(fn):
            setattr(Tensor, name, _make_method(fn))
    # names living at the package root / compat layer
    from ..compat_toplevel import create_parameter, reverse

    def _is_tensor_m(self):
        return True

    def _create_tensor_m(self, *a, **k):
        return Tensor(self._data)
    if not hasattr(Tensor, "reverse"):
        Tensor.reverse = _make_method(reverse)
    if not hasattr(Tensor, "create_parameter"):
        Tensor.create_parameter = staticmethod(create_parameter)
    Tensor.is_tensor = _is_tensor_m
    Tensor.create_tensor = _create_tensor_m


_bind_reference_method_surface()


def inplace_from(t, out):
    return _inplace_from(t, out)
