"""Reduction ops (parity: reference `python/paddle/tensor/math.py` reductions +
`paddle/phi/kernels/funcs/reduce_function.h` machinery — XLA owns the
tiling/tree-reduction here)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import apply, unwrap
from ..core.dtype import convert_dtype

__all__ = [
    "sum", "mean", "max", "min", "amax", "amin", "prod", "all", "any",
    "count_nonzero", "median", "nanmedian", "nansum", "nanmean", "var", "std",
    "quantile", "nanquantile",
]


def _norm_axis(axis):
    if axis is None:
        return None
    axis = unwrap(axis)
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    dt = convert_dtype(dtype) if dtype else None
    return apply(lambda a: jnp.sum(a, axis=ax, dtype=dt, keepdims=keepdim),
                 x, name="sum")


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    dt = convert_dtype(dtype) if dtype else None
    return apply(lambda a: jnp.nansum(a, axis=ax, dtype=dt, keepdims=keepdim),
                 x, name="nansum")


def mean(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply(lambda a: jnp.mean(a, axis=ax, keepdims=keepdim),
                 x, name="mean")


def nanmean(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply(lambda a: jnp.nanmean(a, axis=ax, keepdims=keepdim),
                 x, name="nanmean")


def max(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply(lambda a: jnp.max(a, axis=ax, keepdims=keepdim), x,
                 name="max")


def min(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply(lambda a: jnp.min(a, axis=ax, keepdims=keepdim), x,
                 name="min")


amax = max
amin = min


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    ax = _norm_axis(axis)
    dt = convert_dtype(dtype) if dtype else None
    return apply(lambda a: jnp.prod(a, axis=ax, dtype=dt, keepdims=keepdim),
                 x, name="prod")


def all(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply(lambda a: jnp.all(a, axis=ax, keepdims=keepdim), x,
                 name="all")


def any(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply(lambda a: jnp.any(a, axis=ax, keepdims=keepdim), x,
                 name="any")


def count_nonzero(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply(lambda a: jnp.count_nonzero(a, axis=ax, keepdims=keepdim)
                 .astype(jnp.int64), x, name="count_nonzero")


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    ax = _norm_axis(axis)
    return apply(lambda a: jnp.median(a, axis=ax, keepdims=keepdim),
                 x, name="median")


def nanmedian(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply(lambda a: jnp.nanmedian(a, axis=ax, keepdims=keepdim),
                 x, name="nanmedian")


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _norm_axis(axis)
    ddof = 1 if unbiased else 0
    return apply(lambda a: jnp.var(a, axis=ax, ddof=ddof, keepdims=keepdim),
                 x, name="var")


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _norm_axis(axis)
    ddof = 1 if unbiased else 0
    return apply(lambda a: jnp.std(a, axis=ax, ddof=ddof, keepdims=keepdim),
                 x, name="std")


def quantile(x, q, axis=None, keepdim=False, interpolation="linear",
             name=None):
    ax = _norm_axis(axis)
    qv = unwrap(q)
    return apply(lambda a: jnp.quantile(a, jnp.asarray(qv), axis=ax,
                                        keepdims=keepdim,
                                        method=interpolation),
                 x, name="quantile")


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear",
                name=None):
    ax = _norm_axis(axis)
    qv = unwrap(q)
    return apply(lambda a: jnp.nanquantile(a, jnp.asarray(qv), axis=ax,
                                           keepdims=keepdim,
                                           method=interpolation),
                 x, name="nanquantile")
