"""Reduction ops (parity: reference `python/paddle/tensor/math.py` reductions +
`paddle/phi/kernels/funcs/reduce_function.h` machinery — XLA owns the
tiling/tree-reduction here)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import apply, unwrap
from ..core.dtype import convert_dtype

__all__ = [
    "sum", "mean", "max", "min", "amax", "amin", "prod", "all", "any",
    "count_nonzero", "median", "nanmedian", "nansum", "nanmean", "var", "std",
    "quantile", "nanquantile",
]


def _norm_axis(axis):
    if axis is None:
        return None
    axis = unwrap(axis)
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    dt = convert_dtype(dtype) if dtype else None
    return apply(lambda a: jnp.sum(a, axis=ax, dtype=dt, keepdims=keepdim),
                 x, name="sum")


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    dt = convert_dtype(dtype) if dtype else None
    return apply(lambda a: jnp.nansum(a, axis=ax, dtype=dt, keepdims=keepdim),
                 x, name="nansum")


def mean(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply(lambda a: jnp.mean(a, axis=ax, keepdims=keepdim),
                 x, name="mean")


def nanmean(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply(lambda a: jnp.nanmean(a, axis=ax, keepdims=keepdim),
                 x, name="nanmean")


def max(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply(lambda a: jnp.max(a, axis=ax, keepdims=keepdim), x,
                 name="max")


def min(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply(lambda a: jnp.min(a, axis=ax, keepdims=keepdim), x,
                 name="min")


amax = max
amin = min


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    ax = _norm_axis(axis)
    dt = convert_dtype(dtype) if dtype else None
    return apply(lambda a: jnp.prod(a, axis=ax, dtype=dt, keepdims=keepdim),
                 x, name="prod")


def all(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply(lambda a: jnp.all(a, axis=ax, keepdims=keepdim), x,
                 name="all")


def any(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply(lambda a: jnp.any(a, axis=ax, keepdims=keepdim), x,
                 name="any")


def count_nonzero(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply(lambda a: jnp.count_nonzero(a, axis=ax, keepdims=keepdim)
                 .astype(jnp.int64), x, name="count_nonzero")


def _median_min(a, ax, keepdim, nan_aware):
    """mode='min' median: the LOWER middle element at sorted position
    (n-1)//2 with its index along the axis (reference stat.py median:
    kth-1 for even sizes, kth for odd — both are (n-1)//2). Output
    keeps x's dtype; a NaN anywhere on the axis propagates NaN with the
    first NaN's index (nan_aware=False) or is skipped (nanmedian)."""
    ax = ax % a.ndim
    sz = a.shape[ax]
    order = jnp.argsort(a, axis=ax)  # stable; one sort, values gathered
    svals = jnp.take_along_axis(a, order, axis=ax)
    if nan_aware and jnp.issubdtype(a.dtype, jnp.floating):
        n_valid = jnp.sum(~jnp.isnan(a), axis=ax, keepdims=True)
        pos = jnp.clip((n_valid - 1) // 2, 0, sz - 1)
    else:
        pos = jnp.full([1] * a.ndim, (sz - 1) // 2, jnp.int32)
    val = jnp.take_along_axis(svals, pos, axis=ax)
    idx = jnp.take_along_axis(order, pos, axis=ax).astype(jnp.int64)
    if jnp.issubdtype(a.dtype, jnp.floating):
        isnan = jnp.isnan(a)
        if nan_aware:
            # all-NaN slice: value NaN, index -1 (the reference
            # nanmedian kernel's sentinel, nanmedian_kernel.cc:61)
            all_nan = jnp.all(isnan, axis=ax, keepdims=True)
            val = jnp.where(all_nan, jnp.nan, val)
            idx = jnp.where(all_nan, -1, idx)
        else:
            has_nan = jnp.any(isnan, axis=ax, keepdims=True)
            first_nan = jnp.argmax(isnan, axis=ax, keepdims=True)
            val = jnp.where(has_nan, jnp.nan, val)
            idx = jnp.where(has_nan, first_nan, idx)
    if not keepdim:
        val = jnp.squeeze(val, axis=ax)
        idx = jnp.squeeze(idx, axis=ax)
    return val, idx


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    """Reference paddle.median (stat.py:466): mode='avg' averages the
    two middles (float output); mode='min' takes the lower middle in
    x's dtype and, when axis is given, also returns its index."""
    if mode not in ("avg", "min"):
        raise ValueError(
            f"Mode {mode} is not supported. Must be avg or min.")
    ax = _norm_axis(axis)
    if mode == "min" and isinstance(ax, (list, tuple)):
        raise ValueError(
            "median with mode='min' requires a single int axis or None")
    if mode == "avg":
        return apply(lambda a: jnp.median(
            a, axis=ax, keepdims=keepdim).astype(
                jnp.float64 if a.dtype == jnp.float64 else jnp.float32),
            x, name="median")
    if ax is None:
        return apply(
            lambda a: _median_min(a.reshape(-1), 0, True,
                                  False)[0].reshape(
                [1] * (a.ndim if keepdim else 0)),
            x, name="median")
    return apply(lambda a: _median_min(a, ax, keepdim, False), x,
                 name="median")


def nanmedian(x, axis=None, keepdim=False, mode="avg", name=None):
    """Reference paddle.nanmedian: like median but NaNs are skipped;
    mode='min' with an axis returns (value, index)."""
    if mode not in ("avg", "min"):
        raise ValueError(
            f"Mode {mode} is not supported. Must be avg or min.")
    ax = _norm_axis(axis)
    if mode == "min" and isinstance(ax, (list, tuple)):
        raise ValueError(
            "nanmedian with mode='min' requires a single int axis or None")
    if mode == "avg":
        return apply(lambda a: jnp.nanmedian(
            a, axis=ax, keepdims=keepdim).astype(
                jnp.float64 if a.dtype == jnp.float64 else jnp.float32),
            x, name="nanmedian")
    if ax is None:
        return apply(
            lambda a: _median_min(a.reshape(-1), 0, True,
                                  True)[0].reshape(
                [1] * (a.ndim if keepdim else 0)),
            x, name="nanmedian")
    return apply(lambda a: _median_min(a, ax, keepdim, True), x,
                 name="nanmedian")


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _norm_axis(axis)
    ddof = 1 if unbiased else 0
    return apply(lambda a: jnp.var(a, axis=ax, ddof=ddof, keepdims=keepdim),
                 x, name="var")


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _norm_axis(axis)
    ddof = 1 if unbiased else 0
    return apply(lambda a: jnp.std(a, axis=ax, ddof=ddof, keepdims=keepdim),
                 x, name="std")


def quantile(x, q, axis=None, keepdim=False, interpolation="linear",
             name=None):
    ax = _norm_axis(axis)
    qv = unwrap(q)
    return apply(lambda a: jnp.quantile(a, jnp.asarray(qv), axis=ax,
                                        keepdims=keepdim,
                                        method=interpolation),
                 x, name="quantile")


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear",
                name=None):
    ax = _norm_axis(axis)
    qv = unwrap(q)
    return apply(lambda a: jnp.nanquantile(a, jnp.asarray(qv), axis=ax,
                                           keepdims=keepdim,
                                           method=interpolation),
                 x, name="nanquantile")
