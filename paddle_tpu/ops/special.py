"""Long-tail tensor ops closing the reference tensor-API gaps found by
tools/ops_audit.py.

Reference surface: `python/paddle/tensor/__init__.py` (math.py, linalg.py,
manipulation.py, einsum.py wrappers over `_C_ops`). Implementations are
jnp/jax.scipy compositions — XLA fuses them; none warrant Pallas.
"""

from __future__ import annotations

import itertools
import math as _pymath

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply, as_index, unwrap

__all__ = [
    "as_strided", "block_diag", "cartesian_prod", "cdist",
    "cholesky_inverse", "combinations", "diagonal_scatter", "floor_mod",
    "frexp", "gammainc", "gammaincc", "histogram_bin_edges",
    "householder_product", "i0e", "i1e", "is_integer", "isin", "isneginf",
    "isposinf", "isreal", "masked_scatter", "multigammaln", "multiplex",
    "ormqr", "pca_lowrank", "polygamma", "reduce_as", "select_scatter",
    "sinc", "slice_scatter", "svd_lowrank", "top_p_sampling",
]


def as_strided(x, shape, stride, offset=0, name=None):
    """Strided view (reference `stride/as_strided_kernel.cc`). XLA has no
    aliasing views, so this materializes the gather with the same
    element-mapping semantics."""
    shape = [int(s) for s in shape]
    stride = [int(s) for s in stride]

    def fn(a):
        flat = a.reshape(-1)
        idx = np.asarray(offset, np.int64)
        for dim, (n, st) in enumerate(zip(shape, stride)):
            ar = np.arange(n, dtype=np.int64) * st
            idx = np.expand_dims(idx, -1) + ar.reshape(
                (1,) * np.ndim(idx) + (n,))
        return flat[jnp.asarray(idx.reshape(shape), jnp.int32)]
    return apply(fn, x, name="as_strided")


def block_diag(inputs, name=None):
    def fn(*arrs):
        arrs = [a if a.ndim == 2 else jnp.atleast_2d(a) for a in arrs]
        return jax.scipy.linalg.block_diag(*arrs)
    return apply(fn, *inputs, name="block_diag")


def cartesian_prod(x, name=None):
    def fn(*arrs):
        grids = jnp.meshgrid(*arrs, indexing="ij")
        return jnp.stack([g.reshape(-1) for g in grids], axis=-1)
    return apply(fn, *x, name="cartesian_prod")


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    def fn(a, b):
        diff = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            return jnp.sqrt(jnp.maximum(
                jnp.sum(diff * diff, axis=-1), 0.0))
        if p == float("inf"):
            return jnp.max(jnp.abs(diff), axis=-1)
        if p == 0.0:
            return jnp.sum((diff != 0).astype(a.dtype), axis=-1)
        return jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)
    return apply(fn, x, y, name="cdist")


def cholesky_inverse(x, upper=False, name=None):
    def fn(l):
        n = l.shape[-1]
        eye = jnp.eye(n, dtype=l.dtype)
        inv = jax.scipy.linalg.cho_solve((l, not upper), eye)
        return inv
    return apply(fn, x, name="cholesky_inverse")


def combinations(x, r=2, with_replacement=False, name=None):
    def fn(a):
        n = a.shape[0]
        gen = itertools.combinations_with_replacement(range(n), r) \
            if with_replacement else itertools.combinations(range(n), r)
        idx = np.asarray(list(gen), np.int32).reshape(-1, r)
        return a[jnp.asarray(idx)]
    return apply(fn, x, name="combinations")


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    def fn(a, b):
        mask_np = np.zeros(a.shape, bool)
        rng = range(min(a.shape[axis1], a.shape[axis2]))
        it = np.arange(min(a.shape[axis1], a.shape[axis2]))
        i = it if offset >= 0 else it - offset
        j = it + offset if offset >= 0 else it
        keep = (i < a.shape[axis1]) & (j < a.shape[axis2]) & (i >= 0) & \
            (j >= 0)
        i, j = i[keep], j[keep]
        moved = jnp.moveaxis(jnp.moveaxis(a, axis1, 0), axis2, 1)
        upd = jnp.moveaxis(b, -1, 0)  # diag dim leads
        moved = moved.at[i, j].set(upd.astype(moved.dtype))
        return jnp.moveaxis(jnp.moveaxis(moved, 1, axis2), 0, axis1)
    return apply(fn, x, y, name="diagonal_scatter")


def floor_mod(x, y, name=None):
    from .math import mod
    return mod(x, y)


def frexp(x, name=None):
    return apply(lambda a: jnp.frexp(a), x, name="frexp")


def gammainc(x, y, name=None):
    return apply(lambda a, b: jax.scipy.special.gammainc(a, b), x, y,
                 name="gammainc")


def gammaincc(x, y, name=None):
    return apply(lambda a, b: jax.scipy.special.gammaincc(a, b), x, y,
                 name="gammaincc")


def histogram_bin_edges(input, bins=100, min=0.0, max=0.0, name=None):
    def fn(a):
        lo, hi = float(min), float(max)
        if lo == 0.0 and hi == 0.0:
            return jnp.histogram_bin_edges(a, bins=bins)
        return jnp.linspace(lo, hi, bins + 1, dtype=jnp.float32)
    return apply(fn, input, name="histogram_bin_edges")


def householder_product(x, tau, name=None):
    return apply(lambda a, t: jax.lax.linalg.householder_product(a, t),
                 x, tau, name="householder_product")


def i0e(x, name=None):
    return apply(lambda a: jax.scipy.special.i0e(a), x, name="i0e")


def i1e(x, name=None):
    return apply(lambda a: jax.scipy.special.i1e(a), x, name="i1e")


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return apply(lambda a, b: jnp.isin(a, b, invert=invert), x, test_x,
                 name="isin")


def isneginf(x, name=None):
    return apply(jnp.isneginf, x, name="isneginf")


def isposinf(x, name=None):
    return apply(jnp.isposinf, x, name="isposinf")


def isreal(x, name=None):
    return apply(jnp.isreal, x, name="isreal")


def masked_scatter(x, mask, value, name=None):
    """out[mask] = value[:mask.sum()] elementwise in row-major order
    (reference `masked_scatter` semantics). Static-shape friendly: the
    running count of True entries indexes into the flattened source."""
    def fn(a, m, v):
        m = jnp.broadcast_to(m, a.shape)
        flat_m = m.reshape(-1)
        pos = jnp.cumsum(flat_m.astype(jnp.int32)) - 1
        src = v.reshape(-1)
        take = jnp.clip(pos, 0, src.shape[0] - 1)
        out = jnp.where(flat_m, src[take].astype(a.dtype), a.reshape(-1))
        return out.reshape(a.shape)
    return apply(fn, x, mask, value, name="masked_scatter")


def multigammaln(x, p, name=None):
    return apply(lambda a: jax.scipy.special.multigammaln(a, int(p)), x,
                 name="multigammaln")


def multiplex(inputs, index, name=None):
    """out[i] = inputs[index[i]][i] (reference `multiplex` op)."""
    idx = as_index(unwrap(index)).reshape(-1)

    def fn(*arrs):
        stacked = jnp.stack(arrs, axis=0)  # [n, rows, ...]
        rows = jnp.arange(stacked.shape[1], dtype=jnp.int32)
        return stacked[idx[:stacked.shape[1]], rows]
    return apply(fn, *inputs, name="multiplex")


def ormqr(x, tau, other, left=True, transpose=False, name=None):
    """Multiply `other` by Q from a geqrf-style factorization of x.

    Q is the full m x m orthogonal matrix implied by the reflectors: pad
    the reflector block with identity columns (tau = 0) so
    householder_product yields full Q, matching LAPACK `ormqr`."""
    def fn(a, t, c):
        m, k = a.shape[-2], a.shape[-1]
        side = m if left else c.shape[-1]
        if k < side:
            pad_a = [(0, 0)] * (a.ndim - 1) + [(0, side - k)]
            pad_t = [(0, 0)] * (t.ndim - 1) + [(0, side - k)]
            a = jnp.pad(a, pad_a)
            t = jnp.pad(t, pad_t)
        q = jax.lax.linalg.householder_product(a, t)
        qm = jnp.swapaxes(q, -1, -2) if transpose else q
        return qm @ c if left else c @ qm
    return apply(fn, x, tau, other, name="ormqr")


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    def fn(a):
        m, n = a.shape[-2], a.shape[-1]
        k = q if q is not None else min(6, m, n)
        if center:
            a = a - jnp.mean(a, axis=-2, keepdims=True)
        u, s, vt = jnp.linalg.svd(a, full_matrices=False)
        return u[..., :k], s[..., :k], jnp.swapaxes(vt, -1, -2)[..., :k]
    return apply(fn, x, name="pca_lowrank")


def polygamma(x, n, name=None):
    def fn(a):
        return jax.scipy.special.polygamma(int(n), a)
    return apply(fn, x, name="polygamma")


def reduce_as(x, target, name=None):
    """Sum-reduce x down to target's (broadcastable) shape (reference
    `reduce_as` — the gradient-of-broadcast reduction)."""
    tgt_shape = list(target.shape)

    def fn(a):
        extra = a.ndim - len(tgt_shape)
        if extra > 0:
            a = jnp.sum(a, axis=tuple(range(extra)))
        axes = tuple(i for i, (s, t) in enumerate(zip(a.shape, tgt_shape))
                     if s != t and t == 1)
        if axes:
            a = jnp.sum(a, axis=axes, keepdims=True)
        return a
    return apply(fn, x, name="reduce_as")


def select_scatter(x, values, axis, index, name=None):
    def fn(a, v):
        idx = [slice(None)] * a.ndim
        idx[axis] = index
        return a.at[tuple(idx)].set(v.astype(a.dtype))
    return apply(fn, x, values, name="select_scatter")


def is_integer(x):
    from ..core import dtype as dtype_mod
    return dtype_mod.is_integer(x.dtype)


def sinc(x, name=None):
    return apply(jnp.sinc, x, name="sinc")


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    def fn(a, v):
        idx = [slice(None)] * a.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            idx[ax] = slice(int(st), int(en), int(sd))
        return a.at[tuple(idx)].set(v.astype(a.dtype))
    return apply(fn, x, value, name="slice_scatter")


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    def fn(a):
        b = a if M is None else a - unwrap(M)
        u, s, vt = jnp.linalg.svd(b, full_matrices=False)
        k = builtins_min(q, s.shape[-1])
        return u[..., :k], s[..., :k], jnp.swapaxes(vt, -1, -2)[..., :k]
    builtins_min = min
    return apply(fn, x, name="svd_lowrank")


def top_p_sampling(x, ps, threshold=None, topp_seed=None, seed=-1,
                   k=0, mode="truncated", return_top=False, name=None):
    """Nucleus sampling (reference `top_p_sampling` op): sample one token
    id per row from the smallest set of logits whose cumulative softmax
    probability exceeds `ps`."""
    from ..core.random import next_key
    key = next_key()

    def fn(logits, p):
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        sort_idx = jnp.argsort(-probs, axis=-1)
        sorted_p = jnp.take_along_axis(probs, sort_idx, axis=-1)
        cum = jnp.cumsum(sorted_p, axis=-1)
        keep = cum - sorted_p < p.reshape(-1, 1)
        filt = jnp.where(keep, sorted_p, 0.0)
        filt = filt / jnp.sum(filt, axis=-1, keepdims=True)
        choice = jax.random.categorical(key, jnp.log(
            jnp.maximum(filt, 1e-38)), axis=-1)
        ids = jnp.take_along_axis(
            sort_idx, choice[..., None], axis=-1).astype(jnp.int64)
        scores = jnp.take_along_axis(probs, ids, axis=-1)
        return ids, scores
    return apply(fn, x, ps, name="top_p_sampling")
