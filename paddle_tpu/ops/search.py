"""Search / sort / index ops (parity: reference
`python/paddle/tensor/search.py`). Dynamic-output-shape ops (nonzero, unique)
run eagerly on host — same restriction the reference's static/CINN path has.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply, unwrap
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor

__all__ = [
    "argmax", "argmin", "argsort", "sort", "topk", "nonzero", "kthvalue",
    "mode", "index_sample", "searchsorted", "bucketize", "unique",
    "unique_consecutive", "masked_select",
]


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    dt = convert_dtype(dtype)

    def _argmax(a):
        out = jnp.argmax(a, axis=axis, keepdims=keepdim if axis is not None
                         else False)
        return out.astype(dt)
    return apply(_argmax, x, name="argmax")


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    dt = convert_dtype(dtype)

    def _argmin(a):
        out = jnp.argmin(a, axis=axis, keepdims=keepdim if axis is not None
                         else False)
        return out.astype(dt)
    return apply(_argmin, x, name="argmin")


def argsort(x, axis=-1, descending=False, stable=True, name=None):
    def _argsort(a):
        out = jnp.argsort(a, axis=axis, stable=stable,
                          descending=descending)
        return out.astype(jnp.int64)
    return apply(_argsort, x, name="argsort")


def sort(x, axis=-1, descending=False, stable=True, name=None):
    def _sort(a):
        out = jnp.sort(a, axis=axis, stable=stable, descending=descending)
        return out
    return apply(_sort, x, name="sort")


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    k = int(unwrap(k))

    def _topk(a):
        ax = axis % a.ndim
        moved = jnp.moveaxis(a, ax, -1)
        if largest:
            vals, idx = jax.lax.top_k(moved, k)
        else:
            vals, idx = jax.lax.top_k(-moved, k)
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax),
                jnp.moveaxis(idx, -1, ax).astype(jnp.int64))
    out = apply(_topk, x, name="topk")
    return out[0], out[1]


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    k = int(unwrap(k))

    def _kth(a):
        ax = axis % a.ndim
        vals = jnp.sort(a, axis=ax)
        idx = jnp.argsort(a, axis=ax).astype(jnp.int64)
        v = jax.lax.index_in_dim(vals, k - 1, axis=ax, keepdims=keepdim)
        i = jax.lax.index_in_dim(idx, k - 1, axis=ax, keepdims=keepdim)
        return v, i
    out = apply(_kth, x, name="kthvalue")
    return out[0], out[1]


def mode(x, axis=-1, keepdim=False, name=None):
    a = np.asarray(unwrap(x))
    ax = axis % a.ndim
    moved = np.moveaxis(a, ax, -1)
    flat = moved.reshape(-1, moved.shape[-1])
    vals = np.empty(flat.shape[0], dtype=a.dtype)
    idxs = np.empty(flat.shape[0], dtype=np.int64)
    for i, row in enumerate(flat):
        uniq, counts = np.unique(row, return_counts=True)
        best = uniq[np.argmax(counts)]
        vals[i] = best
        idxs[i] = np.where(row == best)[0][-1]
    shape = moved.shape[:-1]
    vals = vals.reshape(shape)
    idxs = idxs.reshape(shape)
    if keepdim:
        vals = np.expand_dims(vals, ax)
        idxs = np.expand_dims(idxs, ax)
    return Tensor(jnp.asarray(vals)), Tensor(jnp.asarray(idxs))


def nonzero(x, as_tuple=False, name=None):
    a = np.asarray(unwrap(x))
    nz = np.nonzero(a)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(v.astype(np.int64))) for v in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=-1).astype(np.int64)))


def index_sample(x, index, name=None):
    idx = unwrap(index)
    return apply(lambda a: jnp.take_along_axis(a, idx, axis=1), x,
                 name="index_sample")


def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    seq = unwrap(sorted_sequence)
    side = "right" if right else "left"
    dt = jnp.int32 if out_int32 else jnp.int64

    def _ss(v):
        if seq.ndim == 1:
            return jnp.searchsorted(seq, v, side=side).astype(dt)
        flat_seq = seq.reshape(-1, seq.shape[-1])
        flat_v = v.reshape(-1, v.shape[-1])
        outs = jax.vmap(lambda s, q: jnp.searchsorted(s, q, side=side))(
            flat_seq, flat_v)
        return outs.reshape(v.shape).astype(dt)
    return apply(_ss, values, name="searchsorted")


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    a = np.asarray(unwrap(x))
    res = np.unique(a, return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    dt = convert_dtype(dtype)
    outs = [Tensor(jnp.asarray(res[0]))]
    for extra in res[1:]:
        outs.append(Tensor(jnp.asarray(extra.astype(np.dtype(dt)))))
    return tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, dtype="int64", name=None):
    a = np.asarray(unwrap(x))
    if axis is None:
        flat = a.reshape(-1)
        if flat.size == 0:
            keep = np.array([], dtype=bool)
        else:
            keep = np.concatenate([[True], flat[1:] != flat[:-1]])
        vals = flat[keep]
        outs = [Tensor(jnp.asarray(vals))]
        if return_inverse:
            inv = np.cumsum(keep) - 1
            outs.append(Tensor(jnp.asarray(inv.astype(np.int64))))
        if return_counts:
            idx = np.flatnonzero(keep)
            counts = np.diff(np.append(idx, flat.size))
            outs.append(Tensor(jnp.asarray(counts.astype(np.int64))))
        return outs[0] if len(outs) == 1 else tuple(outs)
    raise NotImplementedError("unique_consecutive with axis")


def masked_select(x, mask, name=None):
    from .manipulation import masked_select as _ms
    return _ms(x, mask)
