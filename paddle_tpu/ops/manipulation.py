"""Shape/layout manipulation ops.

Parity target: reference `python/paddle/tensor/manipulation.py` plus the
strided-view kernels (`paddle/phi/kernels/stride/`). On TPU there are no
strided views — XLA owns layout — so view-like ops are functional; the
`_inplace_from` rebinding in `ops/__init__.py` provides the in-place API
surface.
"""

from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply, unwrap
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor

__all__ = [
    "reshape", "transpose", "cast", "concat", "stack", "split", "chunk",
    "squeeze", "unsqueeze", "flatten", "tile", "expand", "expand_as",
    "broadcast_to", "broadcast_tensors", "flip", "rot90", "roll", "gather",
    "gather_nd", "scatter", "scatter_nd_add", "index_select", "index_add",
    "index_put", "masked_select", "masked_fill", "where", "slice",
    "strided_slice", "pad", "unbind", "unstack", "repeat_interleave",
    "take_along_axis", "put_along_axis", "moveaxis", "swapaxes", "as_real",
    "as_complex", "tensordot", "atleast_1d", "atleast_2d", "atleast_3d",
    "unflatten", "view", "view_as", "diagonal", "diag_embed", "crop",
    "shard_index", "tensor_split", "hsplit", "vsplit", "dsplit", "hstack",
    "vstack", "dstack", "column_stack", "row_stack", "numel", "rank",
    "shape", "t",
]


def reshape(x, shape, name=None):
    shape = _shape_arg(shape)
    return apply(lambda a: jnp.reshape(a, shape), x, name="reshape")


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return cast(x, shape_or_dtype)


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def transpose(x, perm=None, name=None):
    perm = _shape_arg(perm) if perm is not None else None
    return apply(lambda a: jnp.transpose(a, perm), x, name="transpose")


def t(x, name=None):
    def _t(a):
        if a.ndim < 2:
            return a
        return a.T
    return apply(_t, x, name="t")


def cast(x, dtype):
    dt = convert_dtype(dtype)
    return apply(lambda a: a.astype(dt), x, name="cast")


def concat(x, axis=0, name=None):
    axis = int(unwrap(axis))
    tensors = list(x)
    return apply(lambda *arrs: jnp.concatenate(arrs, axis=axis), *tensors,
                 name="concat")


def stack(x, axis=0, name=None):
    tensors = list(x)
    return apply(lambda *arrs: jnp.stack(arrs, axis=axis), *tensors,
                 name="stack")


def hstack(x, name=None):
    return apply(lambda *arrs: jnp.hstack(arrs), *list(x), name="hstack")


def vstack(x, name=None):
    return apply(lambda *arrs: jnp.vstack(arrs), *list(x), name="vstack")


def dstack(x, name=None):
    return apply(lambda *arrs: jnp.dstack(arrs), *list(x), name="dstack")


def column_stack(x, name=None):
    return apply(lambda *arrs: jnp.column_stack(arrs), *list(x),
                 name="column_stack")


row_stack = vstack


def split(x, num_or_sections, axis=0, name=None):
    axis = int(unwrap(axis))
    dim = x.shape[axis] if isinstance(x, Tensor) else x.shape[axis]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            raise ValueError(
                f"split: axis {axis} length {dim} is not divisible by "
                f"{num_or_sections}")
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(unwrap(s)) for s in num_or_sections]
        if -1 in sizes:
            known = builtins_sum(s for s in sizes if s != -1)
            sizes[sizes.index(-1)] = dim - known
    offsets = np.cumsum([0] + sizes[:-1])

    def _split(a):
        return tuple(
            jax.lax.slice_in_dim(a, int(o), int(o) + s, axis=axis)
            for o, s in zip(offsets, sizes))
    return apply(_split, x, name="split")


def builtins_sum(it):
    total = 0
    for v in it:
        total += v
    return total


def tensor_split(x, num_or_indices, axis=0, name=None):
    axis = int(axis)
    dim = x.shape[axis]
    if isinstance(num_or_indices, int):
        n = num_or_indices
        base, rem = divmod(dim, n)
        sizes = [base + (1 if i < rem else 0) for i in range(n)]
        return split(x, sizes, axis)
    indices = [0] + [int(unwrap(i)) for i in num_or_indices] + [dim]
    sizes = [b - a for a, b in zip(indices[:-1], indices[1:])]
    return split(x, sizes, axis)


def hsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=1 if x.ndim > 1 else 0)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def squeeze(x, axis=None, name=None):
    if axis is not None:
        axis = _shape_arg(axis) if isinstance(axis, (list, tuple)) else \
            (int(unwrap(axis)),)
        axis = tuple(a for a in axis if x.shape[a] == 1)
    return apply(lambda a: jnp.squeeze(a, axis=axis), x, name="squeeze")


def unsqueeze(x, axis, name=None):
    if isinstance(axis, (list, tuple)):
        axis = tuple(int(unwrap(a)) for a in axis)
    else:
        axis = (int(unwrap(axis)),)
    return apply(lambda a: jnp.expand_dims(a, axis=axis), x, name="unsqueeze")


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    nd = x.ndim
    s = start_axis % nd if nd else 0
    e = stop_axis % nd if nd else 0

    def _flatten(a):
        if a.ndim == 0:
            return a.reshape(1)
        new_shape = a.shape[:s] + (-1,) + a.shape[e + 1:]
        return a.reshape(new_shape)
    return apply(_flatten, x, name="flatten")


def unflatten(x, axis, shape, name=None):
    axis = axis % x.ndim
    shape = _shape_arg(shape)

    def _unflatten(a):
        new_shape = a.shape[:axis] + tuple(shape) + a.shape[axis + 1:]
        return a.reshape(new_shape)
    return apply(_unflatten, x, name="unflatten")


def tile(x, repeat_times, name=None):
    reps = _shape_arg(repeat_times)
    return apply(lambda a: jnp.tile(a, reps), x, name="tile")


def expand(x, shape, name=None):
    shape = _shape_arg(shape)
    def _expand(a):
        target = list(shape)
        # paddle semantics: -1 keeps the original dim
        offset = len(target) - a.ndim
        for i in range(len(target)):
            if target[i] == -1:
                target[i] = a.shape[i - offset] if i >= offset else 1
        return jnp.broadcast_to(a, tuple(target))
    return apply(_expand, x, name="expand")


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_to(x, shape, name=None):
    shape = _shape_arg(shape)
    return apply(lambda a: jnp.broadcast_to(a, tuple(shape)), x,
                 name="broadcast_to")


def broadcast_tensors(inputs, name=None):
    arrs = [unwrap(t) for t in inputs]
    shape = jnp.broadcast_shapes(*[a.shape for a in arrs])
    return [broadcast_to(t, shape) for t in inputs]


def flip(x, axis, name=None):
    if isinstance(axis, (list, tuple)):
        axis = tuple(int(a) for a in axis)
    return apply(lambda a: jnp.flip(a, axis=axis), x, name="flip")


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply(lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), x,
                 name="rot90")


def roll(x, shifts, axis=None, name=None):
    shifts = _shape_arg(shifts) if isinstance(shifts, (list, tuple)) else \
        int(unwrap(shifts))
    if isinstance(axis, (list, tuple)):
        axis = tuple(int(a) for a in axis)
    return apply(lambda a: jnp.roll(a, shifts, axis=axis), x, name="roll")


def gather(x, index, axis=0, name=None):
    axis = int(unwrap(axis))
    idx = unwrap(index)
    return apply(lambda a: jnp.take(a, idx.reshape(-1) if idx.ndim > 0
                                    else idx, axis=axis), x, name="gather")


def gather_nd(x, index, name=None):
    idx = unwrap(index)

    def _gather_nd(a):
        k = idx.shape[-1]
        flat_idx = tuple(jnp.moveaxis(idx, -1, 0))
        return a[flat_idx] if k == a.ndim else a[flat_idx + (Ellipsis,)]
    return apply(_gather_nd, x, name="gather_nd")


def scatter(x, index, updates, overwrite=True, name=None):
    idx = unwrap(index)

    def _scatter(a, u):
        if overwrite:
            return a.at[idx].set(u)
        zeroed = a.at[idx].set(jnp.zeros_like(u))
        return zeroed.at[idx].add(u)
    return apply(_scatter, x, updates, name="scatter")


def scatter_nd_add(x, index, updates, name=None):
    idx = unwrap(index)

    def _scatter_nd_add(a, u):
        flat_idx = tuple(jnp.moveaxis(idx, -1, 0))
        return a.at[flat_idx].add(u)
    return apply(_scatter_nd_add, x, updates, name="scatter_nd_add")


def index_select(x, index, axis=0, name=None):
    idx = unwrap(index)
    return apply(lambda a: jnp.take(a, idx, axis=axis), x,
                 name="index_select")


def index_add(x, index, axis, value, name=None):
    idx = unwrap(index)
    axis = axis % x.ndim

    def _index_add(a, v):
        moved = jnp.moveaxis(a, axis, 0)
        vmoved = jnp.moveaxis(v, axis, 0)
        out = moved.at[idx].add(vmoved)
        return jnp.moveaxis(out, 0, axis)
    return apply(_index_add, x, value, name="index_add")


def index_put(x, indices, value, accumulate=False, name=None):
    idx = tuple(unwrap(i) for i in indices)

    def _index_put(a, v):
        return a.at[idx].add(v) if accumulate else a.at[idx].set(v)
    return apply(_index_put, x, value, name="index_put")


def masked_select(x, mask, name=None):
    # Dynamic output shape: eager-only (documented; same restriction the
    # reference has under CINN/static shape inference). The selection
    # indices are computed host-side from the concrete mask; the gather
    # itself is a recorded op so gradients scatter back into x.
    a = unwrap(x)
    m = np.asarray(jax.device_get(unwrap(mask))).astype(bool)
    m = np.broadcast_to(m, a.shape)
    flat_idx = jnp.asarray(np.nonzero(m.reshape(-1))[0], jnp.int32)
    return apply(lambda v: jnp.take(v.reshape(-1), flat_idx), x,
                 name="masked_select")


def masked_fill(x, mask, value, name=None):
    m = unwrap(mask)
    return apply(lambda a, v: jnp.where(m, v.astype(a.dtype) if
                                        hasattr(v, "astype") else v, a),
                 x, value if isinstance(value, Tensor) else unwrap(value),
                 name="masked_fill")


def where(condition, x=None, y=None, name=None):
    cond = unwrap(condition)
    if x is None and y is None:
        nz = np.nonzero(np.asarray(cond))
        return Tensor(jnp.asarray(np.stack(nz, axis=-1).astype(np.int64)))
    return apply(lambda a, b: jnp.where(cond, a, b), x, y, name="where")


def slice(x, axes, starts, ends, name=None):
    axes = [int(a) for a in axes]
    starts = [int(unwrap(s)) for s in starts]
    ends = [int(unwrap(e)) for e in ends]

    def _slice(a):
        out = a
        for ax, s, e in zip(axes, starts, ends):
            dim = out.shape[ax]
            s_, e_ = _norm_range(s, e, dim)
            out = jax.lax.slice_in_dim(out, s_, e_, axis=ax)
        return out
    return apply(_slice, x, name="slice")


def strided_slice(x, axes, starts, ends, strides, name=None):
    def _ss(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = builtins.slice(int(unwrap(s)), int(unwrap(e)),
                                     int(unwrap(st)))
        return a[tuple(idx)]
    return apply(_ss, x, name="strided_slice")


def _norm_range(s, e, dim):
    if s < 0:
        s += dim
    if e < 0:
        e += dim
    return max(0, min(s, dim)), max(0, min(e, dim))


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    if isinstance(pad, int):
        # int padding means the SPATIAL dims only (reference Pad1D/2D/3D
        # expand an int via _npairs to the partial spatial spec) — the
        # full-rank expansion would also pad batch/channel
        n_spatial = x.ndim - 2 if 3 <= x.ndim <= 5 else x.ndim
        pad = [int(pad)] * (2 * n_spatial)
    else:
        pad = [int(unwrap(p)) for p in pad]

    def _pad(a):
        nd = a.ndim
        if len(pad) == 2 * nd:
            # full-rank paddle order: (before_0, after_0, before_1, ...)
            # paddle actually uses per-dim pairs in *reverse* only for the
            # NCHW conv helper; plain paddle.nn.functional.pad with len==2*nd
            # applies to all dims in order.
            widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            # partial spec applies to SPATIAL dims, innermost first
            # (pad_left/right = W, then H, then D — reference pad3d
            # dispatch in nn/functional/common.py): for channel-last
            # layouts the last spatial dim is nd-2, not nd-1.
            n = len(pad) // 2
            widths = [(0, 0)] * nd
            channel_last = data_format.upper() in ("NLC", "NHWC", "NDHWC")
            last_spatial = nd - 2 if channel_last else nd - 1
            for i in range(n):
                dim = last_spatial - i
                widths[dim] = (pad[2 * i], pad[2 * i + 1])
        jmode = {"constant": "constant", "reflect": "reflect",
                 "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(a, widths, mode=jmode, constant_values=value)
        return jnp.pad(a, widths, mode=jmode)
    return apply(_pad, x, name="pad")


def unbind(x, axis=0, name=None):
    n = x.shape[axis]

    def _unbind(a):
        return tuple(jnp.squeeze(s, axis=axis) for s in
                     jnp.split(a, n, axis=axis))
    return apply(_unbind, x, name="unbind")


unstack = unbind


def repeat_interleave(x, repeats, axis=None, name=None):
    reps = unwrap(repeats)
    return apply(lambda a: jnp.repeat(a, reps, axis=axis), x,
                 name="repeat_interleave")


def take_along_axis(x, indices, axis, broadcast=True, name=None):
    idx = unwrap(indices)
    return apply(lambda a: jnp.take_along_axis(a, idx, axis=axis), x,
                 name="take_along_axis")


def put_along_axis(x, indices, values, axis, reduce="assign", name=None):
    idx = unwrap(indices)

    def _put(a, v):
        v = jnp.broadcast_to(v, idx.shape) if jnp.ndim(v) else \
            jnp.full(idx.shape, v, a.dtype)
        dims = list(range(a.ndim))
        dims.remove(axis % a.ndim)
        full_idx = []
        for d in range(a.ndim):
            if d == axis % a.ndim:
                full_idx.append(idx)
            else:
                shape = [1] * a.ndim
                shape[d] = a.shape[d]
                full_idx.append(jnp.broadcast_to(
                    jnp.arange(a.shape[d]).reshape(shape), idx.shape))
        full_idx = tuple(full_idx)
        if reduce == "assign":
            return a.at[full_idx].set(v)
        if reduce == "add":
            return a.at[full_idx].add(v)
        if reduce == "multiply" or reduce == "mul":
            return a.at[full_idx].multiply(v)
        raise ValueError(f"unknown reduce {reduce}")
    return apply(_put, x, values, name="put_along_axis")


def moveaxis(x, source, destination, name=None):
    return apply(lambda a: jnp.moveaxis(a, source, destination), x,
                 name="moveaxis")


def swapaxes(x, axis0, axis1, name=None):
    return apply(lambda a: jnp.swapaxes(a, axis0, axis1), x, name="swapaxes")


def as_real(x, name=None):
    def _as_real(a):
        return jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1)
    return apply(_as_real, x, name="as_real")


def as_complex(x, name=None):
    return apply(lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x,
                 name="as_complex")


def tensordot(x, y, axes=2, name=None):
    ax = axes
    if isinstance(axes, (list, tuple)):
        ax = tuple(tuple(int(i) for i in (a if isinstance(a, (list, tuple))
                                          else [a])) for a in axes)
    return apply(lambda a, b: jnp.tensordot(a, b, axes=ax), x, y,
                 name="tensordot")


def atleast_1d(*inputs):
    outs = [apply(jnp.atleast_1d, t, name="atleast_1d") for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs):
    outs = [apply(jnp.atleast_2d, t, name="atleast_2d") for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs):
    outs = [apply(jnp.atleast_3d, t, name="atleast_3d") for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda a: jnp.diagonal(a, offset=offset, axis1=axis1,
                                        axis2=axis2), x, name="diagonal")


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    def _diag_embed(a):
        n = a.shape[-1] + abs(offset)
        base = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        i = jnp.arange(a.shape[-1])
        rows = i + max(-offset, 0)
        cols = i + max(offset, 0)
        out = base.at[..., rows, cols].set(a)
        if (dim1, dim2) != (-2, -1):
            out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
        return out
    return apply(_diag_embed, x, name="diag_embed")


def crop(x, shape=None, offsets=None, name=None):
    shape = _shape_arg(shape)
    offsets = [int(unwrap(o)) for o in offsets] if offsets is not None else \
        [0] * x.ndim

    def _crop(a):
        target = [a.shape[i] if shape[i] in (-1, None) else shape[i]
                  for i in range(a.ndim)]
        return jax.lax.dynamic_slice(a, offsets, target)
    return apply(_crop, x, name="crop")


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    size = index_num // nshards

    def _shard(a):
        shard = a // size
        return jnp.where(shard == shard_id, a % size, ignore_value)
    return apply(_shard, input, name="shard_index")


def numel(x, name=None):
    return Tensor(jnp.asarray(int(np.prod(x.shape)) if x.shape else 1,
                              dtype=jnp.int64))


def rank(x):
    return Tensor(jnp.asarray(x.ndim, dtype=jnp.int32))


def shape(x):
    return Tensor(jnp.asarray(x.shape, dtype=jnp.int32))


def _shape_arg(shape):
    if shape is None:
        return None
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in np.asarray(shape._data))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(unwrap(s)) for s in shape)
