"""Linear algebra ops (parity: reference `python/paddle/tensor/linalg.py`).
Decompositions lower to jax.numpy.linalg / lax.linalg (XLA custom calls on
TPU)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import apply, unwrap
from .math import matmul, mm_precision  # re-export home is linalg in paddle

__all__ = [
    "matmul", "dot", "bmm", "mm", "mv", "norm", "vector_norm", "matrix_norm",
    "dist", "cross", "cholesky", "cholesky_solve", "qr", "svd", "svdvals",
    "eig", "eigh", "eigvals", "eigvalsh", "inv", "pinv", "solve",
    "triangular_solve", "lstsq", "matrix_power", "det", "slogdet",
    "multi_dot", "matrix_rank", "cov", "corrcoef", "histogram",
    "histogramdd", "lu", "lu_unpack", "trace", "cond", "matrix_exp",
    "cholesky_inverse", "householder_product", "ormqr", "pca_lowrank",
    "svd_lowrank", "fp8_fp8_half_gemm_fused",
]


def dot(x, y, name=None):
    def _dot(a, b):
        if a.ndim == 1:
            return jnp.dot(a, b)
        return jnp.sum(a * b, axis=-1)
    return apply(_dot, x, y, name="dot")


def bmm(x, y, name=None):
    return apply(lambda a, b: jnp.matmul(
        a, b, precision=mm_precision(a.dtype, b.dtype)), x, y, name="bmm")


def mm(x, y, name=None):
    return bmm(x, y)


def mv(x, y, name=None):
    return bmm(x, y)


def norm(x, p=None, axis=None, keepdim=False, name=None):
    def _norm(a):
        if p is None or p == "fro":
            if axis is None:
                return jnp.sqrt(jnp.sum(jnp.square(a)))
            return jnp.linalg.norm(a, ord=None, axis=_ax(axis),
                                   keepdims=keepdim)
        if p == "nuc":
            return jnp.linalg.norm(a, ord="nuc", axis=_ax(axis),
                                   keepdims=keepdim)
        if p == float("inf") or p == float("-inf") or isinstance(p, (int,
                                                                     float)):
            if axis is None:
                flat = a.reshape(-1)
                return jnp.linalg.norm(flat, ord=p, keepdims=False)
            return jnp.linalg.norm(a, ord=p, axis=_ax(axis),
                                   keepdims=keepdim)
        raise ValueError(f"unsupported norm order {p}")
    return apply(_norm, x, name="norm")


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    def _vn(a):
        return jnp.linalg.vector_norm(a, ord=p, axis=_ax(axis),
                                      keepdims=keepdim)
    return apply(_vn, x, name="vector_norm")


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    def _mn(a):
        return jnp.linalg.matrix_norm(a, ord=p, keepdims=keepdim)
    return apply(_mn, x, name="matrix_norm")


def _ax(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def dist(x, y, p=2, name=None):
    return apply(lambda a, b: jnp.linalg.norm((a - b).reshape(-1), ord=p),
                 x, y, name="dist")


def cross(x, y, axis=9, name=None):
    def _cross(a, b):
        ax = axis
        if ax == 9:  # paddle default: first axis of size 3
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)
    return apply(_cross, x, y, name="cross")


def cholesky(x, upper=False, name=None):
    def _chol(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2).conj() if upper else L
    return apply(_chol, x, name="cholesky")


def cholesky_solve(x, y, upper=False, name=None):
    def _chs(b, chol):
        return jax.scipy.linalg.cho_solve((chol, not upper), b)
    return apply(_chs, x, y, name="cholesky_solve")


def qr(x, mode="reduced", name=None):
    if mode == "r":
        return apply(lambda a: jnp.linalg.qr(a, mode="r"), x, name="qr")
    out = apply(lambda a: tuple(jnp.linalg.qr(a, mode=mode)), x, name="qr")
    return out[0], out[1]


def svd(x, full_matrices=False, name=None):
    out = apply(lambda a: tuple(jnp.linalg.svd(
        a, full_matrices=full_matrices)), x, name="svd")
    return out[0], out[1], out[2]


def svdvals(x, name=None):
    return apply(lambda a: jnp.linalg.svd(a, compute_uv=False), x,
                 name="svdvals")


def eig(x, name=None):
    # jnp.linalg.eig is CPU-only; run on host (reference uses LAPACK too).
    import numpy as np
    a = np.asarray(unwrap(x))
    w, v = np.linalg.eig(a)
    from ..core.tensor import Tensor
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigvals(x, name=None):
    import numpy as np
    a = np.asarray(unwrap(x))
    from ..core.tensor import Tensor
    return Tensor(jnp.asarray(np.linalg.eigvals(a)))


def eigh(x, UPLO="L", name=None):
    out = apply(lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)), x,
                name="eigh")
    return out[0], out[1]


def eigvalsh(x, UPLO="L", name=None):
    return apply(lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), x,
                 name="eigvalsh")


def inv(x, name=None):
    return apply(jnp.linalg.inv, x, name="inv")


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply(lambda a: jnp.linalg.pinv(a, rtol=rcond,
                                           hermitian=hermitian),
                 x, name="pinv")


def solve(x, y, name=None):
    return apply(jnp.linalg.solve, x, y, name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    def _ts(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return apply(_ts, x, y, name="triangular_solve")


def lstsq(x, y, rcond=None, driver=None, name=None):
    def _lstsq(a, b):
        sol, res, rank_, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank_.astype(jnp.int64), sv
    out = apply(_lstsq, x, y, name="lstsq")
    return tuple(out)


def matrix_power(x, n, name=None):
    return apply(lambda a: jnp.linalg.matrix_power(a, n), x,
                 name="matrix_power")


def det(x, name=None):
    return apply(jnp.linalg.det, x, name="det")


def slogdet(x, name=None):
    out = apply(lambda a: tuple(jnp.linalg.slogdet(a)), x, name="slogdet")
    return out[0], out[1]


def multi_dot(x, name=None):
    return apply(lambda *arrs: jnp.linalg.multi_dot(arrs), *list(x),
                 name="multi_dot")


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply(lambda a: jnp.linalg.matrix_rank(a, rtol=tol)
                 .astype(jnp.int64), x, name="matrix_rank")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    fw = unwrap(fweights)
    aw = unwrap(aweights)
    return apply(lambda a: jnp.cov(a, rowvar=rowvar,
                                   ddof=1 if ddof else 0,
                                   fweights=fw, aweights=aw), x, name="cov")


def corrcoef(x, rowvar=True, name=None):
    return apply(lambda a: jnp.corrcoef(a, rowvar=rowvar), x,
                 name="corrcoef")


def histogram(input, bins=100, min=0, max=0, weight=None, density=False,
              name=None):
    a = unwrap(input)
    w = unwrap(weight)
    lo, hi = float(unwrap(min)), float(unwrap(max))
    if lo == 0 and hi == 0:
        lo, hi = float(jnp.min(a)), float(jnp.max(a))
        if lo == hi:
            lo, hi = lo - 0.5, hi + 0.5
    hist, _ = jnp.histogram(a.reshape(-1), bins=bins, range=(lo, hi),
                            weights=w, density=density)
    from ..core.tensor import Tensor
    return Tensor(hist if density or w is not None else
                  hist.astype(jnp.int64))


def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    import numpy as np
    a = np.asarray(unwrap(x))
    w = np.asarray(unwrap(weights)) if weights is not None else None
    hist, edges = np.histogramdd(a, bins=bins, range=ranges,
                                 density=density, weights=w)
    from ..core.tensor import Tensor
    return (Tensor(jnp.asarray(hist)),
            [Tensor(jnp.asarray(e)) for e in edges])


def lu(x, pivot=True, get_infos=False, name=None):
    def _lu(a):
        lu_mat, piv = jax.scipy.linalg.lu_factor(a)
        return lu_mat, (piv + 1).astype(jnp.int32)
    out = apply(_lu, x, name="lu")
    from ..core.tensor import Tensor
    if get_infos:
        info = Tensor(jnp.zeros((), jnp.int32))
        return out[0], out[1], info
    return out[0], out[1]


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True,
              name=None):
    def _unpack(lu_mat):
        m, n = lu_mat.shape[-2:]
        k = min(m, n)
        L = jnp.tril(lu_mat[..., :, :k], -1) + jnp.eye(m, k, dtype=lu_mat.dtype)
        U = jnp.triu(lu_mat[..., :k, :])
        return L, U
    piv = unwrap(lu_pivots)
    out = apply(_unpack, lu_data, name="lu_unpack")
    import numpy as np
    p = np.asarray(piv) - 1
    m = unwrap(lu_data).shape[-2]
    perm = np.arange(m)
    for i, pv in enumerate(p.reshape(-1)):
        perm[[i, pv]] = perm[[pv, i]]
    P = np.zeros((m, m), dtype=np.float32)
    P[perm, np.arange(m)] = 1.0
    from ..core.tensor import Tensor
    return Tensor(jnp.asarray(P)), out[0], out[1]


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda a: jnp.trace(a, offset=offset, axis1=axis1,
                                     axis2=axis2), x, name="trace")


def cond(x, p=None, name=None):
    return apply(lambda a: jnp.linalg.cond(a, p=p), x, name="cond")


def matrix_exp(x, name=None):
    """Matrix exponential (reference paddle.linalg.matrix_exp)."""
    import jax

    from ..core.dispatch import apply as _apply
    return _apply(lambda a: jax.scipy.linalg.expm(a), x, name="matrix_exp")


# long-tail entries shared with paddle.* (ops/special.py)
from .special import (  # noqa: E402,F401
    cholesky_inverse, householder_product, ormqr, pca_lowrank, svd_lowrank,
)


def fp8_fp8_half_gemm_fused(x, y, bias=None, transpose_x=False,
                            transpose_y=False, scale=1.0,
                            output_dtype="bfloat16", name=None):
    """fp8 x fp8 -> bf16 GEMM (reference fusion/fp8_gemm cutlass kernel).
    TPU path: fp8 operands feed dot_general with bf16 accumulation —
    the MXU consumes fp8 natively on v5p+/v6; elsewhere XLA upconverts."""
    import jax.numpy as jnp

    from ..core.dispatch import apply as _apply

    def fn(a, b, *mb):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = jnp.matmul(a, b, preferred_element_type=jnp.float32) * scale
        if mb:
            out = out + mb[0].astype(out.dtype)
        return out.astype(output_dtype)
    args = [x, y] + ([bias] if bias is not None else [])
    return _apply(fn, *args, name="fp8_gemm")
