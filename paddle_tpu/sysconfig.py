"""`paddle.sysconfig` (reference python/paddle/sysconfig.py): include /
lib directories for building native extensions against the framework —
here the XLA-FFI custom-op headers (csrc/include/paddle_ext.h) and the
package's shared libraries."""

from __future__ import annotations

import os

__all__ = ["get_include", "get_lib"]

_PKG = os.path.dirname(os.path.abspath(__file__))


def get_include() -> str:
    """Directory containing the C++ headers (PD_BUILD_OP /
    paddle_ext.h — the custom-op build contract)."""
    return os.path.join(_PKG, "csrc", "include")


def get_lib() -> str:
    """Directory containing the framework's native shared libraries."""
    return os.path.join(_PKG, "csrc", "_build")
