"""`paddle.onnx` export surface (reference: python/paddle/onnx/export.py
delegates to the external paddle2onnx package).

TPU-native path: a jitted model already lowers to StableHLO, which is the
supported interchange format (`export_stablehlo`); ONNX conversion from
StableHLO is an external-tool concern exactly as in the reference.
"""

from __future__ import annotations

__all__ = ["export", "export_stablehlo"]


def export_stablehlo(layer, input_spec, path=None):
    """Lower the layer to StableHLO text (the XLA-world ONNX)."""
    import jax
    import numpy as np

    from ..core.autograd import no_grad
    from ..core.tensor import Tensor

    examples = []
    for spec in input_spec:
        shape = [1 if s is None else s for s in spec.shape]
        examples.append(np.zeros(shape, np.dtype(str(np.dtype(
            spec.dtype.name if hasattr(spec.dtype, "name")
            else spec.dtype)))))

    def fn(*arrays):
        with no_grad():
            out = layer(*[Tensor(a) for a in arrays])
        return out._data if isinstance(out, Tensor) else out

    lowered = jax.jit(fn).lower(*examples)
    text = lowered.as_text()
    if path:
        with open(path, "w") as f:
            f.write(text)
    return text


def export(layer, path, input_spec=None, opset_version=9, **configs):
    raise NotImplementedError(
        "ONNX export requires an external converter in the reference too "
        "(paddle2onnx); paddle_tpu exports StableHLO instead: "
        "paddle_tpu.onnx.export_stablehlo(layer, input_spec, path)")
