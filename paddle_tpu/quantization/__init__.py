"""`paddle.quantization` (reference: python/paddle/quantization/ — QAT/PTQ
framework: QuantConfig, fake quanters, observers, QAT.quantize/convert,
PTQ calibration — qat.py, ptq.py, factory.py, observers/, quanters/).

TPU-first: int8 fake-quant simulates on-device quantization with a
straight-through estimator; the real int8 path on TPU is XLA's native
int8 matmul (v5e doubles int8 peak), so `convert` keeps weights int8
(per-channel scales) and dequantizes at the op edge.

Flows (mirroring the reference drivers):
- QAT:  q = QAT(cfg); qm = q.quantize(model)  -> fake-quant training
        dm = q.convert(qm)                    -> int8 deployment form
- PTQ:  p = PTQ(cfg); om = p.quantize(model)  -> observers inserted
        run calibration batches through om
        dm = p.convert(om)                    -> int8 deployment form
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import nn
from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["QuantConfig", "QAT", "PTQ", "FakeQuanterWithAbsMaxObserver",
           "BaseObserver", "BaseQuanter", "quanter",
           "AbsmaxObserver", "AbsMaxChannelWiseWeightObserver",
           "PercentileObserver", "quanted_linear",
           "QMAX_INT8", "absmax_row_scales", "quantize_rows",
           "dequantize_rows"]


# ---------------------------------------------------------------------------
# int8 row quantization primitives (jit-safe, no module state)
#
# The AbsmaxObserver formula (scale = absmax / qmax) vectorized over the
# last axis: one scale per leading-index "row". This is the math the
# serving KV-cache tier reuses (inference/paged.py,
# FLAGS_kv_cache_dtype=int8 — one scale per (token-slot, kv-head) row
# beside the int8 block pool; docs/PERF.md "Decode speed tiers").
# ---------------------------------------------------------------------------

QMAX_INT8 = 127.0
_SCALE_FLOOR = 1e-8  # an all-zero row quantizes (and dequantizes) to 0


def absmax_row_scales(x, qmax=QMAX_INT8):
    """Per-row absmax scales over the LAST axis of ``x`` — shape
    ``x.shape[:-1]`` float32. Scale floor keeps all-zero rows finite."""
    a = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    return jnp.maximum(a / qmax, _SCALE_FLOOR)


def quantize_rows(x, qmax=QMAX_INT8):
    """-> (int8 array of ``x.shape``, float32 scales of
    ``x.shape[:-1]``): symmetric per-row absmax quantization, the
    round-trip error bounded by ``scale / 2`` per element
    (tests/framework/test_quantization.py pins the bound)."""
    s = absmax_row_scales(x, qmax)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / s[..., None]),
                 -qmax, qmax).astype(jnp.int8)
    return q, s


def dequantize_rows(q, scales, dtype=jnp.float32):
    """Inverse of :func:`quantize_rows` (``scales`` broadcast over the
    last axis); returns ``dtype``."""
    return (q.astype(jnp.float32) * scales[..., None]).astype(dtype)


# ---------------------------------------------------------------------------
# observers / quanters
# ---------------------------------------------------------------------------

class BaseObserver(nn.Layer):
    """Observer base (reference quantization/factory.py BaseObserver):
    collects statistics in forward, yields scales for quantization."""

    quant_bits = 8

    def forward(self, x):
        return x

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        return None

    @property
    def qmax(self):
        return float(2 ** (self.quant_bits - 1) - 1)


class BaseQuanter(BaseObserver):
    """Quanter base (reference BaseQuanter): fake-quantizes in forward."""


class AbsmaxObserver(BaseObserver):
    """PTQ activation observer collecting absmax over calibration batches
    (reference observers/abs_max.py)."""

    def __init__(self, quant_bits=8):
        super().__init__()
        self.quant_bits = quant_bits
        self.absmax = 0.0

    def forward(self, x):
        self.absmax = max(self.absmax, float(jnp.max(jnp.abs(x._data))))
        return x

    def scales(self):
        if self.absmax == 0.0:
            raise RuntimeError(
                "AbsmaxObserver never saw data: run calibration batches "
                "through the PTQ-quantized model before convert()")
        return self.absmax / self.qmax

    scale = scales  # round-2 compat alias


class PercentileObserver(BaseObserver):
    """Percentile activation observer (reference observers/hist.py-style
    clipping): keeps a sample of |x| and clips at the q-th percentile,
    robust to outlier activations."""

    def __init__(self, quant_bits=8, percentile=99.9, sample_size=4096):
        super().__init__()
        self.quant_bits = quant_bits
        self.percentile = percentile
        self.sample_size = sample_size
        self._samples = []

    def forward(self, x):
        a = np.abs(np.asarray(x._data, np.float32)).reshape(-1)
        if a.size > self.sample_size:
            idx = np.random.default_rng(0).choice(a.size, self.sample_size,
                                                  replace=False)
            a = a[idx]
        self._samples.append(a)
        return x

    def scales(self):
        if not self._samples:
            raise RuntimeError(
                "PercentileObserver never saw data: run calibration "
                "batches through the PTQ-quantized model before convert()")
        allv = np.concatenate(self._samples)
        return max(float(np.percentile(allv, self.percentile)),
                   1e-9) / self.qmax


class AbsMaxChannelWiseWeightObserver(BaseObserver):
    """Per-output-channel weight scales (reference
    observers/channel_wise_abs_max.py) — int8 weights keep one scale per
    output channel, the accuracy-critical choice for conv/linear."""

    def __init__(self, quant_bits=8):
        super().__init__()
        self.quant_bits = quant_bits
        self._scales = None

    def observe_weight(self, w, channel_axis):
        axes = tuple(i for i in range(w.ndim) if i != channel_axis)
        s = jnp.max(jnp.abs(w), axis=axes) / self.qmax
        self._scales = jnp.maximum(s, 1e-9)
        return self._scales

    def scales(self):
        return self._scales


class FakeQuanterWithAbsMaxObserver(BaseQuanter):
    """Fake quantization with a moving-average absmax observer (reference
    quanters/abs_max.py FakeQuanterWithAbsMaxObserver)."""

    def __init__(self, moving_rate=0.9, bit_length=8, dtype="float32",
                 name=None):
        super().__init__()
        self.moving_rate = moving_rate
        self.bit_length = bit_length
        self.quant_bits = bit_length
        self.register_buffer("scale",
                             Tensor(jnp.ones([], jnp.float32)))
        # persisted as a buffer: a QAT model restored from a checkpoint
        # must keep its trained scale valid for convert() without having
        # to run another batch first
        self.register_buffer("accum_state",
                             Tensor(jnp.zeros([], jnp.float32)))

    @property
    def _initialized(self):
        return bool(float(self.accum_state._data) != 0.0)

    @_initialized.setter
    def _initialized(self, v):
        self.accum_state._rebind(jnp.asarray(1.0 if v else 0.0, jnp.float32))

    def forward(self, x):
        qmax = float(2 ** (self.bit_length - 1) - 1)
        if self.training:
            cur = float(jnp.max(jnp.abs(x._data)))
            if not self._initialized:
                new_scale = cur
                self._initialized = True
            else:
                new_scale = (self.moving_rate * float(self.scale._data) +
                             (1 - self.moving_rate) * cur)
            self.scale._rebind(jnp.asarray(new_scale, jnp.float32))
        s = jnp.maximum(jnp.asarray(float(self.scale._data)), 1e-9)

        def fq_ste(a):
            # straight-through estimator: rounding is identity in grad
            q = jnp.clip(jnp.round(a / s * qmax), -qmax, qmax)
            deq = q * s / qmax
            return a + jax.lax.stop_gradient(deq - a)

        return apply(fq_ste, x, name="fake_quant")

    def scales(self):
        if not self._initialized:
            # Never saw data: the init value 1.0 is not a real scale. Return
            # None so QAT.convert() skips static activation quant instead of
            # baking act_scale=1/qmax (which would clip deployed activations
            # to roughly [-1, 1]).
            return None
        return float(self.scale._data) / self.qmax


_QUANTER_REGISTRY = {}


def quanter(name):
    """Class decorator registering a quanter under a config name
    (reference quantization/factory.py quanter)."""

    def wrap(cls):
        _QUANTER_REGISTRY[name] = cls
        return cls
    return wrap


quanter("FakeQuanterWithAbsMaxObserver")(FakeQuanterWithAbsMaxObserver)
quanter("AbsmaxObserver")(AbsmaxObserver)
quanter("PercentileObserver")(PercentileObserver)


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------

class QuantConfig:
    """reference config.py QuantConfig: maps layer types/instances to
    quanter factories. Factories may be classes, callables, or registered
    names (strings)."""

    def __init__(self, activation=None, weight=None):
        self.activation = self._resolve(activation)
        self.weight = self._resolve(weight)
        self._type_configs = {}

    @staticmethod
    def _resolve(q):
        if isinstance(q, str):
            return _QUANTER_REGISTRY[q]
        return q

    def add_type_config(self, layer_type, activation=None, weight=None):
        for t in (layer_type if isinstance(layer_type, (list, tuple))
                  else [layer_type]):
            self._type_configs[t] = (self._resolve(activation),
                                     self._resolve(weight))

    def _quanters_for(self, layer):
        for t, (a, w) in self._type_configs.items():
            if isinstance(layer, t):
                return a, w
        return self.activation, self.weight


# ---------------------------------------------------------------------------
# QAT forms (fake-quant training)
# ---------------------------------------------------------------------------

def _instantiate(q):
    return q() if callable(q) and not isinstance(q, nn.Layer) else q


class QuantedLinear(nn.Layer):
    """Linear with fake-quantized activations and weights (QAT form,
    reference nn/quant_layers QuantizedLinear)."""

    def __init__(self, linear, a_quanter, w_quanter):
        super().__init__()
        self.weight = linear.weight
        self.bias = linear.bias
        self.a_quanter = _instantiate(a_quanter)
        self.w_quanter = _instantiate(w_quanter)

    def forward(self, x):
        if self.a_quanter is not None:
            x = self.a_quanter(x)
        w = self.weight
        if self.w_quanter is not None:
            w = self.w_quanter(w)
        return nn.functional.linear(x, w, self.bias)


class QuantedConv2D(nn.Layer):
    """Conv2D with fake-quantized activations and weights (QAT form,
    reference nn/quant_layers QuantizedConv2D)."""

    def __init__(self, conv, a_quanter, w_quanter):
        super().__init__()
        self._conv = conv
        self.weight = conv.weight
        self.bias = conv.bias
        self.a_quanter = _instantiate(a_quanter)
        self.w_quanter = _instantiate(w_quanter)

    def forward(self, x):
        if self.a_quanter is not None:
            x = self.a_quanter(x)
        w = self.weight
        if self.w_quanter is not None:
            w = self.w_quanter(w)
        return nn.functional.conv2d(
            x, w, self.bias, stride=self._conv._stride,
            padding=self._conv._padding, dilation=self._conv._dilation,
            groups=self._conv._groups)


# ---------------------------------------------------------------------------
# PTQ forms (observer calibration)
# ---------------------------------------------------------------------------

class ObservedLinear(nn.Layer):
    def __init__(self, linear, a_observer):
        super().__init__()
        self.weight = linear.weight
        self.bias = linear.bias
        self.a_observer = _instantiate(a_observer) or AbsmaxObserver()

    def forward(self, x):
        x = self.a_observer(x)
        return nn.functional.linear(x, self.weight, self.bias)


class ObservedConv2D(nn.Layer):
    def __init__(self, conv, a_observer):
        super().__init__()
        self._conv = conv
        self.weight = conv.weight
        self.bias = conv.bias
        self.a_observer = _instantiate(a_observer) or AbsmaxObserver()

    def forward(self, x):
        x = self.a_observer(x)
        return nn.functional.conv2d(
            x, self.weight, self.bias, stride=self._conv._stride,
            padding=self._conv._padding, dilation=self._conv._dilation,
            groups=self._conv._groups)


# ---------------------------------------------------------------------------
# deployment forms: int8 weights (per-channel), fp compute at the edge
# ---------------------------------------------------------------------------

def _act_fake_quant(x, scale):
    """Static input quantization at the deployed op edge (one shared
    definition so linear and conv deployment numerics cannot diverge)."""

    def act_q(a):
        return jnp.clip(jnp.round(a / scale), -127, 127) * scale

    return apply(act_q, x, name="act_quant")


def _quantize_weight(w, channel_axis):
    """-> (int8 weights, per-channel fp32 scales)"""
    obs = AbsMaxChannelWiseWeightObserver()
    scales = obs.observe_weight(w, channel_axis)
    shape = [1] * w.ndim
    shape[channel_axis] = -1
    s = scales.reshape(shape)
    q = jnp.clip(jnp.round(w / s), -127, 127).astype(jnp.int8)
    return q, scales


class ConvertedInt8Linear(nn.Layer):
    """Deployment form: per-out-channel int8 weight + fp scales; optional
    static activation scale from the PTQ observer.

    The matmul routes per ``FLAGS_paged_kernel`` (resolved ONCE at
    conversion, the serving convention): on the pallas/interpret route
    the weight stays int8 into `kernels.pallas.quant_matmul` and
    dequantizes in-register; on the dense route (and the default
    ``auto`` on CPU) it keeps the original XLA dequant-then-matmul
    byte-for-byte."""

    def __init__(self, src, act_scale=None):
        super().__init__()
        from ..inference.paged import kernel_route, resolve_paged_kernel
        w = src.weight._data  # [in, out]
        q, scales = _quantize_weight(w, channel_axis=1)
        self.register_buffer("w_int8", Tensor(q))
        self.register_buffer("w_scales", Tensor(scales))
        self.bias = src.bias
        self.act_scale = act_scale
        self._kernel_route = kernel_route(resolve_paged_kernel(None))

    def forward(self, x):
        if self.act_scale is not None:  # simulate static input quant
            x = _act_fake_quant(x, self.act_scale)
        if self._kernel_route != "dense":
            from ..kernels.pallas.quant_matmul import quant_matmul
            interp = self._kernel_route == "interpret"

            def qmm(xx, ww, ss):
                return quant_matmul(xx, ww, ss, interpret=interp)

            out = apply(qmm, x, self.w_int8, self.w_scales,
                        name="quant_matmul")
            return out + self.bias if self.bias is not None else out
        w = Tensor(self.w_int8._data.astype(jnp.float32) *
                   self.w_scales._data[None, :])
        return nn.functional.linear(x, w, self.bias)


class ConvertedInt8Conv2D(nn.Layer):
    def __init__(self, src, act_scale=None):
        super().__init__()
        conv = src._conv
        w = src.weight._data  # [out, in, kh, kw]
        q, scales = _quantize_weight(w, channel_axis=0)
        self.register_buffer("w_int8", Tensor(q))
        self.register_buffer("w_scales", Tensor(scales))
        self.bias = src.bias
        self._conv = conv
        self.act_scale = act_scale

    def forward(self, x):
        if self.act_scale is not None:
            x = _act_fake_quant(x, self.act_scale)
        w = Tensor(self.w_int8._data.astype(jnp.float32) *
                   self.w_scales._data[:, None, None, None])
        return nn.functional.conv2d(
            x, w, self.bias, stride=self._conv._stride,
            padding=self._conv._padding, dilation=self._conv._dilation,
            groups=self._conv._groups)


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------

class QAT:
    """Quantization-aware training driver (reference qat.py): quantize()
    swaps Linear/Conv2D for fake-quant forms; train; convert() emits the
    int8 deployment model."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model, inplace=False):
        target = model if inplace else _clone(model)
        self._swap(target)
        return target

    def _swap(self, layer):
        for name, sub in list(layer.named_children()):
            a, w = self.config._quanters_for(sub)
            if isinstance(sub, nn.Linear) and (a or w):
                setattr(layer, name, QuantedLinear(sub, a, w))
            elif isinstance(sub, nn.Conv2D) and (a or w):
                setattr(layer, name, QuantedConv2D(sub, a, w))
            else:
                self._swap(sub)

    def convert(self, model, inplace=False):
        target = model if inplace else _clone(model)
        self._convert(target)
        return target

    @staticmethod
    def _act_scale(sub):
        q = getattr(sub, "a_quanter", None) or getattr(
            sub, "a_observer", None)
        if isinstance(q, BaseObserver):
            try:
                s = q.scales()
                return float(s) if s is not None else None
            except (NotImplementedError, TypeError):
                return None
        return None

    def _convert(self, layer):
        for name, sub in list(layer.named_children()):
            if isinstance(sub, (QuantedLinear, ObservedLinear)):
                setattr(layer, name,
                        ConvertedInt8Linear(sub, self._act_scale(sub)))
            elif isinstance(sub, (QuantedConv2D, ObservedConv2D)):
                setattr(layer, name,
                        ConvertedInt8Conv2D(sub, self._act_scale(sub)))
            else:
                self._convert(sub)


class PTQ(QAT):
    """Post-training quantization (reference ptq.py): quantize() inserts
    OBSERVERS (model still fp32); run calibration batches; convert()
    quantizes weights per-channel and freezes observed act scales."""

    def _swap(self, layer):
        for name, sub in list(layer.named_children()):
            a, w = self.config._quanters_for(sub)
            # honor the config gating exactly like QAT._swap: a layer the
            # config never opted in must NOT get an observer (and must
            # not be int8-converted later)
            if isinstance(sub, nn.Linear) and (a or w):
                setattr(layer, name, ObservedLinear(sub, a))
            elif isinstance(sub, nn.Conv2D) and (a or w):
                setattr(layer, name, ObservedConv2D(sub, a))
            else:
                self._swap(sub)


def quanted_linear(x, w_int8, scale, bias=None):
    w = Tensor(w_int8._data.astype(jnp.float32) * scale)
    return nn.functional.linear(x, w, bias)


def _clone(model):
    import copy
    return copy.deepcopy(model)
