"""`paddle.quantization` (reference: python/paddle/quantization/ — QAT/PTQ
framework: QuantConfig, fake quanters, observers, QAT.quantize/convert).

TPU-first: int8 fake-quant simulates on-device quantization; the real
int8 path on TPU is XLA's native int8 matmul (v5e doubles int8 peak), so
`convert` keeps weights int8 + scale and dequantizes at the op edge.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import nn
from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["QuantConfig", "QAT", "PTQ", "FakeQuanterWithAbsMaxObserver",
           "BaseObserver", "BaseQuanter", "quanter",
           "AbsmaxObserver", "quanted_linear"]


class FakeQuanterWithAbsMaxObserver(nn.Layer):
    """Fake quantization with a moving-average absmax observer (reference
    fake_quanter.py)."""

    def __init__(self, moving_rate=0.9, bit_length=8, dtype="float32",
                 name=None):
        super().__init__()
        self.moving_rate = moving_rate
        self.bit_length = bit_length
        self.register_buffer("scale",
                             Tensor(jnp.ones([], jnp.float32)))
        self._initialized = False

    def forward(self, x):
        qmax = float(2 ** (self.bit_length - 1) - 1)
        if self.training:
            cur = float(jnp.max(jnp.abs(x._data)))
            if not self._initialized:
                new_scale = cur
                self._initialized = True
            else:
                new_scale = (self.moving_rate * float(self.scale._data) +
                             (1 - self.moving_rate) * cur)
            self.scale._rebind(jnp.asarray(new_scale, jnp.float32))
        s = jnp.maximum(jnp.asarray(float(self.scale._data)), 1e-9)
        import jax

        def fq_ste(a):
            # straight-through estimator: rounding is identity in grad
            q = jnp.clip(jnp.round(a / s * qmax), -qmax, qmax)
            deq = q * s / qmax
            return a + jax.lax.stop_gradient(deq - a)

        return apply(fq_ste, x, name="fake_quant")


class AbsmaxObserver(nn.Layer):
    """PTQ observer collecting absmax over calibration batches."""

    def __init__(self, quant_bits=8):
        super().__init__()
        self.quant_bits = quant_bits
        self.absmax = 0.0

    def forward(self, x):
        self.absmax = max(self.absmax, float(jnp.max(jnp.abs(x._data))))
        return x

    def scale(self):
        return self.absmax / (2 ** (self.quant_bits - 1) - 1)


class QuantConfig:
    """reference config.py QuantConfig: maps layer types/instances to
    quanter factories."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        self._type_configs = {}

    def add_type_config(self, layer_type, activation=None, weight=None):
        for t in (layer_type if isinstance(layer_type, (list, tuple))
                  else [layer_type]):
            self._type_configs[t] = (activation, weight)

    def _quanters_for(self, layer):
        for t, (a, w) in self._type_configs.items():
            if isinstance(layer, t):
                return a, w
        return self.activation, self.weight


class QuantedLinear(nn.Layer):
    """Linear with fake-quantized activations and weights (QAT form)."""

    def __init__(self, linear, a_quanter, w_quanter):
        super().__init__()
        self.weight = linear.weight
        self.bias = linear.bias
        self.a_quanter = a_quanter() if callable(a_quanter) else a_quanter
        self.w_quanter = w_quanter() if callable(w_quanter) else w_quanter

    def forward(self, x):
        if self.a_quanter is not None:
            x = self.a_quanter(x)
        w = self.weight
        if self.w_quanter is not None:
            w = self.w_quanter(w)
        return nn.functional.linear(x, w, self.bias)


class ConvertedInt8Linear(nn.Layer):
    """Deployment form: int8 weight + fp scale."""

    def __init__(self, qlinear):
        super().__init__()
        qmax = 127.0
        w = qlinear.weight._data
        scale = float(jnp.max(jnp.abs(w))) / qmax
        self.register_buffer("w_int8", Tensor(
            jnp.clip(jnp.round(w / scale), -qmax, qmax).astype(jnp.int8)))
        self.scale = scale
        self.bias = qlinear.bias

    def forward(self, x):
        w = Tensor(self.w_int8._data.astype(jnp.float32) * self.scale)
        return nn.functional.linear(x, w, self.bias)


class QAT:
    """Quantization-aware training driver (reference qat.py)."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model, inplace=False):
        target = model if inplace else _clone(model)
        self._swap(target)
        return target

    def _swap(self, layer):
        for name, sub in list(layer.named_children()):
            if isinstance(sub, nn.Linear):
                a, w = self.config._quanters_for(sub)
                if a is not None or w is not None:
                    setattr(layer, name, QuantedLinear(sub, a, w))
            else:
                self._swap(sub)

    def convert(self, model, inplace=False):
        target = model if inplace else _clone(model)
        self._convert(target)
        return target

    def _convert(self, layer):
        for name, sub in list(layer.named_children()):
            if isinstance(sub, QuantedLinear):
                setattr(layer, name, ConvertedInt8Linear(sub))
            else:
                self._convert(sub)


class PTQ(QAT):
    """Post-training quantization: observers instead of fake quanters."""

    pass


def quanted_linear(x, w_int8, scale, bias=None):
    w = Tensor(w_int8._data.astype(jnp.float32) * scale)
    return nn.functional.linear(x, w, bias)


def _clone(model):
    import copy
    return copy.deepcopy(model)


class BaseObserver(nn.Layer):
    """Observer base (reference quantization/factory.py BaseObserver):
    collects statistics in forward, yields scales for quantization."""

    def forward(self, x):
        return x

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        return None


class BaseQuanter(BaseObserver):
    """Quanter base (reference BaseQuanter): fake-quantizes in forward."""


def quanter(name):
    """Class decorator registering a quanter under a config name
    (reference quantization/factory.py quanter)."""
    registry = _QUANTER_REGISTRY

    def wrap(cls):
        registry[name] = cls
        return cls
    return wrap


_QUANTER_REGISTRY = {}
