"""`paddle.distributed.rpc` — worker-to-worker remote function calls.

Reference surface: python/paddle/distributed/rpc/rpc.py (init_rpc:73,
rpc_sync:143, rpc_async:183, shutdown:276, get_worker_info:307,
get_all_worker_infos:337, get_current_worker_info:364), which runs on a
C++ brpc RpcAgent.

TPU-native redesign: TPU pods have no brpc; the control plane is plain
TCP. Each worker runs a small threaded socket server; calls are
length-prefixed pickle frames (fn, args, kwargs) executed in a worker
thread pool; rendezvous and the never-timeout barrier ride the native
TCPStore (csrc/tcp_store.cc), the same store the collective bootstrap
uses. Semantics match the reference: named workers, sync/async calls
returning pickled results, exceptions re-raised at the caller, global
barrier in init_rpc and shutdown.

Only use in a trusted network: like the reference, the wire format is
pickle (reference rpc.py carries the same warning).
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass

from ..core import resilience
from ..profiler import tracing
from ..testing import faults

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_worker_info", "get_all_worker_infos",
           "get_current_worker_info", "WorkerInfo"]

_DEFAULT_RPC_TIMEOUT = 180.0


@dataclass(frozen=True)
class WorkerInfo:
    name: str
    rank: int
    ip: str
    port: int


class _Agent:
    """Per-process RPC agent: a listening socket + executor threads."""

    def __init__(self, name, rank, world_size, store):
        self.name = name
        self.rank = rank
        self.world_size = world_size
        self.store = store
        self.epoch = 0
        self.workers = {}          # name -> WorkerInfo
        # separate pools: blocked outgoing calls must never starve the
        # server side (peers issuing 8+ mutual rpc_async would deadlock
        # on a shared pool)
        self._pool = ThreadPoolExecutor(max_workers=8,
                                        thread_name_prefix="rpc-serve")
        self._client_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="rpc-call")
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(("0.0.0.0", 0))
        self._server.listen(128)
        self.port = self._server.getsockname()[1]
        self.ip = _local_ip()
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="rpc-accept", daemon=True)
        self._accept_thread.start()

    # -- wire helpers -------------------------------------------------
    @staticmethod
    def _send_frame(sock, obj):
        payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        sock.sendall(struct.pack("!Q", len(payload)) + payload)

    @staticmethod
    def _recv_frame(sock):
        hdr = _recv_exact(sock, 8)
        (n,) = struct.unpack("!Q", hdr)
        return pickle.loads(_recv_exact(sock, n))

    # -- server side --------------------------------------------------
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            self._pool.submit(self._serve_one, conn)

    def _serve_one(self, conn):
        try:
            req = self._recv_frame(conn)
            if req.get("op") == "ping":
                self._send_frame(conn, {"ok": True})
                return
            fn = req["fn"]
            try:
                # adopt the caller's trace context (if the frame carries
                # one): spans recorded while executing the remote fn
                # land in THIS host's ring under the caller's trace_id,
                # so multi-host exports stitch into one trace
                with tracing.attach(req.get("trace")), \
                        tracing.span("rpc.serve",
                                     fn=getattr(fn, "__name__",
                                                str(fn))):
                    result = fn(*req.get("args", ()),
                                **req.get("kwargs", {}))
                self._send_frame(conn, {"ok": True, "result": result})
            except BaseException as e:  # noqa: BLE001 — re-raised remotely
                try:
                    self._send_frame(conn, {"ok": False, "error": e})
                except Exception:
                    # unpicklable exception (or result mid-failure):
                    # degrade to a picklable summary instead of slamming
                    # the connection shut (caller would see bare EOFError)
                    import traceback
                    self._send_frame(conn, {
                        "ok": False,
                        "error": RuntimeError(
                            "remote raised unpicklable exception:\n" +
                            "".join(traceback.format_exception(e)))})
        except (OSError, EOFError):
            pass
        finally:
            conn.close()

    # -- client side --------------------------------------------------
    def _open_channel(self, info, timeout):
        """Channel setup ONLY retries here — a refused/reset connect is
        a peer still starting (or an exhausted accept backlog), safe to
        redial; the call frame itself is never resent (remote fns are
        not assumed idempotent)."""
        def dial():
            faults.site("rpc.connect")
            with tracing.span("rpc.connect",
                              peer=f"{info.ip}:{info.port}"):
                return socket.create_connection(
                    (info.ip, info.port), timeout=timeout or None)
        return resilience.retry_call(
            dial, policy=resilience.policy(
                "rpc.connect", deadline=timeout or None,
                retry_on=(ConnectionRefusedError, ConnectionResetError,
                          ConnectionAbortedError)))

    def call(self, to, fn, args, kwargs, timeout):
        info = self.workers.get(to)
        if info is None:
            raise ValueError(f"unknown rpc worker {to!r}; known: "
                             f"{sorted(self.workers)}")
        with tracing.span("rpc.call", to=to,
                          fn=getattr(fn, "__name__", str(fn))):
            # context captured INSIDE the span so the remote rpc.serve
            # span parents under rpc.call, not under the caller's span
            ctx = tracing.current_context()
            frame = {"fn": fn, "args": tuple(args or ()),
                     "kwargs": dict(kwargs or {})}
            if ctx is not None:
                frame["trace"] = ctx
            with self._open_channel(info, timeout) as sock:
                if timeout and timeout > 0:
                    sock.settimeout(timeout)
                sent = False
                try:
                    self._send_frame(sock, frame)
                    sent = True
                    resp = self._recv_frame(sock)
                except (OSError, EOFError) as e:
                    # classify the ambiguity for callers: once the call
                    # frame is on the wire, a timeout/reset/EOF no
                    # longer proves the remote fn did NOT run — retrying
                    # is only safe if the fn is idempotent. Dial
                    # failures (frame never sent) escape from
                    # _open_channel without this attribute.
                    e.frame_sent = sent
                    raise
        if resp["ok"]:
            return resp.get("result")
        raise resp["error"]

    def close(self):
        self._stop.set()
        try:
            self._server.close()
        except OSError:
            pass
        self._pool.shutdown(wait=False)
        self._client_pool.shutdown(wait=False)


_agent: _Agent | None = None


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise EOFError("rpc peer closed connection")
        buf += chunk
    return buf


def _local_ip():
    host = os.environ.get("POD_IP")
    if host:
        return host
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


def _ping(ip, port, timeout=3.0):
    """True iff a live rpc agent answers at (ip, port)."""
    try:
        with socket.create_connection((ip, port), timeout=timeout) as sock:
            sock.settimeout(timeout)
            _Agent._send_frame(sock, {"op": "ping"})
            return bool(_Agent._recv_frame(sock).get("ok"))
    except (OSError, EOFError, pickle.UnpicklingError):
        return False


def _barrier(store, rank, world_size, phase, epoch=0):
    """Never-timeout barrier over the TCPStore (reference
    rpc.py:_barrier_never_timeout — store add + poll)."""
    key = f"rpc/{epoch}/barrier/{phase}"
    store.add(key, 1)
    deadline = time.time() + 600
    while time.time() < deadline:
        if int(store.add(key, 0)) >= world_size:
            return
        time.sleep(0.01)
    raise TimeoutError(f"rpc barrier {phase} timed out")


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """Start this process's RPC agent and rendezvous with the others
    (reference rpc.py:73). rank / world_size / master_endpoint default
    from PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_MASTER."""
    global _agent
    if _agent is not None:
        raise RuntimeError("init_rpc already called")
    from .store import TCPStore
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) \
        if rank is None else int(rank)
    world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", 1)) \
        if world_size is None else int(world_size)
    master_endpoint = master_endpoint or os.environ.get(
        "PADDLE_MASTER", "127.0.0.1:8711")
    host, port = master_endpoint.rsplit(":", 1)
    try:
        store = TCPStore(host, int(port), is_master=(rank == 0),
                         world_size=world_size)
        joined_live_master = False
    except RuntimeError:
        if rank != 0:
            raise
        # a master already serves this endpoint (e.g. the launcher's
        # long-lived store across an elastic restart) — join as client
        store = TCPStore(host, int(port), is_master=False,
                         world_size=world_size)
        joined_live_master = True
    agent = _Agent(name, rank, world_size, store)
    # Epoch-namespace every key: a second rpc life against a still-live
    # master store (elastic restart) must not observe a previous life's
    # worker endpoints or pre-counted barriers. Rank 0 is authoritative:
    # it mints a fresh epoch (monotonic counter — robust to crashed
    # half-initialized lives and world-size changes) and publishes it;
    # other ranks join the published epoch, retrying if they raced a
    # stale value. Keys inside a fresh epoch can only come from this
    # life, since no earlier life ever observed that epoch number.
    if rank == 0:
        if joined_live_master and store.check("rpc/world_size"):
            prev_ws = int(store.get("rpc/world_size"))
            if prev_ws != world_size and store.check("rpc/cur_epoch"):
                # distinguish an elastic resize from a *different job*
                # accidentally sharing the endpoint: only proceed if the
                # latest epoch announced a clean shutdown
                last_sd = int(store.get("rpc/last_shutdown")) \
                    if store.check("rpc/last_shutdown") else -1
                if last_sd < int(store.get("rpc/cur_epoch")):
                    raise RuntimeError(
                        f"rpc master at {master_endpoint} already serves "
                        f"a live job with world_size={prev_ws}; refusing "
                        f"to join with world_size={world_size}")
        store.set("rpc/world_size", str(world_size))
        epoch = int(store.add("rpc/epoch", 1))
        store.set(f"rpc/{epoch}/worker/0",
                  pickle.dumps((name, rank, agent.ip, agent.port)))
        store.set("rpc/cur_epoch", str(epoch))
    else:
        deadline = time.time() + 600
        while True:
            store.wait(["rpc/cur_epoch"])
            epoch = int(store.get("rpc/cur_epoch"))
            store.set(f"rpc/{epoch}/worker/{rank}",
                      pickle.dumps((name, rank, agent.ip, agent.port)))
            try:
                store.wait([f"rpc/{epoch}/worker/{r}"
                            for r in range(world_size)], timeout=10)
            except TimeoutError:
                # raced a stale partially-registered epoch; re-read
                if time.time() > deadline:
                    raise
                if int(store.get("rpc/cur_epoch")) == epoch:
                    continue  # epoch is current; peers just slow — rewait
                continue
            # a FULLY-registered stale epoch (previous life crashed after
            # init) also passes the wait — confirm its rank 0 is alive
            _, _, ip0, port0 = pickle.loads(
                store.get(f"rpc/{epoch}/worker/0"))
            if _ping(ip0, port0):
                break
            if time.time() > deadline:
                raise TimeoutError("rpc init: no live epoch published")
            time.sleep(0.2)
    agent.epoch = epoch
    store.wait([f"rpc/{epoch}/worker/{r}" for r in range(world_size)])
    for r in range(world_size):
        wname, wrank, ip, wport = pickle.loads(
            store.get(f"rpc/{epoch}/worker/{r}"))
        agent.workers[wname] = WorkerInfo(wname, wrank, ip, wport)
    if len(agent.workers) != world_size:
        raise RuntimeError("duplicate rpc worker names")
    _agent = agent
    _barrier(store, rank, world_size, "init", epoch)


class _Future:
    """Async call handle (reference returns a C++ FutureWrapper with
    .wait())."""

    def __init__(self, fut):
        self._fut = fut

    def wait(self, timeout=None):
        return self._fut.result(timeout=timeout)

    def done(self):
        return self._fut.done()


def _require_agent():
    if _agent is None:
        raise RuntimeError("rpc is not initialized; call init_rpc first")
    return _agent


def rpc_sync(to, fn, args=None, kwargs=None, timeout=_DEFAULT_RPC_TIMEOUT):
    """Blocking remote call of ``fn`` on worker ``to`` (reference
    rpc.py:143)."""
    return _require_agent().call(to, fn, args, kwargs, timeout)


def rpc_async(to, fn, args=None, kwargs=None, timeout=_DEFAULT_RPC_TIMEOUT):
    """Non-blocking remote call; returns a future with .wait()
    (reference rpc.py:183)."""
    agent = _require_agent()
    return _Future(agent._client_pool.submit(
        agent.call, to, fn, args, kwargs, timeout))


def shutdown(graceful=True):
    """Barrier with all workers, then stop the agent (reference
    rpc.py:276). ``graceful=False`` skips the barrier — the teardown
    for a survivor whose peer DIED (killed decode host, crashed
    worker): barriering with a corpse would hang until the rendezvous
    deadline, and the survivor has nothing left to synchronize."""
    global _agent
    if _agent is None:
        return
    if graceful:
        _barrier(_agent.store, _agent.rank, _agent.world_size,
                 "shutdown", getattr(_agent, "epoch", 0))
    if graceful and _agent.rank == 0:
        try:  # mark a clean end of life (enables elastic world resize)
            _agent.store.set("rpc/last_shutdown",
                             str(getattr(_agent, "epoch", 0)))
        except RuntimeError:
            pass
    _agent.close()
    _agent = None


def get_worker_info(name):
    """WorkerInfo by name (reference rpc.py:307)."""
    return _require_agent().workers[name]


def get_all_worker_infos():
    """All WorkerInfos, rank order (reference rpc.py:337)."""
    return sorted(_require_agent().workers.values(),
                  key=lambda w: w.rank)


def get_current_worker_info():
    """This process's WorkerInfo (reference rpc.py:364)."""
    agent = _require_agent()
    return agent.workers[agent.name]
