"""Pipeline schedule builder: FThenB / 1F1B / interleaved (VPP) tables.

Parity: the reference's schedule zoo — `PipelineParallel.
forward_backward_pipeline` 1F1B (fleet/meta_parallel/pipeline_parallel.py:
545), `PipelineParallelWithInterleave` VPP (:1136), FThenB (:1957) — and
the static `pipeline_scheduler_pass/` family.

TPU-first: instead of an imperative per-rank schedule loop issuing NCCL
p2p, we PRECOMPUTE the whole schedule as dense per-(device, tick) tables
and let one compiled `lax.scan` follow them (see pipeline.py). A greedy
list scheduler with per-style in-flight caps and backward-priority
reproduces the reference schedules' dependency structure:

- fthenb:     no cap, all forwards first (GPipe memory: M in flight)
- 1f1b:       cap P - d in-flight microbatches on device d -> the classic
              1F1B profile (~P, not M, stashed activations)
- interleave: V virtual chunks per device on a circular ring (device d
              owns virtual stages {d, d+P, ...}); cap (V-1)*P + (P-d)

Virtual stage g (0..P*V-1) lives on device g % P, local chunk g // P;
activations travel the +1 ring (the chunk boundary from device P-1 wraps
to device 0's next chunk), cotangents the -1 ring.

The builder also derives the exact activation-stash depth the engine must
carry — the scheduler's in-flight maximum IS the 1F1B memory claim, and
tests assert it stays ~P as M grows.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

__all__ = ["Schedule", "build_schedule"]


@dataclass
class Schedule:
    P: int              # pipeline devices
    V: int              # virtual chunks per device
    M: int              # microbatches
    T: int              # total ticks
    style: str
    # per (device, tick): local chunk firing a forward / backward (-1 none)
    fchunk: np.ndarray  # [P, T] int32
    fmb: np.ndarray     # [P, T] microbatch id of that forward
    bchunk: np.ndarray  # [P, T]
    bmb: np.ndarray     # [P, T]
    # per (device, tick, local chunk): microbatch id whose forward
    # activation / backward cotangent ARRIVES this tick (-1 none)
    rcvf: np.ndarray    # [P, T, V]
    rcvb: np.ndarray    # [P, T, V]
    stash_depth: int    # fwd-input stash slots needed per chunk
    cot_depth: int      # cotangent stash slots needed per chunk

    @property
    def num_virtual_stages(self):
        return self.P * self.V


def build_schedule(P: int, V: int, M: int, style: str = "1f1b") -> Schedule:
    """Greedy list-schedule of M microbatches over P*V virtual stages.

    Dependencies (1-tick message latency on the ring):
      F(g, f) needs F(g-1, f) finished at an earlier tick (g > 0)
      B(g, b) needs B(g+1, b) finished at an earlier tick (g < N-1)
      B(N-1, b) needs F(N-1, b) finished at an earlier tick (loss seed)
    One op (F or B) per device per tick; backward has priority for
    1f1b/interleave, forward for fthenb.
    """
    if style == "gpipe":
        style = "fthenb"
    assert style in ("fthenb", "1f1b", "interleave"), style
    N = P * V
    if style == "1f1b":
        assert V == 1, "1f1b is the V=1 schedule; use interleave for V>1"
        assert M >= P, f"1F1B needs microbatches >= pp degree ({M} < {P})"
    if style == "interleave":
        assert V > 1, "interleave needs num_virtual_stages V > 1"
        assert M % P == 0, \
            f"interleave needs microbatches % pp == 0 ({M} % {P})"
    if style == "fthenb" and V > 1:
        assert M % P == 0, \
            f"fthenb with virtual stages needs microbatches % pp == 0 " \
            f"({M} % {P})"

    if style == "fthenb":
        cap = [M * V + 1] * P
        b_priority = False
    elif style == "1f1b":
        cap = [P - d for d in range(P)]
        b_priority = True
    else:  # interleave (Megatron-style warmup depth)
        cap = [(V - 1) * P + (P - d) for d in range(P)]
        b_priority = True

    def f_order(d):
        """Per-device forward issue order: groups of P microbatches cycle
        through the chunks (Megatron interleave order; for V=1 this is
        plain microbatch order)."""
        seq = []
        for k in range(V * M):
            group, r = divmod(k, P)
            chunk = group % V
            mb = (group // V) * P + r
            if V == 1:
                chunk, mb = 0, k
            seq.append((chunk, mb))
        return seq

    def b_order(d):
        """Backward order: same grouping, chunks cycled deepest-first."""
        seq = []
        for k in range(V * M):
            group, r = divmod(k, P)
            chunk = V - 1 - (group % V)
            mb = (group // V) * P + r
            if V == 1:
                chunk, mb = 0, k
            seq.append((chunk, mb))
        return seq

    forder = [f_order(d) for d in range(P)]
    border = [b_order(d) for d in range(P)]
    fptr = [0] * P
    bptr = [0] * P
    fdone = {}  # (g, f) -> tick
    bdone = {}
    fire_f = []  # (t, g, f)
    fire_b = []
    t = 0
    max_t = 8 * (M * V + N) + 64
    while sum(bptr) < P * V * M:
        assert t < max_t, f"pipeline scheduler did not converge ({style})"
        for d in range(P):
            b_ready = f_ready = False
            if bptr[d] < V * M:
                c, b = border[d][bptr[d]]
                g = c * P + d
                if g == N - 1:
                    b_ready = fdone.get((g, b), max_t) < t
                else:
                    b_ready = bdone.get((g + 1, b), max_t) < t
            if fptr[d] < V * M and fptr[d] - bptr[d] < cap[d]:
                c, f = forder[d][fptr[d]]
                g = c * P + d
                f_ready = g == 0 or fdone.get((g - 1, f), max_t) < t
            if b_ready and (b_priority or not f_ready):
                c, b = border[d][bptr[d]]
                g = c * P + d
                fire_b.append((t, g, b))
                bdone[(g, b)] = t
                bptr[d] += 1
            elif f_ready:
                c, f = forder[d][fptr[d]]
                g = c * P + d
                fire_f.append((t, g, f))
                fdone[(g, f)] = t
                fptr[d] += 1
        t += 1
    T = t

    fchunk = np.full((P, T), -1, np.int32)
    fmb = np.full((P, T), -1, np.int32)
    bchunk = np.full((P, T), -1, np.int32)
    bmb = np.full((P, T), -1, np.int32)
    rcvf = np.full((P, T, V), -1, np.int32)
    rcvb = np.full((P, T, V), -1, np.int32)
    for tick, g, f in fire_f:
        d, c = g % P, g // P
        fchunk[d, tick] = c
        fmb[d, tick] = f
        if g + 1 < N:  # arrival of this activation downstream
            nd, nc = (g + 1) % P, (g + 1) // P
            rcvf[nd, tick + 1, nc] = f
    for tick, g, b in fire_b:
        d, c = g % P, g // P
        bchunk[d, tick] = c
        bmb[d, tick] = b
        if g - 1 >= 0:
            pd, pc = (g - 1) % P, (g - 1) // P
            rcvb[pd, tick + 1, pc] = b

    # exact stash depths: max simultaneously-live entries per chunk.
    # fwd input of (g, f) lives from its arrival tick through B(g, f)'s
    # tick (the remat backward re-reads it); chunk 0's stage-0 input is
    # the ids array itself (no stash).
    stash_depth = 1
    for g in range(1, N):
        events = []
        for f in range(M):
            arrive = fdone[(g - 1, f)] + 1
            release = bdone[(g, f)] + 1
            events.append((arrive, 1))
            events.append((release, -1))
        stash_depth = max(stash_depth, _max_overlap(events))
    cot_depth = 1
    for g in range(N - 1):
        events = []
        for b in range(M):
            arrive = bdone[(g + 1, b)] + 1
            release = bdone[(g, b)] + 1
            events.append((arrive, 1))
            events.append((release, -1))
        cot_depth = max(cot_depth, _max_overlap(events))

    return Schedule(P=P, V=V, M=M, T=T, style=style, fchunk=fchunk,
                    fmb=fmb, bchunk=bchunk, bmb=bmb, rcvf=rcvf, rcvb=rcvb,
                    stash_depth=stash_depth, cot_depth=cot_depth)


def _max_overlap(events):
    cur = peak = 0
    for _, delta in sorted(events, key=lambda e: (e[0], -e[1])):
        cur += delta
        peak = max(peak, cur)
    return peak
