"""Pipeline schedule builder: FThenB / 1F1B / interleaved (VPP) tables.

Parity: the reference's schedule zoo — `PipelineParallel.
forward_backward_pipeline` 1F1B (fleet/meta_parallel/pipeline_parallel.py:
545), `PipelineParallelWithInterleave` VPP (:1136), FThenB (:1957) — and
the static `pipeline_scheduler_pass/` family.

TPU-first: instead of an imperative per-rank schedule loop issuing NCCL
p2p, we PRECOMPUTE the whole schedule as dense per-(device, tick) tables
and let one compiled `lax.scan` follow them (see pipeline.py). A greedy
list scheduler with per-style in-flight caps and backward-priority
reproduces the reference schedules' dependency structure:

- fthenb:     no cap, all forwards first (GPipe memory: M in flight)
- 1f1b:       cap P - d in-flight microbatches on device d -> the classic
              1F1B profile (~P, not M, stashed activations)
- interleave: V virtual chunks per device on a circular ring (device d
              owns virtual stages {d, d+P, ...}); cap (V-1)*P + (P-d)
- 1f1b_packed / interleave_packed: same dependency structure, but a
  device may fire an F AND a B in the SAME tick. The fused lockstep
  scan traces both phases into every tick anyway (their cost is paid
  whether or not they fire), so packing ~halves the tick count in
  steady state — the lockstep-XLA analogue of what zero-bubble
  scheduling buys an async executor.
- zb (ZB-H1): backward split into B (activation grad — on the critical
  path) and W (weight grad — deferred to fill bubbles), after the
  reference's pipeline_zero_bubble.py (ZB-H1). One op per device/tick,
  priority B > F > W; activation stash is released at W time. Carried
  for measurement: in the lockstep scan a W split adds a third traced
  phase to every tick, which the tick-count model and hardware numbers
  in PARITY.md show is strictly worse than packing — see
  `schedule_cost_report`.

Virtual stage g (0..P*V-1) lives on device g % P, local chunk g // P;
activations travel the +1 ring (the chunk boundary from device P-1 wraps
to device 0's next chunk), cotangents the -1 ring.

The builder also derives the exact activation-stash depth the engine must
carry — the scheduler's in-flight maximum IS the 1F1B memory claim, and
tests assert it stays ~P as M grows.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

__all__ = ["Schedule", "build_schedule", "schedule_cost_report"]


@dataclass
class Schedule:
    P: int              # pipeline devices
    V: int              # virtual chunks per device
    M: int              # microbatches
    T: int              # total ticks
    style: str
    # per (device, tick): local chunk firing a forward / backward (-1 none)
    fchunk: np.ndarray  # [P, T] int32
    fmb: np.ndarray     # [P, T] microbatch id of that forward
    bchunk: np.ndarray  # [P, T]
    bmb: np.ndarray     # [P, T]
    # per (device, tick, local chunk): microbatch id whose forward
    # activation / backward cotangent ARRIVES this tick (-1 none)
    rcvf: np.ndarray    # [P, T, V]
    rcvb: np.ndarray    # [P, T, V]
    stash_depth: int    # fwd-input stash slots needed per chunk
    cot_depth: int      # cotangent stash slots needed per chunk
    # zero-bubble only: deferred weight-grad ops (-1 none; B means
    # activation-grad-only when these are present)
    wchunk: np.ndarray = None  # [P, T]
    wmb: np.ndarray = None     # [P, T]

    @property
    def has_wgrad(self):
        return self.wchunk is not None

    @property
    def num_virtual_stages(self):
        return self.P * self.V


def build_schedule(P: int, V: int, M: int, style: str = "1f1b") -> Schedule:
    """Greedy list-schedule of M microbatches over P*V virtual stages.

    Dependencies (1-tick message latency on the ring):
      F(g, f) needs F(g-1, f) finished at an earlier tick (g > 0)
      B(g, b) needs B(g+1, b) finished at an earlier tick (g < N-1)
      B(N-1, b) needs F(N-1, b) finished at an earlier tick (loss seed)
    One op (F or B) per device per tick; backward has priority for
    1f1b/interleave, forward for fthenb.
    """
    if style == "gpipe":
        style = "fthenb"
    assert style in ("fthenb", "1f1b", "interleave", "1f1b_packed",
                     "interleave_packed", "zb"), style
    packed = style.endswith("_packed")
    base = style[:-7] if packed else style
    N = P * V
    if base in ("1f1b", "zb"):
        assert V == 1, f"{base} is the V=1 schedule; use interleave for V>1"
        assert M >= P, f"{base} needs microbatches >= pp degree ({M} < {P})"
    if base == "interleave":
        assert V > 1, "interleave needs num_virtual_stages V > 1"
        assert M % P == 0, \
            f"interleave needs microbatches % pp == 0 ({M} % {P})"
    if base == "fthenb" and V > 1:
        assert M % P == 0, \
            f"fthenb with virtual stages needs microbatches % pp == 0 " \
            f"({M} % {P})"

    if base == "fthenb":
        cap = [M * V + 1] * P
        b_priority = False
    elif base in ("1f1b", "zb"):
        cap = [P - d for d in range(P)]
        b_priority = True
    else:  # interleave (Megatron-style warmup depth)
        cap = [(V - 1) * P + (P - d) for d in range(P)]
        b_priority = True
    split_w = base == "zb"

    def f_order(d):
        """Per-device forward issue order: groups of P microbatches cycle
        through the chunks (Megatron interleave order; for V=1 this is
        plain microbatch order)."""
        seq = []
        for k in range(V * M):
            group, r = divmod(k, P)
            chunk = group % V
            mb = (group // V) * P + r
            if V == 1:
                chunk, mb = 0, k
            seq.append((chunk, mb))
        return seq

    def b_order(d):
        """Backward order: same grouping, chunks cycled deepest-first."""
        seq = []
        for k in range(V * M):
            group, r = divmod(k, P)
            chunk = V - 1 - (group % V)
            mb = (group // V) * P + r
            if V == 1:
                chunk, mb = 0, k
            seq.append((chunk, mb))
        return seq

    forder = [f_order(d) for d in range(P)]
    border = [b_order(d) for d in range(P)]
    fptr = [0] * P
    bptr = [0] * P
    wptr = [0] * P
    fdone = {}  # (g, f) -> tick
    bdone = {}
    wdone = {}
    fire_f = []  # (t, g, f)
    fire_b = []
    fire_w = []
    t = 0
    max_t = 8 * (M * V * (3 if split_w else 1) + N) + 64
    target = P * V * M

    def _b_ready(d):
        if bptr[d] >= V * M:
            return False
        c, b = border[d][bptr[d]]
        g = c * P + d
        if g == N - 1:
            return fdone.get((g, b), max_t) < t
        return bdone.get((g + 1, b), max_t) < t

    def _f_ready(d):
        if fptr[d] >= V * M or fptr[d] - bptr[d] >= cap[d]:
            return False
        if split_w and fptr[d] - wptr[d] >= cap[d] + 1:
            # ZB-H1 memory bound: the stash lives [wptr, fptr) (the W
            # pass remats from the stashed chunk input), so deferring W
            # unboundedly would grow activation memory to M; cap the
            # window at the 1F1B depth + 1 slack
            return False
        c, f = forder[d][fptr[d]]
        g = c * P + d
        return g == 0 or fdone.get((g - 1, f), max_t) < t

    def _w_ready(d):
        # W(g, b) after its own B(g, b); same order as B
        if not split_w or wptr[d] >= V * M:
            return False
        c, w = border[d][wptr[d]]
        g = c * P + d
        return bdone.get((g, w), max_t) < t

    while (sum(wptr) if split_w else sum(bptr)) < target:
        assert t < max_t, f"pipeline scheduler did not converge ({style})"
        for d in range(P):
            fired = False
            if _b_ready(d) and (b_priority or not _f_ready(d)):
                c, b = border[d][bptr[d]]
                g = c * P + d
                fire_b.append((t, g, b))
                bdone[(g, b)] = t
                bptr[d] += 1
                fired = True
            if _f_ready(d) and (packed or not fired):
                c, f = forder[d][fptr[d]]
                g = c * P + d
                fire_f.append((t, g, f))
                fdone[(g, f)] = t
                fptr[d] += 1
                fired = True
            if _w_ready(d) and not fired:
                # ZB-H1: weight grads fill ticks with no F/B to run
                c, w = border[d][wptr[d]]
                g = c * P + d
                fire_w.append((t, g, w))
                wdone[(g, w)] = t
                wptr[d] += 1
        t += 1
    T = t

    fchunk = np.full((P, T), -1, np.int32)
    fmb = np.full((P, T), -1, np.int32)
    bchunk = np.full((P, T), -1, np.int32)
    bmb = np.full((P, T), -1, np.int32)
    rcvf = np.full((P, T, V), -1, np.int32)
    rcvb = np.full((P, T, V), -1, np.int32)
    for tick, g, f in fire_f:
        d, c = g % P, g // P
        fchunk[d, tick] = c
        fmb[d, tick] = f
        if g + 1 < N:  # arrival of this activation downstream
            nd, nc = (g + 1) % P, (g + 1) // P
            rcvf[nd, tick + 1, nc] = f
    for tick, g, b in fire_b:
        d, c = g % P, g // P
        bchunk[d, tick] = c
        bmb[d, tick] = b
        if g - 1 >= 0:
            pd, pc = (g - 1) % P, (g - 1) // P
            rcvb[pd, tick + 1, pc] = b

    wchunk = wmb = None
    if split_w:
        wchunk = np.full((P, T), -1, np.int32)
        wmb = np.full((P, T), -1, np.int32)
        for tick, g, w in fire_w:
            d, c = g % P, g // P
            wchunk[d, tick] = c
            wmb[d, tick] = w

    # exact stash depths: max simultaneously-live entries per chunk.
    # fwd input of (g, f) lives from its arrival tick through B(g, f)'s
    # tick (the remat backward re-reads it) — or through W(g, f) when
    # weight grads are deferred (zb); chunk 0's stage-0 input is the ids
    # array itself (no stash).
    def _rel(g, f):
        return (wdone[(g, f)] if split_w else bdone[(g, f)]) + 1

    stash_depth = 1
    for g in range(1, N):
        events = []
        for f in range(M):
            arrive = fdone[(g - 1, f)] + 1
            release = _rel(g, f)
            events.append((arrive, 1))
            events.append((release, -1))
        stash_depth = max(stash_depth, _max_overlap(events))
    cot_depth = 1
    for g in range(N - 1):
        events = []
        for b in range(M):
            arrive = bdone[(g + 1, b)] + 1
            release = _rel(g, b)  # zb: the W pass re-reads the cotangent
            events.append((arrive, 1))
            events.append((release, -1))
        cot_depth = max(cot_depth, _max_overlap(events))

    return Schedule(P=P, V=V, M=M, T=T, style=style, fchunk=fchunk,
                    fmb=fmb, bchunk=bchunk, bmb=bmb, rcvf=rcvf, rcvb=rcvb,
                    stash_depth=stash_depth, cot_depth=cot_depth,
                    wchunk=wchunk, wmb=wmb)


def _max_overlap(events):
    cur = peak = 0
    for _, delta in sorted(events, key=lambda e: (e[0], -e[1])):
        cur += delta
        peak = max(peak, cur)
    return peak


# op costs in forward-chunk units for the lockstep scan engine
# (pipeline.py): a combined backward traces remat-forward + full
# backward (~1 + 2); the zb split pays the remat TWICE (once in the
# activation-grad pass, once in the weight-grad pass)
_COST = {"F": 1.0, "B": 3.0, "Bd": 2.0, "W": 2.0}


def schedule_cost_report(P, M, V=1, costs=None):
    """Tick tables + lockstep cost model for every schedule style at
    (P, M[, V]) — the measurement VERDICT r2 asked for (reference
    pipeline_zero_bubble.py ZB-H1). Per tick, every device executes the
    ops its tables fire; the wall-clock of a lockstep tick is the MAX
    over devices of its fired-op cost (devices synchronize on the ring
    ppermute each tick). Returns {style: {ticks, cost, stash, ...}}.

    ``costs`` overrides the analytic per-op costs with MEASURED ones
    ({"F","B","Bd","W"}, any unit) — e.g. per-phase wall-clock of the
    real per-stage computation on TPU (tools/pipeline_tick_ab.py), so
    the report predicts hardware step time instead of trace units."""
    costs = dict(_COST, **(costs or {}))
    styles = ["fthenb", "1f1b", "1f1b_packed", "zb"]
    if V > 1:
        styles = ["fthenb", "interleave", "interleave_packed"]
    out = {}
    for style in styles:
        v = V if "interleave" in style or style == "fthenb" else 1
        try:
            s = build_schedule(P, v, M, style)
        except AssertionError:
            continue
        cost = 0.0
        busy = 0.0
        for t in range(s.T):
            tick_max = 0.0
            for d in range(P):
                c = 0.0
                if s.fmb[d, t] >= 0:
                    c += costs["F"]
                if s.bmb[d, t] >= 0:
                    c += costs["Bd"] if s.has_wgrad else costs["B"]
                if s.has_wgrad and s.wmb[d, t] >= 0:
                    c += costs["W"]
                busy += c
                tick_max = max(tick_max, c)
            cost += tick_max
        useful = P * v * M * (costs["F"] + costs["B"])  # total real work
        out[style] = {
            "ticks": s.T,
            "lockstep_cost": cost,
            "efficiency": useful / (cost * P) if cost else 0.0,
            "stash_depth": s.stash_depth,
            "cot_depth": s.cot_depth,
        }
    return out
