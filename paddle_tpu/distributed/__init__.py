"""`paddle.distributed` surface over jax.sharding / XLA collectives
(reference: python/paddle/distributed/; SURVEY.md §2.3, §5.8)."""

from .api import (  # noqa: F401
    apply_placement_rules, dtensor_from_fn, reshard, shard_layer,
    shard_tensor,
)
from .capability import (  # noqa: F401
    has_jax_shard_map, has_multiprocess_collectives,
    has_partitioning_sharding_rule, has_pinned_host_memory,
)
from .collective import (  # noqa: F401
    Group, ReduceOp, all_gather, all_gather_object, all_reduce, all_to_all,
    alltoall, barrier, broadcast, gather, new_group, ppermute, recv, reduce,
    reduce_scatter, scatter, send,
)
from .env import (  # noqa: F401
    device_count, get_rank, get_world_size, init_parallel_env,
    is_initialized, local_device_count,
)
from .mesh import ProcessMesh, get_mesh, init_mesh, set_mesh  # noqa: F401
from .placement import (  # noqa: F401
    Partial, Placement, Replicate, Shard, named_sharding,
    placements_to_spec, spec_to_placements,
)
from .sharded_step import ShardedTrainStep  # noqa: F401
from .recompute import recompute, recompute_sequential  # noqa: F401
from . import fleet  # noqa: F401
from . import checkpoint  # noqa: F401
from .moe import MoELayer, TopKGate  # noqa: F401
from .parallel import DataParallel, spawn  # noqa: F401
from .ring_attention import ring_attention  # noqa: F401
from .pipeline import PipelineDecoderLM  # noqa: F401
from .watchdog import (  # noqa: F401
    CollectiveWatchdog, FlightRecorder, get_watchdog, watch_step,
)
from .compat import (  # noqa: F401,E402
    CountFilterEntry, DistAttr, DistModel, InMemoryDataset, ParallelEnv,
    ParallelMode, ProbabilityEntry, QueueDataset, ReduceType,
    ShardingStage1, ShardingStage2, ShardingStage3, ShowClickEntry,
    Strategy, alltoall_single, broadcast_object_list,
    destroy_process_group, get_backend, get_group, gloo_barrier,
    gloo_init_parallel_env, gloo_release, irecv, is_available, isend,
    load_state_dict, save_state_dict, scatter_object_list,
    shard_dataloader, shard_optimizer, shard_scaler, split, to_static,
    unshard_dtensor, wait,
)
from . import launch  # noqa: F401,E402
from . import io  # noqa: F401,E402
from . import rpc  # noqa: F401,E402
from . import communication  # noqa: F401,E402
from .communication import stream  # noqa: F401,E402
