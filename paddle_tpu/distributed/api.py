"""Semi-auto dtensor API: shard_tensor / reshard / shard_layer.

Parity: reference python/paddle/distributed/auto_parallel/api.py
(shard_tensor :132, reshard :622, shard_layer :721) over DistTensor +
reshard functions (paddle/phi/core/distributed/auto_parallel/reshard/).
TPU-first: a "DistTensor" is just a Tensor whose jax.Array carries a
NamedSharding; reshard is `jax.device_put` with a new sharding (XLA emits
the collective — the reference needed 20+ hand-written reshard functions,
R↔S, S↔P, nd-mesh, cross-mesh; GSPMD derives them all).
"""

from __future__ import annotations

import jax

from ..core.tensor import Parameter, Tensor
from .mesh import ProcessMesh, get_mesh
from .placement import (
    Partial, Placement, Replicate, Shard, named_sharding, placements_to_spec,
)


def shard_tensor(data, mesh=None, placements=None, dtype=None,
                 stop_gradient=None):
    """Place ``data`` on ``mesh`` with ``placements``; returns a (dist)
    Tensor. Works eagerly and under jit tracing (as a sharding
    constraint)."""
    mesh = mesh or get_mesh()
    t = data if isinstance(data, Tensor) else Tensor(data, dtype=dtype)
    placements = list(placements or [Replicate()] * mesh.ndim)
    sharding = named_sharding(mesh, placements, t.ndim)
    if isinstance(t._data, jax.core.Tracer):
        arr = jax.lax.with_sharding_constraint(t._data, sharding)
    else:
        arr = jax.device_put(t._data, sharding)
    if isinstance(t, Parameter) or isinstance(data, Tensor):
        t._rebind(arr)
        out = t
    else:
        out = Tensor(arr, stop_gradient=t.stop_gradient
                     if stop_gradient is None else stop_gradient)
    out._dist_attr = (mesh, placements)
    return out


def reshard(x, mesh=None, placements=None):
    """Re-place a dist tensor (reference api.py:622). XLA inserts the
    necessary collective (allgather / reduce-scatter / all-to-all /
    ppermute) over ICI."""
    return shard_tensor(x, mesh=mesh, placements=placements)


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh=mesh,
                        placements=placements)


def shard_layer(layer, process_mesh=None, shard_fn=None,
                input_fn=None, output_fn=None):
    """Shard every parameter of ``layer`` (reference api.py:721).

    ``shard_fn(name, layer, mesh)`` may place params itself; default
    replicates everything on the mesh."""
    mesh = process_mesh or get_mesh()

    def default_fn(name, sublayer, mesh):
        for pname, p in sublayer._parameters.items():
            if p is not None and p._dist_attr is None:
                shard_tensor(p, mesh, [Replicate()] * mesh.ndim)

    fn = shard_fn or default_fn
    for name, sub in layer.named_sublayers(include_self=True):
        fn(name, sub, mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda l, inp: input_fn(inp, mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda l, inp, out: output_fn(out, mesh))
    return layer


def apply_placement_rules(model, rules, mesh=None):
    """Shard params whose structured name matches a rule.

    ``rules``: list of (substring_or_callable, [Placement]) tried in order —
    the explicit-rule analogue of the reference's SPMD annotations for the
    ops where deterministic placement matters (SURVEY.md §7.6)."""
    mesh = mesh or get_mesh()
    for name, p in model.named_parameters():
        for pat, placements in rules:
            hit = pat(name) if callable(pat) else pat in name
            if hit:
                shard_tensor(p, mesh, placements)
                break
        else:
            if p._dist_attr is None:
                shard_tensor(p, mesh, [Replicate()] * mesh.ndim)
    return model
