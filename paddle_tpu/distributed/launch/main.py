"""Distributed launcher.

Parity: reference `python/paddle/distributed/launch/` — main.py:23 CLI,
`CollectiveController.build_pod` (controllers/collective.py:37: per-rank
env assignment, master rendezvous, log watching), pod/container process
management (job/), elastic restart (fleet/elastic/manager.py).

TPU mapping: the unit of scheduling is one PROCESS PER HOST (JAX single-
controller), not per device — `--nproc_per_node` exists for CPU-mesh
testing and multi-host simulation (reference-style localhost harness,
SURVEY.md §4). Rendezvous uses the native TCPStore (csrc/tcp_store.cc);
workers get PADDLE_* envs so `init_parallel_env` finds the topology.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


class LaunchConfig:
    def __init__(self, args):
        self.args = args


def _parse_args(argv):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--master", default=None,
                   help="rendezvous endpoint ip:port (default: self)")
    p.add_argument("--nnodes", type=str, default="1",
                   help="node count or range 'min:max' (elastic)")
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", "0")))
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--devices", "--gpus", default=None,
                   help="device ids (accepted for parity)")
    p.add_argument("--job_id", default="default")
    p.add_argument("--log_dir", default="log")
    p.add_argument("--max_restart", type=int, default=3)
    p.add_argument("--run_mode", default="collective",
                   choices=["collective", "ps"])
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _spawn_worker(rank, world_size, master, args, log_dir):
    env = dict(os.environ)
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world_size),
        "PADDLE_MASTER": master,
        "PADDLE_LOCAL_RANK": str(rank % args.nproc_per_node),
        "PADDLE_GLOBAL_SIZE": str(world_size),
        "PADDLE_JOB_ID": args.job_id,
        # JAX coordination-service equivalents
        "COORDINATOR_ADDRESS": master,
        "NUM_PROCESSES": str(world_size),
        "PROCESS_ID": str(rank),
    })
    os.makedirs(log_dir, exist_ok=True)
    log_path = os.path.join(log_dir, f"workerlog.{rank}")
    logf = open(log_path, "a")
    cmd = [sys.executable, args.training_script] + \
        args.training_script_args
    proc = subprocess.Popen(cmd, env=env, stdout=logf, stderr=logf)
    return proc, logf


def launch(argv=None):
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    nnodes = int(str(args.nnodes).split(":")[0])
    world = nnodes * args.nproc_per_node

    # rendezvous master: start the native TCPStore on this (rank-0) node
    store = None
    if args.master is None:
        from ..store import TCPStore
        store = TCPStore("127.0.0.1", 0, is_master=True,
                         world_size=world)
        master = f"127.0.0.1:{store.port}"
    else:
        master = args.master

    base = args.node_rank * args.nproc_per_node
    restarts = 0
    while True:
        procs = [_spawn_worker(base + i, world, master, args, args.log_dir)
                 for i in range(args.nproc_per_node)]

        def _terminate(*_):
            for p, _f in procs:
                p.terminate()
            sys.exit(1)

        signal.signal(signal.SIGTERM, _terminate)
        signal.signal(signal.SIGINT, _terminate)

        rcs = []
        failed = False
        for p, f in procs:
            rc = p.wait()
            f.close()
            rcs.append(rc)
            failed = failed or rc != 0
        if not failed:
            print(f"launch: all {len(procs)} workers exited cleanly")
            return 0
        restarts += 1
        if restarts > args.max_restart:
            print(f"launch: workers failed (rc={rcs}); giving up after "
                  f"{restarts - 1} restarts", file=sys.stderr)
            return 1
        print(f"launch: worker failure (rc={rcs}); elastic restart "
              f"{restarts}/{args.max_restart}", file=sys.stderr)
        for p, _ in procs:
            if p.poll() is None:
                p.terminate()
        time.sleep(1)


def main():
    sys.exit(launch())


if __name__ == "__main__":
    main()
