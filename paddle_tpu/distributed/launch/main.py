"""Distributed launcher.

Parity: reference `python/paddle/distributed/launch/` — main.py:23 CLI,
`CollectiveController.build_pod` (controllers/collective.py:37: per-rank
env assignment, master rendezvous, log watching), pod/container process
management (job/), elastic restart (fleet/elastic/manager.py).

TPU mapping: the unit of scheduling is one PROCESS PER HOST (JAX single-
controller), not per device — `--nproc_per_node` exists for CPU-mesh
testing and multi-host simulation (reference-style localhost harness,
SURVEY.md §4). Rendezvous uses the native TCPStore (csrc/tcp_store.cc);
workers get PADDLE_* envs so `init_parallel_env` finds the topology.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


class LaunchConfig:
    def __init__(self, args):
        self.args = args


def _parse_args(argv):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--master", default=None,
                   help="rendezvous endpoint ip:port (default: self)")
    p.add_argument("--nnodes", type=str, default="1",
                   help="node count or range 'min:max' (elastic)")
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", "0")))
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--devices", "--gpus", default=None,
                   help="device ids (accepted for parity)")
    p.add_argument("--job_id", default="default")
    p.add_argument("--log_dir", default="log")
    p.add_argument("--max_restart", type=int, default=3)
    p.add_argument("--run_mode", default="collective",
                   choices=["collective", "ps"])
    p.add_argument("--elastic_np", default=None,
                   help="'min:max' process-elastic world-size range: a "
                        "worker exiting with code 75 LEAVES the job "
                        "(scale-in to the survivors); a join request on "
                        "the control store scales back out; other "
                        "failures restart at the same size (fault "
                        "tolerance). Workers resume from their "
                        "distributed checkpoint. Reference "
                        "fleet/elastic/manager.py:456,483,506")
    p.add_argument("--auto_tuner_json", default=None,
                   help="search-spec json: run the auto-tuner over "
                        "parallel configs (reference launch "
                        "--auto_tuner_json); each trial launches the "
                        "script once with PADDLE_AUTO_TUNER_CONFIG set, "
                        "history persists/resumes, then the best config "
                        "runs for real")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _spawn_worker(rank, world_size, master, args, log_dir, extra_env=None):
    env = dict(os.environ)
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world_size),
        "PADDLE_MASTER": master,
        "PADDLE_LOCAL_RANK": str(rank % args.nproc_per_node),
        "PADDLE_GLOBAL_SIZE": str(world_size),
        "PADDLE_JOB_ID": args.job_id,
        # JAX coordination-service equivalents
        "COORDINATOR_ADDRESS": master,
        "NUM_PROCESSES": str(world_size),
        "PROCESS_ID": str(rank),
    })
    env.update(extra_env or {})
    os.makedirs(log_dir, exist_ok=True)
    log_path = os.path.join(log_dir, f"workerlog.{rank}")
    logf = open(log_path, "a")
    cmd = [sys.executable, args.training_script] + \
        args.training_script_args
    proc = subprocess.Popen(cmd, env=env, stdout=logf, stderr=logf)
    return proc, logf


def launch(argv=None):
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    nnodes = int(str(args.nnodes).split(":")[0])
    world = nnodes * args.nproc_per_node

    if args.auto_tuner_json:
        return _launch_auto_tune(args, world)
    if args.elastic_np:
        return _launch_elastic(args)

    # rendezvous master: start the native TCPStore on this (rank-0) node
    store = None
    if args.master is None:
        from ..store import TCPStore
        store = TCPStore("127.0.0.1", 0, is_master=True,
                         world_size=world)
        master = f"127.0.0.1:{store.port}"
    else:
        master = args.master

    base = args.node_rank * args.nproc_per_node
    restarts = 0
    while True:
        procs = [_spawn_worker(base + i, world, master, args, args.log_dir)
                 for i in range(args.nproc_per_node)]

        def _terminate(*_):
            for p, _f in procs:
                p.terminate()
            sys.exit(1)

        signal.signal(signal.SIGTERM, _terminate)
        signal.signal(signal.SIGINT, _terminate)

        rcs = []
        failed = False
        for p, f in procs:
            rc = p.wait()
            f.close()
            rcs.append(rc)
            failed = failed or rc != 0
        if not failed:
            print(f"launch: all {len(procs)} workers exited cleanly")
            return 0
        restarts += 1
        if restarts > args.max_restart:
            print(f"launch: workers failed (rc={rcs}); giving up after "
                  f"{restarts - 1} restarts", file=sys.stderr)
            return 1
        print(f"launch: worker failure (rc={rcs}); elastic restart "
              f"{restarts}/{args.max_restart}", file=sys.stderr)
        for p, _ in procs:
            if p.poll() is None:
                p.terminate()
        time.sleep(1)


LEAVE_RC = 75  # worker exit code meaning "scale me out of the job"


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _launch_elastic(args):
    """Process-elastic launch loop (reference ElasticManager semantics,
    fleet/elastic/manager.py): the launcher owns a control TCPStore
    (join requests + worker heartbeats); each *job epoch* spawns the
    current world on a FRESH coordinator port. Classification:
      - all workers exit 0                -> job complete
      - a worker exits LEAVE_RC (75)     -> scale-in to the survivors
      - join requests on the store       -> scale-out (up to max)
      - any other failure                -> fault-tolerant restart, same np
      - heartbeat lease expired (worker  -> treated as a fault (hang
        opted in via PADDLE_ELASTIC_HB)     detection)
    Workers re-form the mesh from the new PADDLE_TRAINERS_NUM and resume
    from their distributed checkpoint (cross-world reshard on load).
    """
    lo, _, hi = str(args.elastic_np).partition(":")
    min_np, max_np = int(lo), int(hi or lo)
    from ..store import TCPStore

    control = TCPStore("127.0.0.1", 0, is_master=True, world_size=max_np)
    os.makedirs(args.log_dir, exist_ok=True)
    np_cur = max_np
    epoch = 0
    restarts = 0
    joins_consumed = 0
    hb_ttl = float(os.environ.get("PADDLE_ELASTIC_HB_TTL", "10"))
    while True:
        epoch += 1
        master = f"127.0.0.1:{_free_port()}"
        extra = {
            "PADDLE_RESTART_EPOCH": str(epoch),
            "PADDLE_ELASTIC_STORE": f"127.0.0.1:{control.port}",
        }
        procs = [_spawn_worker(i, np_cur, master, args, args.log_dir,
                               extra)
                 for i in range(np_cur)]
        print(f"launch[elastic]: epoch {epoch} world={np_cur} "
              f"master={master}", flush=True)
        action = None  # (kind, new_np)
        while action is None:
            time.sleep(0.3)
            rcs = [p.poll() for p, _ in procs]
            if all(rc == 0 for rc in rcs):
                for _, f in procs:
                    f.close()
                print(f"launch[elastic]: all {np_cur} workers completed")
                return 0
            if any(rc is not None and rc != 0 for rc in rcs):
                # grace window: a leaver's peers may crash moments later
                # (wedged collectives); re-poll before classifying so a
                # near-simultaneous leave+fault reads as the leave
                time.sleep(2.0)
                rcs = [p.poll() for p, _ in procs]
                print(f"launch[elastic]: epoch {epoch} rcs={rcs}",
                      flush=True)
            leavers = sum(1 for rc in rcs if rc == LEAVE_RC)
            faults = sum(1 for rc in rcs
                         if rc is not None and rc not in (0, LEAVE_RC))
            # heartbeat-lease hang detection (workers that registered)
            now = time.time()
            for i, (p, _f) in enumerate(procs):
                if p.poll() is not None:
                    continue
                try:
                    ts = float(control.get(f"hb/{epoch}/{i}"))
                except (KeyError, ValueError):
                    continue
                if now - ts > hb_ttl:
                    print(f"launch[elastic]: rank {i} lease expired "
                          f"({now - ts:.1f}s) — treating as fault",
                          flush=True)
                    p.terminate()
                    faults += 1
            try:
                joins = int(control.get("elastic/join"))
            except (KeyError, ValueError):
                joins = 0
            new_joins = joins - joins_consumed
            if os.environ.get("PADDLE_ELASTIC_DEBUG"):
                print(f"launch[elastic]: poll t={time.time():.1f} "
                      f"rcs={rcs} joins={joins}", flush=True)
            if leavers:
                nxt = np_cur - leavers
                if nxt < min_np:
                    print(f"launch[elastic]: world would drop to {nxt} "
                          f"< min {min_np}; giving up", file=sys.stderr)
                    action = ("exit", 1)
                else:
                    action = ("scale_in", nxt)
            elif faults:
                restarts += 1
                if restarts > args.max_restart:
                    print("launch[elastic]: too many faults; giving up",
                          file=sys.stderr)
                    action = ("exit", 1)
                else:
                    action = ("fault_restart", np_cur)
            elif new_joins and np_cur < max_np:
                joins_consumed = joins
                action = ("scale_out", min(max_np, np_cur + new_joins))
        for p, f in procs:
            if p.poll() is None:
                p.terminate()
        for p, f in procs:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()
            f.close()
        kind, nxt = action
        if kind == "exit":
            return nxt
        print(f"launch[elastic]: {kind} -> world {nxt}", flush=True)
        np_cur = nxt
        time.sleep(0.5)


def _launch_auto_tune(args, world):
    """`--auto_tuner_json`: search trials (script subprocesses with the
    candidate in PADDLE_AUTO_TUNER_CONFIG), persistent/resumable history,
    then one real run with the winner (reference auto_tuner/tuner.py:21)."""
    import json

    from ..auto_tuner import launch_tune

    def spawn_trial(cfg, result_path):
        env = dict(os.environ)
        env.update({
            "PADDLE_AUTO_TUNER_CONFIG": json.dumps(cfg),
            "PADDLE_AUTO_TUNER_RESULT": result_path,
            "PADDLE_TRAINER_ID": "0",
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_JOB_ID": args.job_id,
        })
        cmd = [sys.executable, args.training_script] + \
            args.training_script_args
        try:
            return subprocess.run(
                cmd, env=env, timeout=int(os.environ.get(
                    "PADDLE_AUTO_TUNER_TRIAL_TIMEOUT", "600")),
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL).returncode
        except subprocess.TimeoutExpired:
            return -9

    best = launch_tune(args.auto_tuner_json, spawn_trial)
    if best is None:
        return 1
    # the real run, winner exported (script reads current_trial_config())
    os.environ["PADDLE_AUTO_TUNER_CONFIG"] = json.dumps(best)
    os.environ.pop("PADDLE_AUTO_TUNER_RESULT", None)
    args.auto_tuner_json = None
    return launch_from_args(args)


def launch_from_args(args):
    """Re-enter launch() with already-parsed args (tuner final run).
    Forwards EVERY launch option — dropping one here would silently
    change the final run's behavior (e.g. losing elasticity)."""
    argv = []
    if args.master:
        argv += ["--master", args.master]
    if args.elastic_np:
        argv += ["--elastic_np", str(args.elastic_np)]
    if args.devices:
        argv += ["--devices", str(args.devices)]
    argv += ["--nnodes", str(args.nnodes),
             "--node_rank", str(args.node_rank),
             "--nproc_per_node", str(args.nproc_per_node),
             "--job_id", args.job_id, "--log_dir", args.log_dir,
             "--max_restart", str(args.max_restart),
             "--run_mode", args.run_mode,
             args.training_script] + args.training_script_args
    return launch(argv)


def main():
    sys.exit(launch())


if __name__ == "__main__":
    main()
