"""`python -m paddle_tpu.distributed.launch` (reference:
python/paddle/distributed/launch/main.py:23)."""

from .main import launch, main  # noqa: F401
