"""Auto-tuner: search over parallel configurations.

Parity: reference `python/paddle/distributed/auto_tuner/` (tuner.py:21 —
grid/prune search over dp/mp/pp/sharding/micro-batch driven by
`launch --auto_tuner_json`, with history + cost model). Two entry forms:

- Library: `AutoTuner.tune(trial_fn)` measures each candidate with a
  user-supplied `trial_fn(config) -> cost`.
- Launch-integrated (the reference's workflow):
  `python -m paddle_tpu.distributed.launch --auto_tuner_json cfg.json
  train.py` — each trial runs `train.py` as a subprocess with the
  candidate exported as `PADDLE_AUTO_TUNER_CONFIG` (json env); the
  script reports its cost by writing a float to the path in
  `PADDLE_AUTO_TUNER_RESULT`. History persists to disk after EVERY
  trial; a restarted search resumes, skipping configs already tried.
"""

from __future__ import annotations

import itertools
import json
import os

__all__ = ["AutoTuner", "default_candidates", "launch_tune",
           "report_cost", "current_trial_config"]


def default_candidates(num_devices, num_layers=None, max_mp=8, max_pp=8):
    cands = []
    for mp, pp in itertools.product([1, 2, 4, 8], [1, 2, 4, 8]):
        if mp > max_mp or pp > max_pp:
            continue
        if num_devices % (mp * pp) != 0:
            continue
        dp = num_devices // (mp * pp)
        if num_layers is not None and pp > 1 and num_layers % pp != 0:
            continue
        for micro in (1, 2, 4, 8):
            cands.append({"dp_degree": dp, "mp_degree": mp,
                          "pp_degree": pp, "micro_batches": micro,
                          "sharding_degree": 1})
    return cands


class AutoTuner:
    def __init__(self, candidates=None, num_devices=None, num_layers=None,
                 memory_limit_gb=None, model_params=None):
        self.candidates = candidates if candidates is not None else \
            default_candidates(num_devices or 8, num_layers)
        self.memory_limit_gb = memory_limit_gb
        self.model_params = model_params
        self._history = []

    def prune(self):
        """Static pruning by a param-memory heuristic (reference
        prune.py rules)."""
        if self.memory_limit_gb is None or self.model_params is None:
            return self.candidates
        kept = []
        for c in self.candidates:
            shards = c["mp_degree"] * c["pp_degree"] * \
                c.get("sharding_degree", 1)
            # bf16 params + fp32 master/moments ≈ 14 bytes/param
            mem_gb = self.model_params * 14 / shards / 1e9
            if mem_gb <= self.memory_limit_gb:
                kept.append(c)
        self.candidates = kept
        return kept

    def tune(self, trial_fn, max_trials=None):
        """Run trials; returns the best config. ``trial_fn(config)`` must
        return a cost (lower is better) or raise / return None on
        failure."""
        best, best_cost = None, float("inf")
        for i, cfg in enumerate(self.candidates):
            if max_trials is not None and i >= max_trials:
                break
            try:
                cost = trial_fn(cfg)
            except Exception as e:  # OOM / invalid: record and continue
                self._history.append({"config": cfg, "error": str(e)})
                continue
            if cost is None:
                continue
            self._history.append({"config": cfg, "cost": float(cost)})
            if cost < best_cost:
                best, best_cost = cfg, cost
        return best

    def history(self):
        return list(self._history)

    def save_history(self, path):
        with open(path, "w") as f:
            json.dump(self._history, f, indent=2)

    def load_history(self, path):
        """Resume support: load prior trials so tune() skips configs
        already measured (reference tuner.py history resume)."""
        if os.path.exists(path):
            with open(path) as f:
                self._history = json.load(f)
        return self._history

    def tried_configs(self):
        return [h["config"] for h in self._history]


# ---------------------------------------------------------------------------
# launch integration (reference: launch --auto_tuner_json, tuner.py:21)
# ---------------------------------------------------------------------------

def current_trial_config():
    """Inside a training script under the tuner: the candidate config
    dict (dp_degree/mp_degree/pp_degree/micro_batches/...), or None."""
    raw = os.environ.get("PADDLE_AUTO_TUNER_CONFIG")
    return json.loads(raw) if raw else None


def report_cost(cost):
    """Inside a training script under the tuner: report this trial's
    cost (e.g. step time — lower is better)."""
    path = os.environ.get("PADDLE_AUTO_TUNER_RESULT")
    if path:
        with open(path, "w") as f:
            f.write(repr(float(cost)))


def launch_tune(tuner_json_path, spawn_trial, log=print):
    """Drive the search for the launcher.

    ``spawn_trial(config, result_path) -> (returncode)`` runs one trial
    subprocess with the candidate exported. Reads/writes the history
    file after every trial so an interrupted search resumes. Returns the
    best config (also written next to the history as best_cfg.json).
    """
    with open(tuner_json_path) as f:
        spec = json.load(f)
    hist_path = spec.get("history_path", tuner_json_path + ".history.json")
    best_path = spec.get("best_path", tuner_json_path + ".best.json")
    max_trials = spec.get("max_trials")
    cands = spec.get("candidates") or default_candidates(
        spec.get("num_devices", 8), spec.get("num_layers"))
    tuner = AutoTuner(candidates=cands,
                      memory_limit_gb=spec.get("memory_limit_gb"),
                      model_params=spec.get("model_params"))
    tuner.prune()
    tuner.load_history(hist_path)
    tried = {json.dumps(c, sort_keys=True) for c in tuner.tried_configs()}
    n_run = 0
    for cfg in tuner.candidates:
        key = json.dumps(cfg, sort_keys=True)
        if key in tried:
            continue  # resume: already measured in a previous life
        if max_trials is not None and n_run >= max_trials:
            break
        n_run += 1
        result_path = hist_path + ".trial_result"
        if os.path.exists(result_path):
            os.remove(result_path)
        log(f"auto_tuner: trial {n_run}: {cfg}")
        rc = spawn_trial(cfg, result_path)
        entry = {"config": cfg}
        if rc == 0 and os.path.exists(result_path):
            with open(result_path) as f:
                entry["cost"] = float(f.read().strip())
        else:
            entry["error"] = f"returncode={rc}"
        tuner._history.append(entry)
        tuner.save_history(hist_path)  # persist after EVERY trial
    ok = [h for h in tuner._history if "cost" in h]
    if not ok:
        log("auto_tuner: no successful trials")
        return None
    best = min(ok, key=lambda h: h["cost"])
    with open(best_path, "w") as f:
        json.dump(best, f, indent=2)
    log(f"auto_tuner: best config {best['config']} "
        f"(cost {best['cost']:.4g}) -> {best_path}")
    return best["config"]
