"""Auto-tuner: search over parallel configurations.

Parity: reference `python/paddle/distributed/auto_tuner/` (tuner.py:21 —
grid/prune search over dp/mp/pp/sharding/micro-batch driven by
`launch --auto_tuner_json`, with history + cost model). Here the search
enumerates valid mesh factorizations, prunes infeasible ones (divisibility,
memory heuristic), and measures each candidate with a user-supplied
`trial_fn(config) -> cost` (step time); `history()` returns all results.
"""

from __future__ import annotations

import itertools
import json

__all__ = ["AutoTuner", "default_candidates"]


def default_candidates(num_devices, num_layers=None, max_mp=8, max_pp=8):
    cands = []
    for mp, pp in itertools.product([1, 2, 4, 8], [1, 2, 4, 8]):
        if mp > max_mp or pp > max_pp:
            continue
        if num_devices % (mp * pp) != 0:
            continue
        dp = num_devices // (mp * pp)
        if num_layers is not None and pp > 1 and num_layers % pp != 0:
            continue
        for micro in (1, 2, 4, 8):
            cands.append({"dp_degree": dp, "mp_degree": mp,
                          "pp_degree": pp, "micro_batches": micro,
                          "sharding_degree": 1})
    return cands


class AutoTuner:
    def __init__(self, candidates=None, num_devices=None, num_layers=None,
                 memory_limit_gb=None, model_params=None):
        self.candidates = candidates if candidates is not None else \
            default_candidates(num_devices or 8, num_layers)
        self.memory_limit_gb = memory_limit_gb
        self.model_params = model_params
        self._history = []

    def prune(self):
        """Static pruning by a param-memory heuristic (reference
        prune.py rules)."""
        if self.memory_limit_gb is None or self.model_params is None:
            return self.candidates
        kept = []
        for c in self.candidates:
            shards = c["mp_degree"] * c["pp_degree"] * \
                c.get("sharding_degree", 1)
            # bf16 params + fp32 master/moments ≈ 14 bytes/param
            mem_gb = self.model_params * 14 / shards / 1e9
            if mem_gb <= self.memory_limit_gb:
                kept.append(c)
        self.candidates = kept
        return kept

    def tune(self, trial_fn, max_trials=None):
        """Run trials; returns the best config. ``trial_fn(config)`` must
        return a cost (lower is better) or raise / return None on
        failure."""
        best, best_cost = None, float("inf")
        for i, cfg in enumerate(self.candidates):
            if max_trials is not None and i >= max_trials:
                break
            try:
                cost = trial_fn(cfg)
            except Exception as e:  # OOM / invalid: record and continue
                self._history.append({"config": cfg, "error": str(e)})
                continue
            if cost is None:
                continue
            self._history.append({"config": cfg, "cost": float(cost)})
            if cost < best_cost:
                best, best_cost = cfg, cost
        return best

    def history(self):
        return list(self._history)

    def save_history(self, path):
        with open(path, "w") as f:
            json.dump(self._history, f, indent=2)
