"""Sharded (hybrid-parallel) train step.

This is the load-bearing distributed runtime: the analogue of the
reference's entire hybrid-parallel engine (HybridParallelOptimizer +
EagerReducer allreduce overlap + sharding stages + Partitioner/Resharder,
SURVEY.md §2.3). One mesh, parameters placed by dist attrs, batch sharded
on the data axes — jit + GSPMD emit every collective (grad reductions
become reduce-scatters/all-reduces over ICI, resharded activations get
all-gathers) and overlap them with compute automatically.

ZeRO stages map to *optimizer-state placements* (reference
dygraph_sharding_optimizer.py:44 semantics):
- stage 1/2: slots sharded over the data axis, params replicated
- stage 3:   params themselves sharded over the data axis
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..jit import TrainStep
from .mesh import ProcessMesh
from .placement import Replicate, Shard, named_sharding


def _shard_like_param(arr, p, mesh, opt_axis=None):
    """Sharding for one optimizer slot array: same placements as the param
    when shapes match (+ optionally further sharded over ``opt_axis`` for
    ZeRO-1/2), replicated otherwise."""
    if p._dist_attr is None:
        return None
    pmesh, placements = p._dist_attr
    if arr.shape != p._data.shape:
        return named_sharding(pmesh, [Replicate()] * pmesh.ndim, arr.ndim)
    placements = list(placements)
    if opt_axis is not None:
        axis_idx = pmesh.dim_names.index(opt_axis)
        if placements[axis_idx].is_replicated():
            # shard the largest currently-unsharded dim over the opt axis
            taken = {pl.dim for pl in placements if pl.is_shard()}
            cand = [d for d in range(arr.ndim) if d not in taken and
                    arr.shape[d] % pmesh.get_dim_size(opt_axis) == 0]
            if cand:
                dim = max(cand, key=lambda d: arr.shape[d])
                placements[axis_idx] = Shard(dim)
    return named_sharding(pmesh, placements, arr.ndim)


class ShardedTrainStep(TrainStep):
    """TrainStep over a ProcessMesh.

    ``data_placements``: placements for every batch leaf (default:
    Shard(0) over the first mesh axis — pure DP on axis 0).
    ``shard_optimizer_axis``: mesh axis name to shard optimizer slots over
    (ZeRO stage 1/2); None keeps slots placed like their params.
    """

    def __init__(self, model, optimizer, step_fn=None, mesh=None,
                 data_placements=None, shard_optimizer_axis=None,
                 donate=True, offload=None):
        super().__init__(model, optimizer, step_fn, donate=donate)
        assert mesh is not None, "ShardedTrainStep requires a ProcessMesh"
        self._mesh = mesh
        if data_placements is None:
            data_placements = [Shard(0)] + \
                [Replicate()] * (mesh.ndim - 1)
        self._data_placements = data_placements
        self._opt_axis = shard_optimizer_axis
        self._slots_placed = set()
        # CPU offload (reference group_sharded_stage3.py:85 `offload`):
        # "os" parks optimizer slots in `pinned_host` memory between
        # steps; "os+params" parks the (ZeRO-3-sharded) params there too.
        # __call__ prefetches them onto their device shardings (async
        # device_put, overlapped with batch placement) and flushes the
        # updated state back after the step — the reference's hand-rolled
        # CUDA-stream prefetch/flush, expressed as memory-kind transfers.
        assert offload in (None, "os", "os+params"), offload
        self._offload = offload

    def _out_shardings(self):
        """Pin updated params (and their slots) to their declared
        placements so a step never silently re-lays-out the model; loss /
        aux / buffers are left to XLA."""
        param_sh = []
        slot_sh = []
        for _, p in self._params:
            if p._dist_attr is None:
                param_sh.append(None)
                slot_sh.append(None)
                continue
            pmesh, placements = p._dist_attr
            param_sh.append(named_sharding(pmesh, placements, p.ndim))
            st = self._place_slots(p)
            slot_sh.append({
                nm: (None if arr is None else arr.sharding)
                for nm, arr in st.items()})
        return (None, None, param_sh, slot_sh, None)

    def _place_slots(self, p):
        """Device_put optimizer slots with their ZeRO placements once."""
        opt = self._opt
        st = opt._slots_for(p)
        if id(p) in self._slots_placed:
            return st
        for nm, arr in st.items():
            if arr is None:
                continue
            sh = _shard_like_param(arr, p, self._mesh, self._opt_axis)
            if sh is not None:
                if self._offload is not None:
                    sh = sh.with_memory_kind("pinned_host")
                st[nm] = jax.device_put(arr, sh)
        self._slots_placed.add(id(p))
        return st

    def _prefetch(self):
        """H2D: move offloaded slots (and params) onto their device
        shardings before dispatching the step. The device_puts are async —
        they overlap with the host-side batch placement below."""
        if self._offload is None:
            return
        opt = self._opt
        for _, p in self._params:
            if p._dist_attr is None:
                continue
            st = opt._slots_for(p)
            for nm, arr in st.items():
                if arr is None:
                    continue
                sh = _shard_like_param(arr, p, self._mesh, self._opt_axis)
                if sh is not None:
                    st[nm] = jax.device_put(arr, sh)
            if self._offload == "os+params":
                pmesh, placements = p._dist_attr
                p._rebind(jax.device_put(
                    p._data, named_sharding(pmesh, placements, p.ndim)))

    def _flush_to_host(self):
        """D2H: park the updated slots (and params) back in pinned host
        memory until the next step."""
        if self._offload is None:
            return
        opt = self._opt
        for _, p in self._params:
            if p._dist_attr is None:
                continue
            st = opt._state.get(id(p))
            if st:
                for nm, arr in st.items():
                    if arr is None or not hasattr(arr, "sharding"):
                        continue
                    st[nm] = jax.device_put(
                        arr, arr.sharding.with_memory_kind("pinned_host"))
            if self._offload == "os+params":
                p._rebind(jax.device_put(
                    p._data,
                    p._data.sharding.with_memory_kind("pinned_host")))

    def __call__(self, *batch):
        # place params (idempotent: already committed), slots, and batch
        for _, p in self._params:
            if p._dist_attr is not None:
                self._place_slots(p)
        self._prefetch()
        placed = []
        for leaf in batch:
            t = leaf if isinstance(leaf, Tensor) else Tensor(leaf)
            sharding = named_sharding(self._mesh, self._data_placements,
                                      t.ndim)
            placed.append(Tensor(jax.device_put(t._data, sharding)))
        with self._mesh.jax_mesh:
            out = super().__call__(*placed)
        self._flush_to_host()
        return out
