"""Functional collective API.

Parity: reference `python/paddle/distributed/communication/` (all_reduce /
all_gather / reduce_scatter / all_to_all / broadcast / send / recv +
stream variants) over ProcessGroupNCCL (process_group_nccl.cc:819).

TPU-first semantics: a Group is a mesh axis. Inside `shard_map`-traced code
these lower to XLA ICI collectives (`lax.psum`, `lax.all_gather`,
`lax.psum_scatter`, `lax.all_to_all`, `lax.ppermute`) — asynchronously
scheduled by XLA, no comm streams or watchdog to manage. Called eagerly on
global (sharded) arrays, they are resolved through sharding: e.g. eager
all_reduce of a Partial tensor = reshard to Replicate.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor
from ..profiler import metrics as _metrics
from .mesh import get_mesh


# one (calls, bytes) counter pair per collective, pre-bound so a
# gradient all_reduce storm pays one dict hit + locked add per call
_C_COLLECTIVE = {
    name: (_metrics.counter(f"collective.{name}.calls"),
           _metrics.counter(f"collective.{name}.bytes"))
    for name in ("all_reduce", "all_gather", "reduce_scatter",
                 "all_to_all", "broadcast", "scatter", "gather", "send",
                 "recv", "ppermute", "barrier")}


def _record_collective(name, *tensors):
    """Per-collective telemetry: call count + payload bytes. Sizes come
    from meta (shape/dtype) only — recording a collective must never
    materialize a deferred chain or block on a device value."""
    c_calls, c_bytes = _C_COLLECTIVE[name]
    c_calls.inc()
    nbytes = 0
    for t in tensors:
        if t is None:
            continue
        for x in (t if isinstance(t, (list, tuple)) else (t,)):
            try:
                if isinstance(x, Tensor):
                    shape, dt = x._meta()
                else:
                    shape, dt = x.shape, x.dtype
                nbytes += int(np.prod(shape) if shape else 1) * \
                    np.dtype(dt).itemsize
            except Exception:  # noqa: BLE001 — unsized payloads: skip
                pass
    if nbytes:
        c_bytes.inc(nbytes)


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A communication group = one mesh axis (or all axes)."""

    def __init__(self, axis_name=None, mesh=None, ranks=None):
        self.axis_name = axis_name
        self.mesh = mesh or get_mesh()
        self._ranks = ranks

    @property
    def nranks(self):
        if self.mesh is None:
            return jax.device_count()
        if self.axis_name is None:
            return int(jnp.prod(jnp.asarray(self.mesh.shape)))
        return self.mesh.get_dim_size(self.axis_name)

    world_size = nranks

    @property
    def rank(self):
        return 0  # single-controller: per-device rank only exists in-trace

    def __repr__(self):
        return f"Group(axis={self.axis_name}, nranks={self.nranks})"


_world_group = None


def _group(group):
    global _world_group
    if group is not None:
        return group
    if _world_group is None:
        _world_group = Group(axis_name=None)
    return _world_group


def new_group(ranks=None, backend=None, axis_name=None, mesh=None):
    return Group(axis_name=axis_name, mesh=mesh, ranks=ranks)


def _in_shard_map(axis_name):
    """True when tracing inside shard_map with this named axis bound."""
    if axis_name is None:
        return False
    try:
        lax.axis_index(axis_name)
        return True
    except Exception:
        return False


def _axis(group):
    g = _group(group)
    return g.axis_name


_REDUCERS = {
    ReduceOp.SUM: lax.psum,
    ReduceOp.MAX: lax.pmax,
    ReduceOp.MIN: lax.pmin,
}


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    axis = _axis(group)
    _record_collective("all_reduce", tensor)
    if _in_shard_map(axis):
        def fn(a):
            if op == ReduceOp.AVG:
                return lax.pmean(a, axis)
            if op == ReduceOp.PROD:
                return jnp.exp(lax.psum(jnp.log(a), axis))
            return _REDUCERS[op](a, axis)
        out = apply(fn, tensor, name="all_reduce")
        from ..ops import _inplace_from
        return _inplace_from(tensor, out)
    # eager global view: values are already global; reduce is identity
    return tensor


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    g = _group(group)
    _record_collective("all_gather", tensor)
    if _in_shard_map(g.axis_name):
        def fn(a):
            return lax.all_gather(a, g.axis_name)
        gathered = apply(fn, tensor, name="all_gather")
        if tensor_list is not None:
            from .. import ops
            tensor_list.extend(ops.unbind(gathered, axis=0))
        return gathered
    if tensor_list is not None:
        tensor_list.extend([tensor] * g.nranks)
    return tensor


def all_gather_object(object_list, obj, group=None):
    object_list.extend([obj] * _group(group).nranks)


def reduce_scatter(tensor, tensor_or_tensor_list, op=ReduceOp.SUM,
                   group=None, sync_op=True):
    g = _group(group)
    _record_collective("reduce_scatter", tensor_or_tensor_list)
    src = tensor_or_tensor_list
    if isinstance(src, (list, tuple)):
        from .. import ops
        src = ops.concat(list(src), axis=0)
    if _in_shard_map(g.axis_name):
        def fn(a):
            return lax.psum_scatter(a, g.axis_name, scatter_dimension=0,
                                    tiled=True)
        out = apply(fn, src, name="reduce_scatter")
        if tensor is not None:
            from ..ops import _inplace_from
            return _inplace_from(tensor, out)
        return out
    return src


def all_to_all(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    g = _group(group)
    _record_collective("all_to_all", in_tensor_list)
    from .. import ops
    stacked = in_tensor_list if isinstance(in_tensor_list, Tensor) else \
        ops.stack(list(in_tensor_list), axis=0)
    if _in_shard_map(g.axis_name):
        def fn(a):
            return lax.all_to_all(a, g.axis_name, split_axis=0,
                                  concat_axis=0, tiled=False)
        out = apply(fn, stacked, name="all_to_all")
    else:
        out = stacked
    if out_tensor_list is not None:
        out_tensor_list.extend(ops.unbind(out, axis=0))
    return out


alltoall = all_to_all  # legacy name (reference c_ops alltoall)


def broadcast(tensor, src=0, group=None, sync_op=True):
    """Inside shard_map: every rank takes src's shard (all_gather +
    static index — XLA turns this into the broadcast collective).
    Single-controller eager: a replicated value is already broadcast —
    identity."""
    g = _group(group)
    _record_collective("broadcast", tensor)
    if _in_shard_map(g.axis_name):
        def fn(a):
            return lax.all_gather(a, g.axis_name)[src]
        out = apply(fn, tensor, name="broadcast")
        from ..ops import _inplace_from
        return _inplace_from(tensor, out)
    return tensor


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op=op, group=group)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    """Inside shard_map: rank r receives src's ``tensor_list[r]``."""
    g = _group(group)
    if tensor_list is None:
        return tensor  # identity no-op: not a collective, not counted
    _record_collective("scatter", tensor_list)
    from .. import ops
    if _in_shard_map(g.axis_name):
        stacked = ops.stack(list(tensor_list), axis=0)  # [n, ...]

        def fn(a):
            gathered = lax.all_gather(a, g.axis_name)  # [ranks, n, ...]
            r = lax.axis_index(g.axis_name)
            return gathered[src][r]
        out = apply(fn, stacked, name="scatter")
        if tensor is not None:
            from ..ops import _inplace_from
            return _inplace_from(tensor, out)
        return out
    return tensor_list[0]


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """Inside shard_map: dst receives every rank's value (computed on
    all ranks — XLA's gather is an all_gather on a lockstep mesh)."""
    g = _group(group)
    _record_collective("gather", tensor)
    if _in_shard_map(g.axis_name):
        def fn(a):
            return lax.all_gather(a, g.axis_name)
        gathered = apply(fn, tensor, name="gather")
        if gather_list is not None:
            from .. import ops
            gather_list.extend(ops.unbind(gathered, axis=0))
        return gathered
    if gather_list is not None:
        gather_list.extend([tensor] * g.nranks)
    return tensor


_P2P_CHUNK = 1 << 19  # store.get reads into a 1 MB buffer; stay under
_p2p_state = None


def _p2p():
    """Lazy TCPStore channel for eager cross-process p2p. Rank 0 hosts
    the server on PADDLE_MASTER's port + 7 (clear of the rendezvous,
    rpc and ps stores); every rank connects a client."""
    global _p2p_state
    if _p2p_state is None:
        import os

        from .env import get_rank
        from .store import TCPStore
        addr = os.environ.get("PADDLE_P2P_MASTER") or \
            os.environ.get("PADDLE_MASTER", "127.0.0.1:8711")
        host, port = addr.rsplit(":", 1)
        port = int(port) + 7
        store = TCPStore(host, port, is_master=(get_rank() == 0))
        _p2p_state = (store, {}, {})
    return _p2p_state


def _p2p_guard(g, fn_name, tensor):
    import jax.core
    if _in_shard_map(g.axis_name) or isinstance(
            getattr(tensor, "_data", tensor), jax.core.Tracer):
        # the default world group has axis_name None, so also catch the
        # traced case directly — a tracer must never reach .numpy()
        raise NotImplementedError(
            f"{fn_name} inside traced/shard_map code: use "
            "paddle_tpu.distributed.ppermute (the ICI form of p2p) / "
            "the pipeline engine")
    from .env import get_world_size
    if get_world_size() <= 1:
        raise RuntimeError(
            f"{fn_name} requires a multi-process launch (it is the "
            "eager MPMD p2p path; in-mesh p2p is ppermute)")


def send(tensor, dst=0, group=None, sync_op=True):
    """Eager cross-process point-to-point send (reference
    communication/send.py over NCCL P2P; here: length-chunked frames on
    the native TCPStore — the DCN control-plane path. ICI-speed p2p
    inside compiled code is `ppermute`)."""
    import pickle
    g = _group(group)
    _p2p_guard(g, "send", tensor)
    _record_collective("send", tensor)
    from .env import get_rank
    store, sseq, _ = _p2p()
    src = get_rank()
    seq = sseq.get((src, dst), 0)
    arr = np.asarray(tensor.numpy() if hasattr(tensor, "numpy")
                     else tensor)
    raw = arr.tobytes()
    base = f"p2p/{src}/{dst}/{seq}"
    chunks = [raw[i:i + _P2P_CHUNK]
              for i in range(0, len(raw), _P2P_CHUNK)] or [b""]
    for ci, c in enumerate(chunks):
        store.set(f"{base}/c{ci}", c)
    # header last: its presence means every chunk is readable
    store.set(f"{base}/h",
              pickle.dumps((str(arr.dtype), arr.shape, len(chunks))))
    # commit the sequence only on success: a failed set must leave the
    # channel aligned so a retry reuses the same slot
    sseq[(src, dst)] = seq + 1
    return None


def recv(tensor, src=0, group=None, sync_op=True):
    """Blocking receive matching :func:`send`; fills ``tensor``
    in-place and returns it (reference communication/recv.py
    semantics)."""
    import pickle
    g = _group(group)
    _p2p_guard(g, "recv", tensor)
    _record_collective("recv", tensor)
    from .env import get_rank
    store, _, rseq = _p2p()
    dst = get_rank()
    seq = rseq.get((src, dst), 0)
    base = f"p2p/{src}/{dst}/{seq}"
    # commit the sequence only after the message arrives: a timeout here
    # must not desynchronize the channel (the retry waits on seq again)
    store.wait([f"{base}/h"])
    rseq[(src, dst)] = seq + 1
    dtype, shape, nch = pickle.loads(store.get(f"{base}/h"))
    raw = b"".join(store.get(f"{base}/c{i}") for i in range(nch))
    arr = np.frombuffer(raw, dtype=dtype).reshape(shape)
    for i in range(nch):
        store.delete_key(f"{base}/c{i}")
    store.delete_key(f"{base}/h")
    if tuple(tensor.shape) != tuple(shape):
        raise ValueError(
            f"recv: tensor shape {tuple(tensor.shape)} != sent {shape}")
    buf_dtype = str(np.dtype(getattr(tensor, "_data", tensor).dtype))
    if buf_dtype != str(np.dtype(dtype)):
        raise ValueError(
            f"recv: tensor dtype {buf_dtype} != sent {dtype}")
    from ..ops import _inplace_from
    return _inplace_from(tensor, Tensor(jnp.asarray(arr)))


def ppermute(tensor, perm, group=None):
    """Ring/permutation p2p (the XLA-native form of batch_isend_irecv)."""
    axis = _axis(group)
    _record_collective("ppermute", tensor)

    def fn(a):
        return lax.ppermute(a, axis, perm)

    return apply(fn, tensor, name="ppermute")


def barrier(group=None):
    _record_collective("barrier")
    jax.effects_barrier()


def get_rank(group=None):
    return jax.process_index()


def get_world_size(group=None):
    g = _group(group)
    return g.nranks if g.axis_name is not None else jax.process_count()
