"""`paddle.distributed.communication` subpackage path (reference:
python/paddle/distributed/communication/ — group/collectives + the
`stream` explicit-stream variants).

The functional collectives live in `paddle_tpu.distributed.collective`
(one implementation over lax collectives); this package re-exports them
under the reference's module layout so `paddle.distributed.
communication.*` and `paddle.distributed.stream.*` imports resolve.
"""

from ..collective import (  # noqa: F401
    Group, ReduceOp, all_gather, all_gather_object, all_reduce,
    all_to_all, barrier, broadcast, gather, get_rank, get_world_size,
    new_group, recv, reduce, reduce_scatter, scatter, send,
)
from . import stream  # noqa: F401
from . import group  # noqa: F401
