"""`paddle.distributed.communication.group` (reference group.py: the
Group object + helpers)."""

from ..collective import Group, new_group, get_rank, get_world_size  # noqa: F401


def is_initialized():
    """Whether the parallel env is up (reference group.py
    is_initialized)."""
    from ..env import is_initialized as _is_init
    return _is_init()


def destroy_process_group(group=None):
    """Release process-group state (reference group.py). Mesh axes are
    compile-time constructs here; nothing to tear down per group."""
    return None


def get_group(gid=0):
    from ..collective import _group
    return _group(None)
