"""`paddle.distributed.communication.stream` — explicit-stream collective
variants (reference: python/paddle/distributed/communication/stream/,
each op taking sync_op / use_calc_stream).

TPU-native: XLA owns stream scheduling — collectives compile into the
program and the runtime overlaps them with compute (the hand-placed
comm-stream machinery the reference exposes is the compiler's job
here). The variants therefore delegate to the one implementation in
`distributed/collective.py` and return a completed task handle.
"""

from __future__ import annotations

from ... import collective as _c
from ...compat import alltoall_single as _alltoall_single

__all__ = ["all_gather", "all_reduce", "alltoall", "alltoall_single",
           "broadcast", "reduce", "reduce_scatter", "recv", "scatter",
           "send", "gather"]


class _DoneTask:
    """Completed-communication handle (reference returns a
    core.task / Work object)."""

    def wait(self):
        return None

    def is_completed(self):
        return True


def _task(_result=None):
    return _DoneTask()


def all_reduce(tensor, op=_c.ReduceOp.SUM, group=None, sync_op=True,
               use_calc_stream=False):
    _c.all_reduce(tensor, op=op, group=group, sync_op=sync_op)
    return _task()


def all_gather(tensor_or_tensor_list, tensor, group=None, sync_op=True,
               use_calc_stream=False):
    _c.all_gather(tensor_or_tensor_list, tensor, group=group,
                  sync_op=sync_op)
    return _task()


def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True,
             use_calc_stream=False):
    _c.all_to_all(out_tensor_list, in_tensor_list, group=group,
                  sync_op=sync_op)
    return _task()


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True,
                    use_calc_stream=False):
    _alltoall_single(out_tensor, in_tensor, in_split_sizes,
                     out_split_sizes, group=group, sync_op=sync_op)
    return _task()


def broadcast(tensor, src=0, group=None, sync_op=True,
              use_calc_stream=False):
    _c.broadcast(tensor, src=src, group=group, sync_op=sync_op)
    return _task()


def reduce(tensor, dst=0, op=_c.ReduceOp.SUM, group=None, sync_op=True,
           use_calc_stream=False):
    _c.reduce(tensor, dst=dst, op=op, group=group, sync_op=sync_op)
    return _task()


def reduce_scatter(tensor, tensor_or_tensor_list, op=_c.ReduceOp.SUM,
                   group=None, sync_op=True, use_calc_stream=False):
    _c.reduce_scatter(tensor, tensor_or_tensor_list, op=op, group=group,
                      sync_op=sync_op)
    return _task()


def scatter(tensor, tensor_or_tensor_list=None, src=0, group=None,
            sync_op=True, use_calc_stream=False):
    _c.scatter(tensor, tensor_or_tensor_list, src=src, group=group,
               sync_op=sync_op)
    return _task()


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True,
           use_calc_stream=False):
    _c.gather(tensor, gather_list, dst=dst, group=group, sync_op=sync_op)
    return _task()


def send(tensor, dst=0, group=None, sync_op=True, use_calc_stream=False):
    _c.send(tensor, dst=dst, group=group, sync_op=sync_op)
    return _task()


def recv(tensor, src=0, group=None, sync_op=True, use_calc_stream=False):
    _c.recv(tensor, src=src, group=group, sync_op=sync_op)
    return _task()
