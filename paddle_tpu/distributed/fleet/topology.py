"""Hybrid-parallel topology.

Parity: reference `python/paddle/distributed/fleet/base/topology.py` —
`CommunicateTopology` axes [data, pipe, sharding, sep, model] (:65) and
`HybridCommunicateGroup` (:178) handing out per-axis comm groups.
TPU-first: the topology IS a ProcessMesh; each axis group is a mesh-axis
Group (collective.py), and rank coordinates are mesh coordinates.
"""

from __future__ import annotations

import numpy as np

from ..collective import Group
from ..mesh import ProcessMesh, set_mesh

AXES = ["data", "pipe", "sharding", "sep", "model"]
AXIS_SHORT = {"data": "dp", "pipe": "pp", "sharding": "sharding",
              "sep": "sep", "model": "mp"}


class CommunicateTopology:
    def __init__(self, hybrid_group_names=None, dims=None):
        self._names = hybrid_group_names or list(AXES)
        self._dims = list(dims or [1] * len(self._names))

    def get_hybrid_group_names(self):
        return list(self._names)

    def get_dim(self, axis_name):
        return self._dims[self._names.index(axis_name)]

    def world_size(self):
        return int(np.prod(self._dims))

    def get_rank(self, **kwargs):
        coords = [kwargs[n] for n in self._names]
        return int(np.ravel_multi_index(coords, self._dims))

    def get_coord(self, rank):
        return tuple(int(c) for c in
                     np.unravel_index(rank, self._dims))

    def get_dim_size(self, axis_name):
        return self.get_dim(axis_name)

    def get_comm_list(self, axis_name):
        """Peer-rank groups along ``axis_name`` (reference
        topology.py:120 get_comm_list): one list per combination of the
        OTHER axes' coordinates; together they partition the world."""
        ax = self._names.index(axis_name)
        ids = np.arange(self.world_size()).reshape(self._dims)
        moved = np.moveaxis(ids, ax, -1).reshape(-1, self._dims[ax])
        return [list(map(int, row)) for row in moved]

    def get_rank_from_stage(self, global_rank, **kwargs):
        """Rank with the same coords as ``global_rank`` except the axes
        overridden in kwargs (reference get_rank_from_stage)."""
        coord = dict(zip(self._names, self.get_coord(global_rank)))
        coord.update(kwargs)
        return self.get_rank(**coord)


class HybridCommunicateGroup:
    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        dims = [topology.get_dim(n) for n in AXES if n in
                topology.get_hybrid_group_names()]
        names = [AXIS_SHORT[n] for n in topology.get_hybrid_group_names()]
        # drop singleton axes from the physical mesh but remember them
        self._degrees = dict(zip(names, dims))
        mesh_names = [n for n, d in zip(names, dims)]
        ids = np.arange(int(np.prod(dims))).reshape(dims)
        self._mesh = ProcessMesh(ids, mesh_names)
        set_mesh(self._mesh)

    @property
    def mesh(self):
        return self._mesh

    @property
    def nranks(self):
        return self._topo.world_size()

    def topology(self):
        return self._topo

    def get_parallel_mode(self):
        if self._degrees.get("mp", 1) > 1 or self._degrees.get("pp", 1) > 1:
            return "hybrid"
        if self._degrees.get("sharding", 1) > 1:
            return "sharding"
        if self._degrees.get("dp", 1) > 1:
            return "data"
        return "single"

    # -- world sizes -------------------------------------------------------
    def get_data_parallel_world_size(self):
        return self._degrees.get("dp", 1)

    def get_model_parallel_world_size(self):
        return self._degrees.get("mp", 1)

    def get_pipe_parallel_world_size(self):
        return self._degrees.get("pp", 1)

    def get_sharding_parallel_world_size(self):
        return self._degrees.get("sharding", 1)

    def get_sep_parallel_world_size(self):
        return self._degrees.get("sep", 1)

    # -- ranks (single-controller: coordinates only exist in-trace) -------
    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_sharding_parallel_rank(self):
        return 0

    def get_sep_parallel_rank(self):
        return 0

    # -- groups ------------------------------------------------------------
    def _group(self, axis):
        return Group(axis_name=axis if axis in self._mesh.dim_names
                     else None, mesh=self._mesh)

    def get_data_parallel_group(self):
        return self._group("dp")

    def get_model_parallel_group(self):
        return self._group("mp")

    def get_pipe_parallel_group(self):
        return self._group("pp")

    def get_sharding_parallel_group(self):
        return self._group("sharding")

    def get_sep_parallel_group(self):
        return self._group("sep")

    def get_check_parallel_group(self, *a, **k):
        return Group(axis_name=None, mesh=self._mesh)

    def get_data_parallel_group_src_rank(self):
        return 0

    def get_model_parallel_group_src_rank(self):
        return 0


_hcg = None


def get_hcg():
    return _hcg


def set_hcg(hcg):
    global _hcg
    _hcg = hcg
    return hcg
