"""Per-axis RNG state tracking.

Parity: reference `python/paddle/distributed/fleet/layers/mpu/random.py`
(RNGStatesTracker) — distinct dropout randomness on the TP axis vs
replicated randomness elsewhere, the determinism contract for TP training.
"""

from __future__ import annotations

import contextlib

from ...core import random as random_mod

MODEL_PARALLEL_RNG = "model_parallel_rng"


class RNGStatesTracker:
    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def add(self, name, seed):
        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already exists")
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        self.seeds_.add(seed)
        gen = random_mod.Generator(seed)
        self.states_[name] = gen.get_state()

    def get_states_tracker(self):
        return dict(self.states_)

    def set_states_tracker(self, states):
        self.states_ = dict(states)

    @contextlib.contextmanager
    def rng_state(self, name=MODEL_PARALLEL_RNG):
        if name not in self.states_:
            raise ValueError(f"state {name} does not exist")
        gen = random_mod.default_generator()
        orig = gen.get_state()
        gen.set_state(self.states_[name])
        try:
            yield
        finally:
            self.states_[name] = gen.get_state()
            gen.set_state(orig)


_tracker = RNGStatesTracker()


def get_rng_state_tracker():
    return _tracker


def model_parallel_random_seed(seed=None):
    import random as pyrandom
    seed = seed or (pyrandom.getrandbits(32))
    _tracker.reset()
    random_mod.seed(seed)
    _tracker.add(MODEL_PARALLEL_RNG, seed + 1)
