"""`paddle.distributed.fleet` facade.

Parity: reference python/paddle/distributed/fleet/fleet.py (`Fleet` :99,
`fleet.init` :166 → RoleMaker → hybrid topology :598) and
DistributedStrategy (base/distributed_strategy.py:175).
"""

from __future__ import annotations

from . import topology as _topology
from .mp_layers import (  # noqa: F401
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding,
)
from .random import get_rng_state_tracker  # noqa: F401
from .topology import (  # noqa: F401
    CommunicateTopology, HybridCommunicateGroup, get_hcg, set_hcg,
)
from ..recompute import recompute, recompute_sequential  # noqa: F401

import paddle_tpu.distributed as _dist


class DistributedStrategy:
    """Config object (reference: protobuf-backed
    distributed_strategy.proto). Plain attributes here; same knob names."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sep_degree": 1,
        }
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1,
                                 "micro_batch_size": 1}
        self.tensor_parallel = False
        self.tensor_parallel_configs = {}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.gradient_scale_configs = {"scale_strategy": "avg"}


class _FleetState:
    def __init__(self):
        self.initialized = False
        self.strategy = None
        self.hcg = None


_state = _FleetState()


def init(role_maker=None, is_collective=False, strategy=None, log_level=2):
    """fleet.init (reference fleet.py:166). Builds the hybrid topology mesh
    from strategy.hybrid_configs and installs it as the global mesh."""
    strategy = strategy or DistributedStrategy()
    hc = strategy.hybrid_configs
    topo = CommunicateTopology(
        hybrid_group_names=["data", "pipe", "sharding", "sep", "model"],
        dims=[hc.get("dp_degree", 1), hc.get("pp_degree", 1),
              hc.get("sharding_degree", 1), hc.get("sep_degree", 1),
              hc.get("mp_degree", 1)])
    hcg = HybridCommunicateGroup(topo)
    set_hcg(hcg)
    _state.initialized = True
    _state.strategy = strategy
    _state.hcg = hcg
    _dist.init_parallel_env()
    return _state


def is_initialized():
    return _state.initialized


def get_hybrid_communicate_group():
    return _state.hcg


def distributed_model(model):
    """reference fleet/model.py:32 dispatch. Under GSPMD every strategy is
    expressed through placements, so the model is returned as-is once its
    params carry dist attrs; pure-DP models need no wrapper at all."""
    return model


def distributed_optimizer(optimizer, strategy=None):
    """reference fleet/optimizer.py → HybridParallelOptimizer
    (hybrid_parallel_optimizer.py:255). Grad sync + cross-axis global-norm
    clip happen inside the compiled step via GSPMD; the wrapper keeps the
    fleet API surface."""
    return optimizer


def get_rank():
    return _dist.get_rank()


def worker_num():
    return _dist.get_world_size()


def worker_index():
    return _dist.get_rank()


def is_first_worker():
    return _dist.get_rank() == 0


def barrier_worker():
    _dist.barrier()


# ---------------------------------------------------------------------------
# role makers (reference fleet/base/role_maker.py: PaddleCloudRoleMaker
# env parsing :542, UserDefinedRoleMaker) and the Fleet object facade
# ---------------------------------------------------------------------------

class Role:
    """Reference fleet/base/role_maker.py Role enum."""

    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class PaddleCloudRoleMaker:
    """Role from the PADDLE_* env contract (reference role_maker.py:542:
    PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / TRAINING_ROLE)."""

    def __init__(self, is_collective=True, **kwargs):
        import os
        self._is_collective = is_collective
        self._role = Role.SERVER if os.environ.get(
            "TRAINING_ROLE", "TRAINER").upper() == "PSERVER" else \
            Role.WORKER
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        self._size = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))

    def _is_worker(self):
        return self._role == Role.WORKER

    def _is_server(self):
        return self._role == Role.SERVER

    def _worker_index(self):
        return self._rank

    def _worker_num(self):
        return self._size

    def _role_id(self):
        return self._rank


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """Explicit role assignment (reference role_maker.py
    UserDefinedRoleMaker)."""

    def __init__(self, is_collective=True, current_id=0, role=Role.WORKER,
                 worker_num=1, server_endpoints=None, **kwargs):
        self._is_collective = is_collective
        self._role = role
        self._rank = current_id
        self._size = worker_num
        self._server_endpoints = server_endpoints or []


class UtilBase:
    """Cross-worker utilities (reference fleet/base/util_factory.py):
    barrier / all_gather over the collective backend."""

    def barrier(self, comm_world="worker"):
        _dist.barrier()

    def all_gather(self, obj, comm_world="worker"):
        out = []
        _dist.all_gather_object(out, obj)
        return out

    def get_file_shard(self, files):
        """Contiguous-block file split in the user-given order (reference
        util_factory.get_file_shard:231: [a,b,c,d,e] over 2 trainers ->
        [a,b,c] and [d,e]). Worker identity follows the PADDLE_TRAINER_*
        env contract, falling back to the collective world."""
        import os
        size = max(int(os.environ.get(
            "PADDLE_TRAINERS_NUM", max(_dist.get_world_size(), 1))), 1)
        rank = int(os.environ.get("PADDLE_TRAINER_ID", _dist.get_rank()))
        if rank >= size:
            return []
        base, rem = divmod(len(files), size)
        start = rank * base + min(rank, rem)
        return list(files[start:start + base + (1 if rank < rem else 0)])


class MultiSlotDataGenerator:
    """PS data-generator protocol (reference
    distributed/fleet/data_generator/data_generator.py MultiSlot
    variants): subclass overrides generate_sample; run_from_stdin /
    run_from_memory emit the MultiSlotDataFeed wire format — per slot
    `N v1 v2 ...`, slots space-joined (e.g.
    [("words", [1926, 8]), ("label", [1])] -> "2 1926 8 1 1")."""

    def __init__(self):
        self._proto_info = None

    def generate_sample(self, line):
        raise NotImplementedError(
            "subclass MultiSlotDataGenerator and implement "
            "generate_sample(line) returning a callable (or iterator) "
            "that yields [(slot_name, [values...]), ...] samples")

    def _gen_str(self, sample):
        if isinstance(sample, zip):
            sample = list(sample)
        if not isinstance(sample, (list, tuple)):
            raise ValueError(
                "the output of generate_sample() must yield list/tuple "
                "samples, e.g. [('words', ['1926', '08']), "
                "('label', ['1'])]")
        parts = []
        for _name, elements in sample:
            vals = elements if isinstance(elements, (list, tuple)) else \
                [elements]
            parts.append(str(len(vals)) + (" " if vals else "") +
                         " ".join(str(v) for v in vals))
        return " ".join(parts)

    def _samples(self, line):
        r = self.generate_sample(line)
        it = r() if callable(r) else r
        for sample in it:
            if sample is None:  # reference protocol: None filters the line
                continue
            yield sample

    def run_from_memory(self, lines):
        out = []
        for line in lines:
            for sample in self._samples(line):
                out.append(self._gen_str(sample))
        return out

    def run_from_stdin(self):
        import sys
        for line in sys.stdin:
            for sample in self._samples(line):
                sys.stdout.write(self._gen_str(sample) + "\n")


class MultiSlotStringDataGenerator(MultiSlotDataGenerator):
    """String-slot variant (values emitted verbatim; same wire format)."""


class Fleet:
    """The object behind the module-level facade (reference fleet.py:99
    `Fleet`; the reference exposes a singleton `fleet = Fleet()` whose
    methods this module mirrors as functions)."""

    def __init__(self):
        self._role_maker = None

    def init(self, role_maker=None, is_collective=False, strategy=None,
             log_level=2):
        self._role_maker = role_maker or PaddleCloudRoleMaker(
            is_collective=is_collective)
        return init(role_maker=role_maker, is_collective=is_collective,
                    strategy=strategy, log_level=log_level)

    def is_first_worker(self):
        return is_first_worker()

    def worker_index(self):
        return worker_index()

    def worker_num(self):
        return worker_num()

    def is_worker(self):
        rm = self._role_maker or PaddleCloudRoleMaker()
        return rm._is_worker()

    def is_server(self):
        rm = self._role_maker or PaddleCloudRoleMaker()
        return rm._is_server()

    def barrier_worker(self):
        barrier_worker()

    def distributed_model(self, model):
        return distributed_model(model)

    def distributed_optimizer(self, optimizer, strategy=None):
        return distributed_optimizer(optimizer, strategy=strategy)

    def get_hybrid_communicate_group(self):
        return get_hybrid_communicate_group()

    @property
    def util(self):
        return utils


utils = UtilBase()
