"""`paddle.distributed.fleet` facade.

Parity: reference python/paddle/distributed/fleet/fleet.py (`Fleet` :99,
`fleet.init` :166 → RoleMaker → hybrid topology :598) and
DistributedStrategy (base/distributed_strategy.py:175).
"""

from __future__ import annotations

from . import topology as _topology
from .mp_layers import (  # noqa: F401
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding,
)
from .random import get_rng_state_tracker  # noqa: F401
from .topology import (  # noqa: F401
    CommunicateTopology, HybridCommunicateGroup, get_hcg, set_hcg,
)
from ..recompute import recompute, recompute_sequential  # noqa: F401

import paddle_tpu.distributed as _dist


class DistributedStrategy:
    """Config object (reference: protobuf-backed
    distributed_strategy.proto). Plain attributes here; same knob names."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sep_degree": 1,
        }
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1,
                                 "micro_batch_size": 1}
        self.tensor_parallel = False
        self.tensor_parallel_configs = {}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.gradient_scale_configs = {"scale_strategy": "avg"}


class _FleetState:
    def __init__(self):
        self.initialized = False
        self.strategy = None
        self.hcg = None


_state = _FleetState()


def init(role_maker=None, is_collective=False, strategy=None, log_level=2):
    """fleet.init (reference fleet.py:166). Builds the hybrid topology mesh
    from strategy.hybrid_configs and installs it as the global mesh."""
    strategy = strategy or DistributedStrategy()
    hc = strategy.hybrid_configs
    topo = CommunicateTopology(
        hybrid_group_names=["data", "pipe", "sharding", "sep", "model"],
        dims=[hc.get("dp_degree", 1), hc.get("pp_degree", 1),
              hc.get("sharding_degree", 1), hc.get("sep_degree", 1),
              hc.get("mp_degree", 1)])
    hcg = HybridCommunicateGroup(topo)
    set_hcg(hcg)
    _state.initialized = True
    _state.strategy = strategy
    _state.hcg = hcg
    _dist.init_parallel_env()
    return _state


def is_initialized():
    return _state.initialized


def get_hybrid_communicate_group():
    return _state.hcg


def distributed_model(model):
    """reference fleet/model.py:32 dispatch. Under GSPMD every strategy is
    expressed through placements, so the model is returned as-is once its
    params carry dist attrs; pure-DP models need no wrapper at all."""
    return model


def distributed_optimizer(optimizer, strategy=None):
    """reference fleet/optimizer.py → HybridParallelOptimizer
    (hybrid_parallel_optimizer.py:255). Grad sync + cross-axis global-norm
    clip happen inside the compiled step via GSPMD; the wrapper keeps the
    fleet API surface."""
    return optimizer


def get_rank():
    return _dist.get_rank()


def worker_num():
    return _dist.get_world_size()


def worker_index():
    return _dist.get_rank()


def is_first_worker():
    return _dist.get_rank() == 0


def barrier_worker():
    _dist.barrier()


utils = None
