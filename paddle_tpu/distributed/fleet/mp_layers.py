"""Megatron tensor-parallel layers.

Parity: reference `python/paddle/distributed/fleet/layers/mpu/mp_layers.py`
— VocabParallelEmbedding (:47), ColumnParallelLinear (:334),
RowParallelLinear (:541), ParallelCrossEntropy (:742) and the comm
autograd ops of mp_ops.py (_c_identity/_mp_allreduce pairs).

TPU-first: the layers hold the FULL logical weight annotated with a Shard
placement on the mp mesh axis; GSPMD partitions the matmul and inserts the
identity/allreduce pairs the reference hand-writes as PyLayers. The
`gather_output` / `input_is_parallel` switches become sharding constraints
on the activations (= Megatron-SP's scatter/gather points).
"""

from __future__ import annotations

import jax

from ... import nn
from ...core.dispatch import apply
from ...core.tensor import Tensor
from ..api import shard_tensor
from ..mesh import get_mesh
from ..placement import Replicate, Shard, named_sharding
from .topology import get_hcg


def _mp_axis(mp_group=None):
    if mp_group is not None and mp_group.axis_name:
        return mp_group.mesh, mp_group.axis_name
    hcg = get_hcg()
    if hcg is not None and "mp" in hcg.mesh.dim_names:
        return hcg.mesh, "mp"
    mesh = get_mesh()
    if mesh is not None and "mp" in mesh.dim_names:
        return mesh, "mp"
    return mesh, None


def _constrain(t, mesh, placements):
    """Sharding-constrain an activation (trace-safe)."""
    if mesh is None:
        return t
    sharding = named_sharding(mesh, placements, t.ndim)

    def fn(a):
        return jax.lax.with_sharding_constraint(a, sharding)

    return apply(fn, t, name="sharding_constraint")


def _mp_placements(mesh, axis, tensor_dim):
    pl = [Replicate()] * mesh.ndim
    if axis is not None:
        pl[mesh.dim_names.index(axis)] = Shard(tensor_dim)
    return pl


class ColumnParallelLinear(nn.Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        mesh, axis = _mp_axis(mp_group)
        self._mesh, self._axis = mesh, axis
        self._gather_output = gather_output
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=nn.initializer.XavierNormal())
        if mesh is not None and axis is not None:
            shard_tensor(self.weight, mesh, _mp_placements(mesh, axis, 1))
        if has_bias:
            self.bias = self.create_parameter(
                shape=[out_features], is_bias=True)
            if mesh is not None and axis is not None:
                shard_tensor(self.bias, mesh, _mp_placements(mesh, axis, 0))
        else:
            self.bias = None

    def forward(self, x):
        out = nn.functional.linear(x, self.weight, self.bias)
        if self._mesh is None or self._axis is None:
            return out
        if self._gather_output:
            pl = [Replicate()] * self._mesh.ndim
        else:
            pl = _mp_placements(self._mesh, self._axis, out.ndim - 1)
        return _constrain(out, self._mesh, pl)


class RowParallelLinear(nn.Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        mesh, axis = _mp_axis(mp_group)
        self._mesh, self._axis = mesh, axis
        self._input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=nn.initializer.XavierNormal())
        if mesh is not None and axis is not None:
            shard_tensor(self.weight, mesh, _mp_placements(mesh, axis, 0))
        if has_bias:
            self.bias = self.create_parameter(
                shape=[out_features], is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        if self._mesh is not None and self._axis is not None and \
                self._input_is_parallel:
            x = _constrain(x, self._mesh,
                           _mp_placements(self._mesh, self._axis, x.ndim - 1))
        out = nn.functional.linear(x, self.weight, self.bias)
        if self._mesh is not None:
            out = _constrain(out, self._mesh,
                             [Replicate()] * self._mesh.ndim)
        return out


class VocabParallelEmbedding(nn.Layer):
    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        mesh, axis = _mp_axis(mp_group)
        self._mesh, self._axis = mesh, axis
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=nn.initializer.XavierNormal())
        if mesh is not None and axis is not None:
            shard_tensor(self.weight, mesh, _mp_placements(mesh, axis, 0))

    def forward(self, x):
        out = nn.functional.embedding(x, self.weight)
        if self._mesh is not None:
            out = _constrain(out, self._mesh,
                             [Replicate()] * self._mesh.ndim)
        return out


class ParallelCrossEntropy(nn.Layer):
    """Vocab-parallel CE (reference mp_layers.py:742 /
    c_softmax_with_cross_entropy): with vocab-sharded logits GSPMD computes
    the softmax reduction over the mp axis with one allreduce, which is
    exactly the hand-written kernel's comm pattern."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return nn.functional.cross_entropy(
            input, label, ignore_index=self.ignore_index, reduction="none")
