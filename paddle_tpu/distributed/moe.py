"""Mixture-of-Experts with expert parallelism.

Parity: reference `python/paddle/incubate/distributed/models/moe/`
(MoELayer :263, MoEScatter/MoEGather PyLayers over global_scatter/
global_gather all-to-all collective ops, gates gshard/switch/naive,
capacity pruning kernels prune_gate_by_capacity/limit_by_capacity).

TPU-first (GShard formulation): routing is expressed as dense one-hot
dispatch/combine einsums over an expert axis; expert weights are stacked
[E, ...] and sharded over the `ep` mesh axis, so GSPMD partitions the
vmapped expert compute and inserts the all-to-alls the reference issues
manually via global_scatter/global_gather. Capacity pruning is the
position-in-expert cumsum mask — same semantics as limit_by_capacity.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .. import nn
from ..core.dispatch import apply
from ..core.tensor import Parameter, Tensor
from .api import shard_tensor
from .mesh import get_mesh
from .placement import Replicate, Shard

__all__ = ["MoELayer", "TopKGate"]


def _one_hot(idx, n):
    return jax.nn.one_hot(idx, n, dtype=jnp.float32)


def topk_gating(logits, top_k, capacity, *, second_noise=0.0, key=None):
    """GShard-style top-k dispatch/combine.

    logits: [T, E] float32. Returns (dispatch [T,E,C] bool-ish,
    combine [T,E,C] float, aux_loss scalar).
    """
    T, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)

    gates = []
    masks = []
    p = probs
    for k in range(top_k):
        idx = jnp.argmax(p, axis=-1)
        mask = _one_hot(idx, E)
        gates.append(jnp.sum(probs * mask, axis=-1))  # [T]
        masks.append(mask)
        p = p * (1.0 - mask)

    # aux load-balance loss (GShard eq.4 / reference gshard_gate)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(masks[0], axis=0)
    aux = jnp.sum(me * ce) * E

    # position within each expert's queue, over all k choices in order
    dispatch = jnp.zeros((T, E, capacity), jnp.float32)
    combine = jnp.zeros((T, E, capacity), jnp.float32)
    prev_counts = jnp.zeros((E,), jnp.float32)
    # top-1 = Switch semantics (raw router prob); top-k>1 = Mixtral/GShard
    # normalization over the chosen experts
    denom = sum(gates) if top_k > 1 else jnp.ones_like(gates[0])
    for mask, gate in zip(masks, gates):
        pos = jnp.cumsum(mask, axis=0) - 1.0 + prev_counts[None, :]
        prev_counts = prev_counts + jnp.sum(mask, axis=0)
        in_cap = (pos < capacity) & (mask > 0)
        pos_clamped = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)
        sel = in_cap.astype(jnp.float32)  # [T, E]
        pos_oh = _one_hot(pos_clamped, capacity) * sel[..., None]
        dispatch = dispatch + mask[..., None] * pos_oh
        gate_norm = jnp.where(denom > 0, gate / jnp.maximum(denom, 1e-9),
                              0.0)
        combine = combine + (gate_norm[:, None, None] *
                             mask[..., None] * pos_oh)
    return dispatch, combine, aux


class TopKGate(nn.Layer):
    """Gate network (reference gate/gshard_gate.py, switch_gate.py: switch
    is top_k=1, gshard top_k=2)."""

    def __init__(self, d_model, num_experts, top_k=2,
                 capacity_factor=1.25):
        super().__init__()
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.weight = self.create_parameter(
            shape=[d_model, num_experts],
            default_initializer=nn.initializer.XavierUniform())

    def capacity(self, num_tokens):
        return max(int(math.ceil(
            self.top_k * num_tokens / self.num_experts *
            self.capacity_factor)), 4)


class MoELayer(nn.Layer):
    """MoE layer (reference moe_layer.py:263 API: gate + experts +
    moe_group). ``experts``: list of identical Layers (e.g. LlamaMLP).
    aux loss is accumulated on ``self.aux_loss`` each forward (the
    reference returns it via gate state)."""

    def __init__(self, gate=None, experts=None, d_model=None,
                 num_experts=None, top_k=2, capacity_factor=1.25,
                 mesh=None, ep_axis=None, moe_group=None,
                 recompute_interval=0):
        super().__init__()
        if gate is None:
            gate = TopKGate(d_model, num_experts or len(experts),
                            top_k=top_k, capacity_factor=capacity_factor)
        self.gate = gate
        self._template = experts[0]
        self._n_experts = len(experts)
        self._mesh = mesh or get_mesh()
        self._ep_axis = ep_axis
        self.aux_loss = None

        names = [n for n, _ in experts[0].named_parameters()]
        self._expert_param_names = names
        self._stacked = nn.ParameterList()
        for name in names:
            arrs = [dict(e.named_parameters())[name]._data for e in experts]
            stacked = Parameter(jnp.stack(arrs, 0))
            stacked.name = "experts." + name
            if self._mesh is not None and ep_axis is not None and \
                    ep_axis in self._mesh.dim_names:
                placements = [Replicate()] * self._mesh.ndim
                placements[self._mesh.dim_names.index(ep_axis)] = Shard(0)
                shard_tensor(stacked, self._mesh, placements)
            self._stacked.append(stacked)

    def forward(self, x):
        E = self._n_experts
        top_k = self.gate.top_k
        template = self._template
        names = self._expert_param_names
        orig_shape = None

        T = 1
        for s in x.shape[:-1]:
            T *= s
        capacity = self.gate.capacity(T)

        def pure(xa, gate_w, *expert_params):
            shape = xa.shape
            tokens = xa.reshape(-1, shape[-1])  # [T, d]
            logits = (tokens.astype(jnp.float32) @
                      gate_w.astype(jnp.float32))
            dispatch, combine, aux = topk_gating(logits, top_k, capacity)
            # dispatch tokens: [E, C, d]
            expert_in = jnp.einsum("tec,td->ecd",
                                   dispatch.astype(xa.dtype), tokens)
            params = dict(zip(names, expert_params))

            def run_one(p_one, x_one):
                from .pipeline import _functional_call
                return _functional_call(template, p_one, x_one)

            expert_out = jax.vmap(run_one)(params, expert_in)  # [E, C, d']
            out = jnp.einsum("ecd,tec->td", expert_out,
                             combine.astype(expert_out.dtype))
            out = out.reshape(*shape[:-1], out.shape[-1]).astype(xa.dtype)
            return out, aux.astype(jnp.float32)

        out, aux = apply(pure, x, self.gate.weight, *list(self._stacked),
                         name="moe")
        self.aux_loss = aux
        return out
