"""Distributed (sharded) checkpoint.

Parity: reference `python/paddle/distributed/checkpoint/` —
`save_state_dict` (save_state_dict.py:100: per-rank local shards + global
metadata, replicated-tensor dedup :72) and `load_state_dict` (reshards
across mismatched meshes/strategies at load).

TPU-first: the single-controller runtime holds global (sharded) arrays, so
"shards" are the addressable shards of each jax.Array. Each HOST writes
only its addressable shards (multi-host safe) plus one metadata.json
mapping tensor -> (global shape/dtype, shard index ranges, file). Loading
reassembles the global array and `device_put`s it to the TARGET sharding —
cross-strategy resharding for free (the reference needs explicit reshard
functions). Async save runs on a background thread (orbax-style), double
parity with the reference's async_save.
"""

from __future__ import annotations

import json
import os
import threading

import jax
import numpy as np

from ..core.tensor import Tensor

__all__ = ["save_state_dict", "load_state_dict", "async_save_state_dict"]

_METADATA = "metadata.json"


def _flatten(sd, prefix=""):
    flat = {}
    for k, v in sd.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            flat.update(_flatten(v, key))
        else:
            flat[key] = v
    return flat


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, async_save=False):
    """Write sharded checkpoint to directory ``path``."""
    os.makedirs(path, exist_ok=True)
    flat = _flatten(state_dict)
    host = jax.process_index()
    meta = {"tensors": {}, "num_hosts": jax.process_count()}
    shard_file = os.path.join(path, f"shards_{host}.npz")
    arrays = {}
    for name, t in flat.items():
        if isinstance(t, Tensor):
            arr = t._data
        elif isinstance(t, (int, float, str)):
            meta["tensors"][name] = {"scalar": t}
            continue
        else:
            arr = t
        arr = jax.device_get(arr) if not isinstance(arr, jax.Array) else arr
        entry = {"shape": list(np.shape(arr)),
                 "dtype": str(getattr(arr, "dtype", "float32")),
                 "shards": []}
        if isinstance(arr, jax.Array) and hasattr(arr, "addressable_shards"):
            seen_indices = set()
            for i, sh in enumerate(arr.addressable_shards):
                idx = tuple(
                    (0 if s.start is None else s.start,
                     dim if s.stop is None else s.stop)
                    for s, dim in zip(sh.index, arr.shape)) if sh.index \
                    else ()
                if idx in seen_indices:  # dedup replicated shards
                    continue
                seen_indices.add(idx)
                key = f"{name}::{i}"
                arrays[key] = np.asarray(sh.data)
                entry["shards"].append({"key": key, "index": list(idx),
                                        "host": host})
        else:
            key = f"{name}::0"
            arrays[key] = np.asarray(arr)
            entry["shards"].append(
                {"key": key,
                 "index": [[0, d] for d in np.shape(arr)], "host": host})
        meta["tensors"][name] = entry

    def _write():
        np.savez(shard_file, **{k: v for k, v in arrays.items()})
        if host == coordinator_rank:
            with open(os.path.join(path, _METADATA), "w") as f:
                json.dump(meta, f)

    if async_save:
        th = threading.Thread(target=_write, daemon=True)
        th.start()
        return th
    _write()


def async_save_state_dict(state_dict, path, **kw):
    return save_state_dict(state_dict, path, async_save=True, **kw)


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None):
    """Fill ``state_dict``'s tensors in place from ``path``, resharding to
    each target tensor's current sharding (any source strategy)."""
    with open(os.path.join(path, _METADATA)) as f:
        meta = json.load(f)
    files = {}
    for fn in os.listdir(path):
        if fn.startswith("shards_") and fn.endswith(".npz"):
            files[fn] = np.load(os.path.join(path, fn))

    def lookup(key):
        for z in files.values():
            if key in z:
                return z[key]
        raise KeyError(key)

    flat = _flatten(state_dict)
    for name, target in flat.items():
        if name not in meta["tensors"]:
            continue
        entry = meta["tensors"][name]
        if "scalar" in entry:
            continue
        import ml_dtypes
        dtype = entry["dtype"]
        np_dtype = getattr(ml_dtypes, dtype) if "bfloat16" in dtype or \
            "float8" in dtype else np.dtype(dtype)
        full = np.zeros(entry["shape"], np_dtype)
        for sh in entry["shards"]:
            data = lookup(sh["key"])
            sl = tuple(slice(lo, hi) for lo, hi in sh["index"]) or ...
            full[sl] = data
        if isinstance(target, Tensor):
            arr = full
            if getattr(target._data, "sharding", None) is not None and \
                    not isinstance(target._data, jax.core.Tracer):
                arr = jax.device_put(full, target._data.sharding)
            target._rebind(arr if isinstance(arr, jax.Array)
                           else jax.numpy.asarray(arr))
    return state_dict
