"""Distributed (sharded) checkpoint — crash-safe on-disk format v2.

Parity: reference `python/paddle/distributed/checkpoint/` —
`save_state_dict` (save_state_dict.py:100: per-rank local shards + global
metadata, replicated-tensor dedup :72) and `load_state_dict` (reshards
across mismatched meshes/strategies at load). Persistence semantics
follow orbax-style atomic checkpointing: stage, fsync, rename, commit
marker last.

TPU-first: the single-controller runtime holds global (sharded) arrays,
so "shards" are the addressable shards of each jax.Array. Each HOST
writes only its addressable shards plus its own ``metadata_{host}.json``
(the reference's per-rank `.distcp` + global metadata, without needing a
cross-host barrier); the loader unions all per-host metadata files.
Loading reassembles the global array and `device_put`s it to the TARGET
sharding — cross-strategy resharding for free.

On-disk format v2 (docs/ROBUSTNESS.md has the full contract)::

    <path>/                        # checkpoint ROOT passed to save/load
      ckpt_1/                      # one complete checkpoint per save
        shards_0.npz               # per-host shard payload
        metadata_0.json            # per-host manifest + crc32 checksums
      ckpt_2/                      # a later save
      ckpt_3.corrupt-*/            # quarantined by a failed load
      .tmp.ckpt_4.0.<pid>/         # staging of an in-flight save

Crash safety: every file is written into a private staging dir, fsynced,
then ``os.replace``d into the final dir with ``metadata_{host}.json``
moved LAST — the metadata file is the per-host commit marker, and a
kill -9 at ANY point leaves either no ``ckpt_N`` dir, or one without
metadata, or a complete one; never a half-trusted state.
``load_state_dict`` scans candidates newest-first, verifies checksums
and shard coverage BEFORE touching any target tensor, quarantines
invalid dirs (rename to ``*.corrupt-<n>``, counted + flight-recorded),
and restores the most recent valid checkpoint. A retain-last-K sweep
(``FLAGS_checkpoint_keep``) bounds disk growth. The v1 flat layout
(files directly under ``path``) still loads as the oldest candidate.

Async saves return an ``AsyncSaveHandle`` backed by a tracked
non-daemon writer thread; a captured exception re-raises on
``result()``/``join()`` and, if never collected, on the NEXT save —
failures cannot vanish with a daemon thread.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
import zlib

import jax
import numpy as np

from ..core import flags as flags_mod
from ..core import resilience
from ..core.tensor import Tensor
from ..profiler import metrics as _metrics
from ..profiler import tracing as _tracing
from ..testing import faults

__all__ = ["save_state_dict", "load_state_dict", "async_save_state_dict",
           "AsyncSaveHandle"]

_LEGACY_METADATA = "metadata.json"
_CKPT_RE = re.compile(r"^ckpt_(\d+)$")

_C_SAVES = _metrics.counter("checkpoint.saves")
_C_LOADS = _metrics.counter("checkpoint.loads")
_C_ASYNC_FAIL = _metrics.counter("checkpoint.async.failures")
_C_QUARANTINE = _metrics.counter("checkpoint.quarantined")
_C_RETAIN = _metrics.counter("checkpoint.retention_removed")


class CorruptCheckpointError(ValueError):
    """A candidate checkpoint failed integrity validation (missing
    commit marker, checksum mismatch, unreadable shard, coverage gap)."""


def _flatten(sd, prefix=""):
    flat = {}
    for k, v in sd.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            flat.update(_flatten(v, key))
        else:
            flat[key] = v
    return flat


# -- durability helpers ----------------------------------------------------

def _fsync_path(path):
    """Flush a file's (or directory's) dirty pages to stable storage —
    the rename-based commit is only atomic-after-crash if the renamed
    bytes and the directory entry both hit disk."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _crc32(path):
    crc = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def _ckpt_ids(root):
    ids = []
    try:
        names = os.listdir(root)
    except OSError:
        return ids
    for fn in names:
        m = _CKPT_RE.match(fn)
        if m and os.path.isdir(os.path.join(root, fn)):
            ids.append(int(m.group(1)))
    return sorted(ids)


def _next_ckpt_id(root):
    # count quarantined/corrupt dirs too: a recycled id would make
    # "newest" ambiguous after a quarantine
    last = 0
    try:
        names = os.listdir(root)
    except OSError:
        names = []
    for fn in names:
        m = re.match(r"^\.?(?:tmp\.)?ckpt_(\d+)", fn)
        if m:
            last = max(last, int(m.group(1)))
    return last + 1


# -- save ------------------------------------------------------------------

_async_lock = threading.Lock()
_reserve_lock = threading.Lock()
_async_pending: list["AsyncSaveHandle"] = []
_live_staging: set = set()
_save_seq = [0]  # distinguishes concurrent saves in one process


def _reserve_staging(root, final_dir, host):
    """Create the staging dir SYNCHRONOUSLY (before any writer thread
    runs): the dir both uniquifies this save and reserves its ckpt id —
    `_next_ckpt_id` counts staging names, so an overlapping async save
    scans past it instead of sharing the same id and staging path."""
    with _async_lock:
        _save_seq[0] += 1
        seq = _save_seq[0]
    staging = os.path.join(
        root, f".tmp.{os.path.basename(final_dir)}.{host}."
              f"{os.getpid()}.{seq}")
    with _async_lock:
        _live_staging.add(staging)
    os.makedirs(staging, exist_ok=True)
    return staging


class AsyncSaveHandle:
    """Tracked async-save writer. ``result()``/``join()`` re-raise the
    writer's exception; an uncollected failure surfaces on the next
    ``save_state_dict`` call."""

    def __init__(self, path):
        self.path = path
        self._exc = None
        self._thread = None
        self._collected = False

    def done(self):
        th = self._thread
        # ident is None until start(): a created-but-unstarted writer
        # must not read as finished (reap would untrack it)
        return th is not None and th.ident is not None \
            and not th.is_alive()

    def result(self, timeout=None):
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError(
                f"async save to {self.path} still running")
        if self._exc is not None:
            self._collected = True  # seen here: don't resurface later
            raise self._exc
        return self.path

    # drop-in for the daemon-Thread object earlier versions returned
    join = result


def _reap_async():
    """Surface finished-but-uncollected async failures on the caller's
    thread (the 'next save' half of the handle contract). Raises ONE
    failure per call and leaves the rest pending, so no failure is
    ever dropped when several writers died."""
    failed = None
    with _async_lock:  # one critical section: concurrent reaps must
        for h in list(_async_pending):  # not double-remove a handle
            if not h.done():
                continue
            if h._exc is not None and not h._collected:
                if failed is None:
                    failed = h
                    _async_pending.remove(h)
                # further failures stay pending for the NEXT reap
            else:
                _async_pending.remove(h)
    if failed is not None:
        raise RuntimeError(
            f"previous async save_state_dict to {failed.path} "
            "failed") from failed._exc


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, async_save=False):
    """Write one crash-safe checkpoint under root ``path``.

    Multi-host safe: every host writes ``shards_{host}.npz`` with its
    addressable shards and ``metadata_{host}.json`` describing them; no
    host needs to see another host's shards. Multi-host runs should
    pass an agreed ``unique_id`` (the step number) so hosts commit into
    the same ``ckpt_<id>`` dir; single-host saves auto-increment.

    Returns ``None``, or an :class:`AsyncSaveHandle` when
    ``async_save=True``.
    """
    _reap_async()
    os.makedirs(path, exist_ok=True)
    flat = _flatten(state_dict)
    host = jax.process_index()
    shard_fn = f"shards_{host}.npz"
    meta = {"format": 2, "tensors": {}, "host": host,
            "num_hosts": jax.process_count()}
    arrays = {}
    for name, t in flat.items():
        if isinstance(t, Tensor):
            arr = t._data
        elif isinstance(t, (int, float, str)):
            meta["tensors"][name] = {"scalar": t}
            continue
        else:
            arr = t
        arr = jax.device_get(arr) if not isinstance(arr, jax.Array) else arr
        entry = {"shape": list(np.shape(arr)),
                 "dtype": str(getattr(arr, "dtype", "float32")),
                 "shards": []}
        if isinstance(arr, jax.Array) and hasattr(arr, "addressable_shards"):
            seen_indices = set()
            for i, sh in enumerate(arr.addressable_shards):
                idx = tuple(
                    (0 if s.start is None else s.start,
                     dim if s.stop is None else s.stop)
                    for s, dim in zip(sh.index, arr.shape)) if sh.index \
                    else ()
                if idx in seen_indices:  # dedup locally-replicated shards
                    continue
                seen_indices.add(idx)
                key = f"{name}::{host}::{i}"
                arrays[key] = np.asarray(sh.data)
                entry["shards"].append({"key": key, "index": list(idx),
                                        "host": host, "file": shard_fn})
        else:
            key = f"{name}::{host}::0"
            arrays[key] = np.asarray(arr)
            entry["shards"].append(
                {"key": key, "file": shard_fn,
                 "index": [[0, d] for d in np.shape(arr)], "host": host})
        meta["tensors"][name] = entry

    # id choice + staging reservation are one critical section: the
    # staging dir is what makes the chosen id visible to the next
    # scan, so a concurrent save in another thread must not scan
    # between the two
    with _reserve_lock:
        if unique_id is not None:
            final_dir = os.path.join(path, f"ckpt_{int(unique_id)}")
        elif jax.process_count() > 1:
            # hosts cannot agree on a scan-derived id without
            # coordination (two racing saves would split one checkpoint
            # across two ids, and the loader would quarantine both
            # halves) — fall back to the v1 flat layout, which needs no
            # agreement; versioned multi-host saves require an agreed
            # unique_id (the step)
            final_dir = path
        else:
            final_dir = os.path.join(path, f"ckpt_{_next_ckpt_id(path)}")
        staging = _reserve_staging(path, final_dir, host)

    if async_save:
        handle = AsyncSaveHandle(final_dir)

        def _run():
            try:
                _write_commit(path, final_dir, host, shard_fn, arrays,
                              meta, staging)
                _retention_sweep(path, host)
            except BaseException as e:  # noqa: BLE001 — held for result()
                handle._exc = e
                _C_ASYNC_FAIL.inc()
                resilience.degrade("checkpoint.async_save",
                                   detail=final_dir, exc=e)

        th = threading.Thread(target=_run, daemon=False,
                              name="paddle-tpu-ckpt-writer")
        handle._thread = th
        with _async_lock:
            _async_pending.append(handle)
        th.start()
        return handle

    # child span when a trace is active (a checkpoint inside a traced
    # request/step); the async path runs on the writer thread, which
    # has no ambient context — its lifecycle is visible through the
    # checkpoint.* counters and degrade events instead
    with _tracing.span("checkpoint.save", dir=final_dir):
        _write_commit(path, final_dir, host, shard_fn, arrays, meta,
                      staging)
        _retention_sweep(path, host)
    return None


def _write_commit(root, final_dir, host, shard_fn, arrays, meta,
                  staging):
    """Stage -> fsync -> rename, metadata last (the commit marker)."""
    try:
        shard_path = os.path.join(staging, shard_fn)
        faults.site("checkpoint.write_shards")
        np.savez(shard_path, **arrays)
        faults.site("checkpoint.fsync")
        _fsync_path(shard_path)
        meta["files"] = {shard_fn: {"crc32": _crc32(shard_path),
                                    "bytes": os.path.getsize(shard_path)}}
        meta_fn = f"metadata_{host}.json"
        meta_path = os.path.join(staging, meta_fn)
        faults.site("checkpoint.write_meta")
        with open(meta_path, "w") as f:
            json.dump(meta, f)
            f.flush()
            os.fsync(f.fileno())
        faults.site("checkpoint.commit")
        os.makedirs(final_dir, exist_ok=True)
        # re-saving an already-committed id (or the flat layout) must
        # not tear the old copy: move the old METADATA aside first so
        # the dir is never [new shard + old manifest] — any crash from
        # here leaves either the old commit intact (.bak not yet made)
        # or an uncommitted dir the loader skips, old bytes preserved
        baks = []
        try:
            for fn in (meta_fn, shard_fn):
                p = os.path.join(final_dir, fn)
                if os.path.exists(p):
                    os.replace(p, p + ".bak")
                    baks.append(p)
            os.replace(shard_path, os.path.join(final_dir, shard_fn))
            # metadata rename is the per-host commit point: a crash
            # before this line leaves a dir the loader treats as invalid
            os.replace(meta_path, os.path.join(final_dir, meta_fn))
        except BaseException:
            # non-crash failure mid-recommit: put the old commit back
            # (shard before metadata, so the manifest only reappears
            # over matching bytes); a true kill -9 can't run this, and
            # the loader then skips/falls back as documented
            for p in reversed(baks):
                try:
                    os.replace(p + ".bak", p)
                except OSError:
                    pass
            raise
        _fsync_path(final_dir)
        _fsync_path(root)
        for p in baks:
            try:
                os.remove(p + ".bak")
            except OSError:
                pass
        _C_SAVES.inc()
    finally:
        shutil.rmtree(staging, ignore_errors=True)
        with _async_lock:
            _live_staging.discard(staging)


def _retention_sweep(root, host):
    """Keep the last ``FLAGS_checkpoint_keep`` committed checkpoints
    (host 0 only — one retention sweeper per shared filesystem); EVERY
    host reaps its own dead-writer staging dirs, since only the owner
    can tell a crashed save from an in-flight one."""
    faults.site("checkpoint.retention")
    if host == 0:
        keep = int(flags_mod.flag("FLAGS_checkpoint_keep"))
        if keep > 0:
            for i in _ckpt_ids(root)[:-keep]:
                shutil.rmtree(os.path.join(root, f"ckpt_{i}"),
                              ignore_errors=True)
                _C_RETAIN.inc()
    # orphaned staging: only THIS host's dirs whose writer process is
    # gone — another host's (or a live async writer's) staging on a
    # shared filesystem is an in-flight save, not garbage. listdir
    # FIRST, live-set second: writers register in _live_staging before
    # mkdir, so any dir the listing caught is in a snapshot taken after
    names = os.listdir(root)
    with _async_lock:
        live = set(_live_staging)
    for fn in names:
        p = os.path.join(root, fn)
        m = re.match(r"^\.tmp\..+\.(\d+)\.(\d+)\.(\d+)$", fn)
        if m is None or p in live or int(m.group(1)) != host:
            continue
        pid = int(m.group(2))
        if pid != os.getpid():
            try:
                os.kill(pid, 0)
                continue  # writer still alive
            except ProcessLookupError:
                pass
            except OSError:
                continue  # can't tell: leave it
        shutil.rmtree(p, ignore_errors=True)


def async_save_state_dict(state_dict, path, **kw):
    return save_state_dict(state_dict, path, async_save=True, **kw)


# -- load ------------------------------------------------------------------

def _read_metadata(path):
    """Union all per-host metadata files; returns (tensors, metas).
    Raises CorruptCheckpointError when no metadata exists (an
    uncommitted / torn checkpoint dir)."""
    metas = []
    for fn in sorted(os.listdir(path)):
        if fn.startswith("metadata_") and fn.endswith(".json"):
            try:
                with open(os.path.join(path, fn)) as f:
                    metas.append(json.load(f))
            except (OSError, json.JSONDecodeError) as e:
                raise CorruptCheckpointError(
                    f"unreadable metadata {fn}: {e}") from e
    if not metas and os.path.exists(os.path.join(path, _LEGACY_METADATA)):
        with open(os.path.join(path, _LEGACY_METADATA)) as f:
            metas.append(json.load(f))
    if not metas:
        raise CorruptCheckpointError(
            f"no metadata (uncommitted checkpoint) in {path}")
    merged = {}
    for m in metas:
        default_file = f"shards_{m.get('host', 0)}.npz"
        for name, entry in m["tensors"].items():
            if "scalar" in entry:
                merged.setdefault(name, entry)
                continue
            tgt = merged.setdefault(
                name, {"shape": entry["shape"], "dtype": entry["dtype"],
                       "shards": []})
            seen = {tuple(map(tuple, s["index"])) for s in tgt["shards"]}
            for sh in entry["shards"]:
                idx = tuple(map(tuple, sh["index"]))
                if idx in seen:  # same range replicated on another host
                    continue
                seen.add(idx)
                sh = dict(sh)
                sh.setdefault("file", default_file)
                tgt["shards"].append(sh)
    return merged, metas


def _verify_checksums(path, metas):
    """v2 manifests record per-file crc32: any referenced file must
    exist and match before a single byte is trusted."""
    for m in metas:
        for fn, info in (m.get("files") or {}).items():
            p = os.path.join(path, fn)
            if not os.path.exists(p):
                raise CorruptCheckpointError(
                    f"manifest references missing file {fn}")
            crc = _crc32(p)
            if crc != int(info.get("crc32", crc)):
                raise CorruptCheckpointError(
                    f"checksum mismatch for {fn}: "
                    f"{crc:#010x} != {int(info['crc32']):#010x}")


def _union_elems(ranges, shape):
    """Elements covered by the UNION of axis-aligned index boxes
    (each ``[(lo, hi), ...]`` per dim), O(#boxes * #grid-cells) via
    coordinate compression — no O(numel) mask allocation. An empty
    range list means the box covers the whole array (the loader
    assigns it with ``...``)."""
    if ranges and any(len(r) == 0 for r in ranges):
        ranges = [r for r in ranges if r] + \
            [[(0, d) for d in shape]]  # normalize full-cover boxes
    if not shape:
        return 1 if ranges else 0
    edges = []
    for d, dim in enumerate(shape):
        es = {0, dim}
        for r in ranges:
            es.add(min(max(r[d][0], 0), dim))
            es.add(min(max(r[d][1], 0), dim))
        edges.append(sorted(es))
    import itertools
    total = 0
    for cell in itertools.product(*(range(len(e) - 1) for e in edges)):
        lo = [edges[d][c] for d, c in enumerate(cell)]
        hi = [edges[d][c + 1] for d, c in enumerate(cell)]
        if any(h <= l for l, h in zip(lo, hi)):
            continue
        for r in ranges:
            if all(r[d][0] <= lo[d] and hi[d] <= r[d][1]
                   for d in range(len(shape))):
                vol = 1
                for l, h in zip(lo, hi):
                    vol *= h - l
                total += vol
                break
    return total


def _assemble(flat_targets, path):
    """Validate + reassemble every target tensor's full array from
    ``path``. Pure read phase: raises CorruptCheckpointError without
    having touched any target, so a corrupt candidate can be skipped
    with the state_dict intact."""
    tensors, metas = _read_metadata(path)
    _verify_checksums(path, metas)
    files = {}

    def lookup(shard):
        fn = shard["file"]
        if fn not in files:
            files[fn] = np.load(os.path.join(path, fn))
        return files[fn][shard["key"]]

    import ml_dtypes
    out = {}
    try:
        for name, target in flat_targets.items():
            if name not in tensors:
                continue
            entry = tensors[name]
            if "scalar" in entry:
                continue
            dtype = entry["dtype"]
            np_dtype = getattr(ml_dtypes, dtype) if "bfloat16" in dtype or \
                "float8" in dtype else np.dtype(dtype)
            full = np.zeros(entry["shape"], np_dtype)
            for sh in entry["shards"]:
                data = lookup(sh)
                sl = tuple(slice(lo, hi) for lo, hi in sh["index"]) or ...
                full[sl] = data
            # coverage by the UNION of shard index ranges: summing
            # per-shard element counts double-counts overlap, letting
            # "overlapping shards + one missing" pass validation
            expected = int(np.prod(entry["shape"]))  # 0: nothing to cover
            n_cov = _union_elems(
                [[tuple(map(int, ix)) for ix in sh["index"]]
                 for sh in entry["shards"]], tuple(entry["shape"]))
            if expected > 0 and n_cov < expected:
                raise CorruptCheckpointError(
                    f"checkpoint shard(s) missing for '{name}': covered "
                    f"{n_cov}/{expected} elements — a host's "
                    "shard/metadata file is absent from the checkpoint "
                    "directory")
            out[name] = full
    except CorruptCheckpointError:
        raise
    except Exception as e:  # torn npz / bad key / shape mismatch
        raise CorruptCheckpointError(
            f"unreadable shard data in {path}: "
            f"{type(e).__name__}: {e}") from e
    finally:
        for f in files.values():  # NpzFile handles hold the zip open
            f.close()
    return out


def _save_in_flight(root, cand):
    """True while any host's staging dir for ``cand``'s id exists — the
    save may still commit, so an invalid-looking candidate must be
    skipped, not quarantined. (A kill -9 leaves its staging behind too;
    that save stays 'in flight' until the owner host's next retention
    sweep reaps the dead writer's dir, after which a load may
    quarantine the torn commit.)"""
    prefix = f".tmp.{os.path.basename(cand)}."
    try:
        return any(fn.startswith(prefix) for fn in os.listdir(root))
    except OSError:
        return False


def _quarantine(root, cand, err):
    """Rename an invalid ckpt dir out of the candidate namespace so the
    next scan skips it; keep the bytes for forensics."""
    dst = cand + ".corrupt"
    n = 0
    while os.path.exists(dst):
        n += 1
        dst = f"{cand}.corrupt-{n}"
    try:
        os.replace(cand, dst)
    except OSError:
        return
    _C_QUARANTINE.inc()
    resilience.degrade("checkpoint.quarantine",
                       detail=os.path.basename(cand), exc=err)


def _candidates(path):
    """Candidate checkpoint dirs, newest committed first; the legacy
    flat layout (v1 files directly under ``path``) is the fallback."""
    cands = [os.path.join(path, f"ckpt_{i}")
             for i in reversed(_ckpt_ids(path))]
    for fn in os.listdir(path):
        if fn == _LEGACY_METADATA or (fn.startswith("metadata_")
                                      and fn.endswith(".json")):
            cands.append(path)
            break
    return cands


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None):
    """Fill ``state_dict``'s tensors in place from the most recent VALID
    checkpoint under root ``path``, resharding to each target tensor's
    current sharding (any source strategy). Invalid candidates are
    quarantined and the scan falls back to the previous save; target
    tensors are only mutated once a candidate fully validates."""
    flat = _flatten(state_dict)
    cands = _candidates(path)
    if not cands:
        raise FileNotFoundError(f"no checkpoint found under {path}")
    last_err = None
    for cand in cands:
        try:
            # one span per candidate attempt: a quarantined dir shows
            # up as an "error" span preceding the successful load
            with _tracing.span("checkpoint.load", dir=cand):
                values = _assemble(flat, cand)
        except (CorruptCheckpointError, OSError) as e:
            # OSError: the candidate vanished mid-scan (concurrent
            # quarantine / retention) — fall back like any bad dir
            last_err = e
            if cand == path:
                # legacy flat layout IS the root: nothing to fall back
                # to and renaming the user's directory would be rude
                raise
            if _save_in_flight(path, cand):
                # a writer is still staging for this id (concurrent
                # async save / another host mid-commit): incomplete,
                # not corrupt — skip it without destroying the commit
                continue
            _quarantine(path, cand, e)
            continue
        for name, full in values.items():
            target = flat[name]
            if isinstance(target, Tensor):
                arr = full
                if getattr(target._data, "sharding", None) is not None \
                        and not isinstance(target._data, jax.core.Tracer):
                    arr = jax.device_put(full, target._data.sharding)
                target._rebind(arr if isinstance(arr, jax.Array)
                               else jax.numpy.asarray(arr))
        _C_LOADS.inc()
        return state_dict
    raise CorruptCheckpointError(
        f"no valid checkpoint under {path}; last error: {last_err}")
