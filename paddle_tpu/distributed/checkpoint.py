"""Distributed (sharded) checkpoint.

Parity: reference `python/paddle/distributed/checkpoint/` —
`save_state_dict` (save_state_dict.py:100: per-rank local shards + global
metadata, replicated-tensor dedup :72) and `load_state_dict` (reshards
across mismatched meshes/strategies at load).

TPU-first: the single-controller runtime holds global (sharded) arrays, so
"shards" are the addressable shards of each jax.Array. Each HOST writes
only its addressable shards plus its own ``metadata_{host}.json`` (the
reference's per-rank `.distcp` + global metadata, without needing a
cross-host barrier); the loader unions all per-host metadata files. Shard
keys are host-qualified and each shard entry records its source file, so
same-named shards from different hosts can never collide. Loading
reassembles the global array and `device_put`s it to the TARGET sharding —
cross-strategy resharding for free (the reference needs explicit reshard
functions). Async save runs on a background thread (orbax-style), parity
with the reference's async_save.
"""

from __future__ import annotations

import json
import os
import threading

import jax
import numpy as np

from ..core.tensor import Tensor

__all__ = ["save_state_dict", "load_state_dict", "async_save_state_dict"]

_LEGACY_METADATA = "metadata.json"


def _flatten(sd, prefix=""):
    flat = {}
    for k, v in sd.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            flat.update(_flatten(v, key))
        else:
            flat[key] = v
    return flat


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, async_save=False):
    """Write sharded checkpoint to directory ``path``.

    Multi-host safe: every host writes ``shards_{host}.npz`` with its
    addressable shards and ``metadata_{host}.json`` describing them; no
    host needs to see another host's shards.
    """
    os.makedirs(path, exist_ok=True)
    flat = _flatten(state_dict)
    host = jax.process_index()
    shard_fn = f"shards_{host}.npz"
    meta = {"tensors": {}, "host": host, "num_hosts": jax.process_count()}
    arrays = {}
    for name, t in flat.items():
        if isinstance(t, Tensor):
            arr = t._data
        elif isinstance(t, (int, float, str)):
            meta["tensors"][name] = {"scalar": t}
            continue
        else:
            arr = t
        arr = jax.device_get(arr) if not isinstance(arr, jax.Array) else arr
        entry = {"shape": list(np.shape(arr)),
                 "dtype": str(getattr(arr, "dtype", "float32")),
                 "shards": []}
        if isinstance(arr, jax.Array) and hasattr(arr, "addressable_shards"):
            seen_indices = set()
            for i, sh in enumerate(arr.addressable_shards):
                idx = tuple(
                    (0 if s.start is None else s.start,
                     dim if s.stop is None else s.stop)
                    for s, dim in zip(sh.index, arr.shape)) if sh.index \
                    else ()
                if idx in seen_indices:  # dedup locally-replicated shards
                    continue
                seen_indices.add(idx)
                key = f"{name}::{host}::{i}"
                arrays[key] = np.asarray(sh.data)
                entry["shards"].append({"key": key, "index": list(idx),
                                        "host": host, "file": shard_fn})
        else:
            key = f"{name}::{host}::0"
            arrays[key] = np.asarray(arr)
            entry["shards"].append(
                {"key": key, "file": shard_fn,
                 "index": [[0, d] for d in np.shape(arr)], "host": host})
        meta["tensors"][name] = entry

    def _write():
        np.savez(os.path.join(path, shard_fn), **arrays)
        with open(os.path.join(path, f"metadata_{host}.json"), "w") as f:
            json.dump(meta, f)

    if async_save:
        th = threading.Thread(target=_write, daemon=True)
        th.start()
        return th
    _write()


def async_save_state_dict(state_dict, path, **kw):
    return save_state_dict(state_dict, path, async_save=True, **kw)


def _read_metadata(path):
    """Union all per-host metadata files (legacy single-file fallback)."""
    metas = []
    for fn in sorted(os.listdir(path)):
        if fn.startswith("metadata_") and fn.endswith(".json"):
            with open(os.path.join(path, fn)) as f:
                metas.append(json.load(f))
    if not metas and os.path.exists(os.path.join(path, _LEGACY_METADATA)):
        with open(os.path.join(path, _LEGACY_METADATA)) as f:
            metas.append(json.load(f))
    merged = {}
    for m in metas:
        default_file = f"shards_{m.get('host', 0)}.npz"
        for name, entry in m["tensors"].items():
            if "scalar" in entry:
                merged.setdefault(name, entry)
                continue
            tgt = merged.setdefault(
                name, {"shape": entry["shape"], "dtype": entry["dtype"],
                       "shards": []})
            seen = {tuple(map(tuple, s["index"])) for s in tgt["shards"]}
            for sh in entry["shards"]:
                idx = tuple(map(tuple, sh["index"]))
                if idx in seen:  # same range replicated on another host
                    continue
                seen.add(idx)
                sh = dict(sh)
                sh.setdefault("file", default_file)
                tgt["shards"].append(sh)
    return merged


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None):
    """Fill ``state_dict``'s tensors in place from ``path``, resharding to
    each target tensor's current sharding (any source strategy)."""
    tensors = _read_metadata(path)
    files = {}

    def lookup(shard):
        fn = shard["file"]
        if fn not in files:
            files[fn] = np.load(os.path.join(path, fn))
        return files[fn][shard["key"]]

    flat = _flatten(state_dict)
    for name, target in flat.items():
        if name not in tensors:
            continue
        entry = tensors[name]
        if "scalar" in entry:
            continue
        import ml_dtypes
        dtype = entry["dtype"]
        np_dtype = getattr(ml_dtypes, dtype) if "bfloat16" in dtype or \
            "float8" in dtype else np.dtype(dtype)
        full = np.zeros(entry["shape"], np_dtype)
        filled = 0
        for sh in entry["shards"]:
            data = lookup(sh)
            sl = tuple(slice(lo, hi) for lo, hi in sh["index"]) or ...
            full[sl] = data
            filled += int(np.prod(np.shape(data))) or 1
        expected = int(np.prod(entry["shape"])) or 1
        if filled < expected:
            raise ValueError(
                f"checkpoint shard(s) missing for '{name}': covered "
                f"{filled}/{expected} elements — a host's shard/metadata "
                "file is absent from the checkpoint directory")
        if isinstance(target, Tensor):
            arr = full
            if getattr(target._data, "sharding", None) is not None and \
                    not isinstance(target._data, jax.core.Tracer):
                arr = jax.device_put(full, target._data.sharding)
            target._rebind(arr if isinstance(arr, jax.Array)
                           else jax.numpy.asarray(arr))
    return state_dict
