"""Pipeline parallelism, TPU-native.

Parity target: the reference's PP engine — `PipelineLayer`
(pp_layers.py:257), 1F1B/interleave schedules (pipeline_parallel.py:545,
1136), p2p via batch_isend_irecv (p2p_communication.py), and the C++
fleet_executor 1F1B interceptors (SURVEY.md §2.1).

TPU-first redesign: NCCL-style imperative p2p does not exist under XLA; a
pipeline is instead expressed INSIDE the compiled program as a microbatch
loop over a `shard_map` region on the `pp` mesh axis:

- every stage's block parameters are STACKED on a leading layers axis that
  is sharded over `pp` (each device holds its stage's contiguous slice);
- one fused `lax.scan` loop runs the GPipe/FThenB schedule: at tick t a
  stage computes its micro-step and hands the activation to the next stage
  with `lax.ppermute` (the XLA-native batch_isend_irecv);
- the loop is differentiable — `jax.vjp` through ppermute IS the backward
  pipeline (reversed ring), so fwd+bwd+optimizer still compile into ONE
  XLA program, with XLA overlapping the ICI transfer with stage compute;
- embedding runs before the loop and the LM head after it, each under
  plain GSPMD sharding (their params live replicated on the pp axis).

Bubble fraction matches GPipe: (P-1)/(M+P-1); raise micro-batch count M to
amortize, and wrap blocks in remat for the 1F1B memory profile.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import nn
from ..core.dispatch import apply
from ..core.tensor import Parameter, Tensor
from .api import shard_tensor
from .mesh import ProcessMesh
from .placement import Replicate, Shard

__all__ = ["PipelineDecoderLM"]


def _functional_call(layer, params, *xs):
    """Run ``layer`` with ``params`` (dict name->array) swapped in;
    trace-safe (same rebinding trick as jit.TrainStep). Gradients flow
    through the pure wrapper, not the tape, so params are frozen here."""
    items = list(layer.named_parameters())
    restore = []
    try:
        for name, p in items:
            restore.append((p, p._data, p._node, p.stop_gradient))
            p._data = params[name]
            p._node = None
            p.stop_gradient = True
        args = [Tensor(x) if not isinstance(x, Tensor) else x for x in xs]
        out = layer(*args)
        return out._data if isinstance(out, Tensor) else out
    finally:
        for p, data, node, sg in restore:
            p._data = data
            p._node = node
            p.stop_gradient = sg


class PipelineDecoderLM(nn.Layer):
    """Decoder-LM pipeline wrapper.

    ``embed``: Layer mapping int ids -> hidden states
    ``blocks``: LayerList of IDENTICAL blocks (length L, L % pp == 0) —
        stacked on a leading axis and sharded over ``pp``
    ``head``: Layer mapping final hidden -> logits
    ``loss_fn(logits, labels) -> scalar Tensor`` (mean reduction)

    Block parameters are re-registered as stacked [L, ...] Parameters with
    Shard(0) on the pp axis, so ``parameters()`` is pipeline-ready and any
    Optimizer / (Sharded)TrainStep works unchanged.
    """

    def __init__(self, embed, blocks, head, loss_fn, mesh: ProcessMesh,
                 pp_axis="pp", num_microbatches=None):
        super().__init__()
        self.embed = embed
        self.head = head
        self._loss_fn = loss_fn
        self._mesh = mesh
        self._pp_axis = pp_axis
        self._pp = mesh.get_dim_size(pp_axis)
        self._n_micro = num_microbatches or self._pp
        self._template = blocks[0]
        self._n_layers = len(blocks)
        assert self._n_layers % self._pp == 0, \
            "layer count must divide pp degree"

        names = [n for n, _ in blocks[0].named_parameters()]
        self._block_param_names = names
        self._stacked = nn.ParameterList()
        pp_idx = mesh.dim_names.index(pp_axis)
        for name in names:
            arrs = [dict(b.named_parameters())[name]._data for b in blocks]
            stacked = Parameter(jnp.stack(arrs, 0))
            stacked.name = "blocks." + name
            placements = [Replicate()] * mesh.ndim
            placements[pp_idx] = Shard(0)
            shard_tensor(stacked, mesh, placements)
            self._stacked.append(stacked)

    def stacked_parameters(self):
        return list(self._stacked)

    def unstack_block_state(self):
        """[L, ...] stacked arrays -> per-block state dicts (for
        checkpoint interop with the unstacked model form)."""
        out = []
        for i in range(self._n_layers):
            out.append({
                name: Tensor(p._data[i])
                for name, p in zip(self._block_param_names, self._stacked)})
        return out

    def forward(self, input_ids):
        raise NotImplementedError(
            "PipelineDecoderLM computes loss inside the pipeline; "
            "use .loss(ids, labels)")

    def loss(self, input_ids, labels):
        mesh = self._mesh
        pp_axis = self._pp_axis
        pp = self._pp
        M = self._n_micro
        template = self._template
        embed, head, loss_fn = self.embed, self.head, self._loss_fn
        names = self._block_param_names

        embed_items = list(embed.named_parameters())
        head_items = list(head.named_parameters())
        n_embed = len(embed_items)
        n_head = len(head_items)

        def pure(ids, lab, *flat_params):
            e_params = dict(zip([n for n, _ in embed_items],
                                flat_params[:n_embed]))
            h_params = dict(zip([n for n, _ in head_items],
                                flat_params[n_embed:n_embed + n_head]))
            b_params = dict(zip(names, flat_params[n_embed + n_head:]))

            x = _functional_call(embed, e_params, ids)
            mb = ids.shape[0] // M
            x_micro = x.reshape(M, mb, *x.shape[1:])

            block_spec = jax.tree.map(lambda _: P(pp_axis), b_params)

            def pipe_body(x_all, local_blocks):
                stage = lax.axis_index(pp_axis)
                is_first = stage == 0
                is_last = stage == pp - 1
                perm = [(i, (i + 1) % pp) for i in range(pp)]

                def run_stage(h):
                    def scan_block(h, one_block):
                        return _functional_call(template, one_block,
                                                h), None
                    h, _ = lax.scan(scan_block, h, local_blocks)
                    return h

                def tick(carry, t):
                    src_idx = jnp.clip(t, 0, M - 1)
                    inp = jnp.where(
                        is_first,
                        lax.dynamic_index_in_dim(x_all, src_idx, 0,
                                                 keepdims=False),
                        carry)
                    out = run_stage(inp)
                    collected = jnp.where(
                        jnp.logical_and(is_last, t >= pp - 1), out, 0.0)
                    carry = lax.ppermute(out, pp_axis, perm)
                    return carry, collected

                _, outs = lax.scan(tick, jnp.zeros_like(x_all[0]),
                                   jnp.arange(M + pp - 1))
                # outs[pp-1:] are the M last-stage results (zeros
                # elsewhere); share across stages so the head can run
                # under plain GSPMD afterwards
                final = lax.psum(outs[pp - 1:], pp_axis)
                return final

            final = jax.shard_map(
                pipe_body, mesh=mesh.jax_mesh,
                in_specs=(P(), block_spec), out_specs=P(),
                check_vma=False)(x_micro, b_params)
            hidden = final.reshape(ids.shape[0], *final.shape[2:])
            logits = _functional_call(head, h_params, hidden)
            out = loss_fn(Tensor(logits), Tensor(lab))
            return out._data if isinstance(out, Tensor) else out

        flat = ([p for _, p in embed_items] + [p for _, p in head_items] +
                list(self._stacked))
        return apply(pure, input_ids, labels, *flat, name="pipeline_loss")
