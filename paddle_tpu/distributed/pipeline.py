"""Pipeline parallelism, TPU-native.

Parity target: the reference's PP engine — `PipelineLayer`
(pp_layers.py:257), 1F1B/interleave schedules (pipeline_parallel.py:545,
1136), p2p via batch_isend_irecv (p2p_communication.py), and the C++
fleet_executor 1F1B interceptors (SURVEY.md §2.1).

TPU-first redesign: NCCL-style imperative p2p does not exist under XLA; a
pipeline is instead expressed INSIDE the compiled program as a microbatch
loop over a `shard_map` region on the `pp` mesh axis:

- every stage's block parameters are STACKED on a leading layers axis that
  is sharded over `pp` (each device holds its stage's contiguous slice);
- one fused `lax.scan` loop runs the GPipe/FThenB schedule: at tick t a
  stage computes its micro-step and hands the activation to the next stage
  with `lax.ppermute` (the XLA-native batch_isend_irecv);
- the loop is differentiable — `jax.vjp` through ppermute IS the backward
  pipeline (reversed ring), so fwd+bwd+optimizer still compile into ONE
  XLA program, with XLA overlapping the ICI transfer with stage compute;
- embedding runs before the loop and the LM head after it, each under
  plain GSPMD sharding (their params live replicated on the pp axis).

Bubble fraction matches GPipe: (P-1)/(M+P-1); raise micro-batch count M to
amortize, and wrap blocks in remat for the 1F1B memory profile.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import nn
from ..core.autograd import Node, is_grad_enabled
from ..core.dispatch import apply
from ..core.tensor import Parameter, Tensor
from .api import shard_tensor
from .mesh import ProcessMesh
from .pipeline_schedule import build_schedule
from .placement import Replicate, Shard

__all__ = ["PipelineDecoderLM", "LayerDesc", "SharedLayerDesc"]


def _functional_call(layer, params, *xs):
    """Run ``layer`` with ``params`` (dict name->array) swapped in;
    trace-safe (same rebinding trick as jit.TrainStep). Gradients flow
    through the pure wrapper, not the tape, so params are frozen here."""
    items = list(layer.named_parameters())
    restore = []
    try:
        for name, p in items:
            restore.append((p, p._data, p._node, p.stop_gradient))
            p._data = params[name]
            p._node = None
            p.stop_gradient = True
        args = [Tensor(x) if not isinstance(x, Tensor) else x for x in xs]
        out = layer(*args)
        return out._data if isinstance(out, Tensor) else out
    finally:
        for p, data, node, sg in restore:
            p._data = data
            p._node = node
            p.stop_gradient = sg


class LayerDesc:
    """Build-on-demand layer descriptor (reference `LayerDesc`,
    fleet/meta_parallel/parallel_layers/pp_layers.py:56): lets a pipeline
    be declared without materializing every stage's parameters first."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Descriptor for a layer whose ``shared_weight_attr`` Parameter is
    TIED across pipeline positions with the same ``key`` (reference
    `SharedLayerDesc` pp_layers.py:76 — tied input/output embeddings).

    TPU-first: instead of the reference's cross-stage allreduce of the
    shared weight's gradient, both positions hold the SAME Parameter
    object (replicated over pp under GSPMD); the engine's grad psum over
    pp plus the tape's duplicate-parent accumulation realize the tied
    gradient sum exactly.
    """

    def __init__(self, key, layer_cls, *args, forward_func=None,
                 shared_weight_attr="weight", **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr

    def build(self, shared_registry=None):
        import types

        layer = super().build()
        if shared_registry is not None:
            if self.key in shared_registry:
                # tie: rebind this layer's shared weight to the first
                # occurrence's Parameter object
                owner = shared_registry[self.key]
                shared = getattr(owner, self.shared_weight_attr)
                setattr(layer, self.shared_weight_attr, shared)
            else:
                shared_registry[self.key] = layer
        if self.forward_func is not None:
            # reference pp_layers.py:76: forward_func replaces the layer's
            # forward at THIS pipeline position (e.g. the tied embedding
            # running as a logits head)
            layer.forward = types.MethodType(self.forward_func, layer)
        return layer


class PipelineDecoderLM(nn.Layer):
    """Decoder-LM pipeline wrapper.

    ``embed``: Layer mapping int ids -> hidden states
    ``blocks``: LayerList of IDENTICAL blocks (length L, L % pp == 0) —
        stacked on a leading axis and sharded over ``pp``
    ``head``: Layer mapping final hidden -> logits
    ``loss_fn(logits, labels) -> scalar Tensor`` (mean reduction)

    Block parameters are re-registered as stacked [L, ...] Parameters with
    Shard(0) on the pp axis, so ``parameters()`` is pipeline-ready and any
    Optimizer / (Sharded)TrainStep works unchanged.
    """

    def __init__(self, embed, blocks, head, loss_fn, mesh: ProcessMesh,
                 pp_axis="pp", num_microbatches=None, schedule="gpipe",
                 num_virtual_stages=1):
        super().__init__()
        self.embed = embed
        self.head = head
        self._loss_fn = loss_fn
        self._mesh = mesh
        self._pp_axis = pp_axis
        self._pp = mesh.get_dim_size(pp_axis)
        self._n_micro = num_microbatches or self._pp
        self._template = blocks[0]
        self._n_layers = len(blocks)
        self._schedule = schedule
        self._vpp = num_virtual_stages
        if schedule == "gpipe":
            assert num_virtual_stages == 1, \
                "gpipe schedule has no virtual stages"
            assert self._n_layers % self._pp == 0, \
                "layer count must divide pp degree"
        else:
            assert schedule in ("fthenb", "1f1b", "interleave",
                                "1f1b_packed", "interleave_packed",
                                "zb"), schedule
            self._sched = build_schedule(self._pp, self._vpp,
                                         self._n_micro, schedule)

        # pad to a multiple of P*V virtual-stage rows (identity-masked,
        # parity with the reference's uneven SegmentLayers), then permute
        # rows so each device's contiguous Shard(0) slice is the concat of
        # its V chunks (virtual stage g = c*P + d lives on device d).
        N = self._pp * self._vpp
        L = self._n_layers
        Lpad = -(-L // N) * N
        Lc = Lpad // N
        perm = []
        for d in range(self._pp):
            for c in range(self._vpp):
                g = c * self._pp + d
                perm.extend(range(g * Lc, (g + 1) * Lc))
        self._perm = perm          # stacked row r holds original layer perm[r]
        self._rows_per_chunk = Lc
        self._n_layers_padded = Lpad
        self._layer_mask = np.array(
            [perm[r] < L for r in range(Lpad)], bool)

        # inverse permutation: padded-position j -> engine row index
        self._inv_perm = [0] * Lpad
        for r, j in enumerate(perm):
            self._inv_perm[j] = r

        # Stacked params are STORED in original layer order [L, ...] so
        # state_dicts are schedule-independent (a checkpoint saved under
        # interleave loads into gpipe and vice versa); the engine pads +
        # permutes at entry and inverse-permutes grads on return.
        names = [n for n, _ in blocks[0].named_parameters()]
        self._block_param_names = names
        self._stacked = nn.ParameterList()
        pp_idx = mesh.dim_names.index(pp_axis)
        for name in names:
            arrs = [dict(b.named_parameters())[name]._data for b in blocks]
            stacked = Parameter(jnp.stack(arrs, 0))
            stacked.name = "blocks." + name
            placements = [Replicate()] * mesh.ndim
            if L % self._pp == 0:
                placements[pp_idx] = Shard(0)
            # (uneven L: stored replicated — NamedSharding needs
            # divisibility; the engine pads to Lpad and shards internally)
            shard_tensor(stacked, mesh, placements)
            self._stacked.append(stacked)

    @classmethod
    def from_descs(cls, descs, loss_fn, mesh, pp_axis="pp",
                   num_microbatches=None, schedule="gpipe",
                   num_virtual_stages=1):
        """Build a pipeline from LayerDesc/SharedLayerDesc descriptors
        (reference PipelineLayer(layers=[...]) form): descs[0] is the
        embedding stage, descs[-1] the head stage, the rest identical
        blocks. SharedLayerDescs with the same key share their weight
        Parameter (tied embeddings)."""
        registry = {}

        def build(d):
            if isinstance(d, SharedLayerDesc):
                return d.build(registry)
            if isinstance(d, LayerDesc):
                return d.build()
            return d  # already a Layer

        embed = build(descs[0])
        blocks = nn.LayerList([build(d) for d in descs[1:-1]])
        head = build(descs[-1])
        return cls(embed, blocks, head, loss_fn, mesh, pp_axis=pp_axis,
                   num_microbatches=num_microbatches, schedule=schedule,
                   num_virtual_stages=num_virtual_stages)

    def stacked_parameters(self):
        return list(self._stacked)

    def unstack_block_state(self):
        """[L, ...] stacked arrays -> per-block state dicts (for
        checkpoint interop with the unstacked model form)."""
        out = []
        for i in range(self._n_layers):
            out.append({
                name: Tensor(p._data[i])
                for name, p in zip(self._block_param_names, self._stacked)})
        return out

    def forward(self, input_ids):
        raise NotImplementedError(
            "PipelineDecoderLM computes loss inside the pipeline; "
            "use .loss(ids, labels)")

    def loss(self, input_ids, labels):
        if self._schedule != "gpipe":
            return self._table_loss(input_ids, labels)
        mesh = self._mesh
        pp_axis = self._pp_axis
        pp = self._pp
        M = self._n_micro
        template = self._template
        embed, head, loss_fn = self.embed, self.head, self._loss_fn
        names = self._block_param_names

        embed_items = list(embed.named_parameters())
        head_items = list(head.named_parameters())
        n_embed = len(embed_items)
        n_head = len(head_items)

        def pure(ids, lab, *flat_params):
            e_params = dict(zip([n for n, _ in embed_items],
                                flat_params[:n_embed]))
            h_params = dict(zip([n for n, _ in head_items],
                                flat_params[n_embed:n_embed + n_head]))
            b_params = dict(zip(names, flat_params[n_embed + n_head:]))

            x = _functional_call(embed, e_params, ids)
            mb = ids.shape[0] // M
            x_micro = x.reshape(M, mb, *x.shape[1:])

            block_spec = jax.tree.map(lambda _: P(pp_axis), b_params)

            def pipe_body(x_all, local_blocks):
                stage = lax.axis_index(pp_axis)
                is_first = stage == 0
                is_last = stage == pp - 1
                perm = [(i, (i + 1) % pp) for i in range(pp)]

                def run_stage(h):
                    def scan_block(h, one_block):
                        return _functional_call(template, one_block,
                                                h), None
                    h, _ = lax.scan(scan_block, h, local_blocks)
                    return h

                def tick(carry, t):
                    src_idx = jnp.clip(t, 0, M - 1)
                    inp = jnp.where(
                        is_first,
                        lax.dynamic_index_in_dim(x_all, src_idx, 0,
                                                 keepdims=False),
                        carry)
                    out = run_stage(inp)
                    collected = jnp.where(
                        jnp.logical_and(is_last, t >= pp - 1), out, 0.0)
                    carry = lax.ppermute(out, pp_axis, perm)
                    return carry, collected

                _, outs = lax.scan(tick, jnp.zeros_like(x_all[0]),
                                   jnp.arange(M + pp - 1))
                # outs[pp-1:] are the M last-stage results (zeros
                # elsewhere); share across stages so the head can run
                # under plain GSPMD afterwards
                final = lax.psum(outs[pp - 1:], pp_axis)
                return final

            final = jax.shard_map(
                pipe_body, mesh=mesh.jax_mesh,
                in_specs=(P(), block_spec), out_specs=P(),
                check_vma=False)(x_micro, b_params)
            hidden = final.reshape(ids.shape[0], *final.shape[2:])
            logits = _functional_call(head, h_params, hidden)
            out = loss_fn(Tensor(logits), Tensor(lab))
            return out._data if isinstance(out, Tensor) else out

        flat = ([p for _, p in embed_items] + [p for _, p in head_items] +
                list(self._stacked))
        return apply(pure, input_ids, labels, *flat, name="pipeline_loss")

    # ------------------------------------------------------------------
    # table-driven schedules (fthenb / 1f1b / interleave)
    # ------------------------------------------------------------------

    def _table_loss(self, input_ids, labels):
        """1F1B-family loss: the whole schedule — forwards, per-microbatch
        remat backwards, grad accumulation — runs inside ONE compiled
        shard_map scan following the precomputed tables (reference
        pipeline_parallel.py:545/:1136 semantics). The backward having
        already run, loss.backward() just scales the precomputed grads
        (a hand-built tape Node), so TrainStep/ShardedTrainStep work
        unchanged. Memory: stash depth from the scheduler (~P for 1F1B,
        not M)."""
        ids = input_ids._data if isinstance(input_ids, Tensor) \
            else jnp.asarray(input_ids)
        lab = labels._data if isinstance(labels, Tensor) \
            else jnp.asarray(labels)

        embed_items = list(self.embed.named_parameters())
        head_items = list(self.head.named_parameters())
        e_tensors = [p for _, p in embed_items]
        h_tensors = [p for _, p in head_items]
        b_tensors = list(self._stacked)
        e_arrs = [p._data for p in e_tensors]
        h_arrs = [p._data for p in h_tensors]
        b_arrs = [p._data for p in b_tensors]

        recording = is_grad_enabled() and any(
            not p.stop_gradient
            for p in e_tensors + h_tensors + b_tensors)
        loss_arr, grads_out = self._run_schedule(
            ids, lab, e_arrs, h_arrs, b_arrs, with_backward=recording)

        out = Tensor(loss_arr)
        if recording:
            ge, gh, gb = grads_out
            parents = e_tensors + h_tensors + b_tensors
            grads = list(ge) + list(gh) + list(gb)
            diff = [(p, g) for p, g in zip(parents, grads)
                    if not p.stop_gradient]
            d_parents = [p for p, _ in diff]
            d_grads = [g for _, g in diff]

            def vjp_fn(cts):
                return tuple(g * cts[0] for g in d_grads)

            node = Node(vjp_fn, d_parents,
                        [(loss_arr.shape, loss_arr.dtype)],
                        name=f"pipeline_{self._schedule}")
            out.stop_gradient = False
            out._node = node
            out._out_idx = 0
        return out

    def _run_schedule(self, ids, lab, e_arrs, h_arrs, b_arrs,
                      with_backward=True):
        """Pure jax: returns (loss, (embed grads, head grads, block
        grads)) by following the schedule tables (grads None when
        ``with_backward`` is off — the backward half is not even traced,
        so eval/no_grad pays forward cost only)."""
        mesh = self._mesh
        pp_axis = self._pp_axis
        Pdeg, V, M = self._pp, self._vpp, self._n_micro
        sched = self._sched
        K, K2 = sched.stash_depth, sched.cot_depth
        Lc = self._rows_per_chunk
        L, Lpad = self._n_layers, self._n_layers_padded

        # engine layout: pad [L]->[Lpad] rows (duplicating layer 0 —
        # numerically inert under the mask, NaN-safe unlike zeros), then
        # permute so each device's Shard(0) slice is its V chunks.
        # Stored params stay in original layer order (see __init__).
        perm_idx = jnp.asarray(self._perm, jnp.int32)
        b_arrs = [jnp.concatenate(
            [a] + [a[:1]] * (Lpad - L), 0)[perm_idx] if Lpad > L
            else a[perm_idx] for a in b_arrs]
        embed, head, loss_fn = self.embed, self.head, self._loss_fn
        template = self._template
        names = self._block_param_names
        e_names = [n for n, _ in list(embed.named_parameters())]
        h_names = [n for n, _ in list(head.named_parameters())]

        # data parallelism inside the pipeline region: microbatches are
        # sharded over the "dp" mesh axis (when present) on their batch
        # dim; grads/loss psum over it
        dp_axis = "dp" if ("dp" in mesh.dim_names and
                           mesh.get_dim_size("dp") > 1) else None
        B = ids.shape[0]
        mb = B // M
        assert B % M == 0, f"batch {B} % microbatches {M} != 0"
        if dp_axis is not None and mb % mesh.get_dim_size("dp") != 0:
            dp_axis = None  # microbatch too small to shard: replicate
        DP = mesh.get_dim_size("dp") if dp_axis else 1
        red_axes = (pp_axis, dp_axis) if dp_axis else (pp_axis,)
        ids_micro = ids.reshape(M, mb, *ids.shape[1:])
        lab_micro = lab.reshape(M, mb, *lab.shape[1:])

        # dense schedule tables as device-indexed constants
        has_wgrad = sched.has_wgrad  # zb: deferred weight-grad phase
        tabs = dict(
            fchunk=jnp.asarray(sched.fchunk), fmb=jnp.asarray(sched.fmb),
            bchunk=jnp.asarray(sched.bchunk), bmb=jnp.asarray(sched.bmb),
            rcvf=jnp.asarray(sched.rcvf), rcvb=jnp.asarray(sched.rcvb))
        if has_wgrad:
            tabs["wchunk"] = jnp.asarray(sched.wchunk)
            tabs["wmb"] = jnp.asarray(sched.wmb)
        mask_rows = jnp.asarray(self._layer_mask)  # [Lpad] over all devices

        perm_fwd = [(i, (i + 1) % Pdeg) for i in range(Pdeg)]
        perm_bwd = [(i, (i - 1) % Pdeg) for i in range(Pdeg)]

        def run_chunk(x, rows, row_mask):
            """Apply one chunk's blocks (masked rows are identity)."""
            def scan_block(h, row):
                row_params, m = row
                out = _functional_call(template, dict(zip(names,
                                                          row_params)), h)
                return jnp.where(m, out, h), None
            row_leaves = [r for r in rows]
            h, _ = lax.scan(scan_block, x, (row_leaves, row_mask))
            return h

        def body(ids_m, lab_m, e_p, h_p, b_local):
            d = lax.axis_index(pp_axis)
            # rows of this device: [V * Lc, ...]; chunk c = rows
            # [c*Lc:(c+1)*Lc]. mask rows for this device:
            lmask = lax.dynamic_slice_in_dim(mask_rows, d * V * Lc, V * Lc)

            def embed_fn(f):
                x = _functional_call(embed, dict(zip(e_names, e_p)),
                                     ids_m[f])
                return x

            # probe hidden shape statically via eval_shape on microbatch 0
            probe = jax.eval_shape(embed_fn, 0)
            hshape, hdtype = probe.shape, probe.dtype

            def chunk_fwd(c, x_in, f, e_p_, h_p_, rows):
                """Full chunk-c computation: returns (h_out, loss/M)."""
                if c == 0:
                    x0 = _functional_call(
                        embed, dict(zip(e_names, e_p_)), ids_m[f])
                    x_in = jnp.where(jnp.equal(d, 0), x0, x_in)
                h = run_chunk(x_in, rows, lmask[c * Lc:(c + 1) * Lc])
                # NOTE: chunk_fwd is differentiated (jax.vjp in the
                # backward tick). No pcast may appear in here: the
                # transpose of an invariant->varying pcast is a psum over
                # pp, and inside a stage-divergent cond branch that
                # collective deadlocks the mesh. "Zero" outputs are
                # derived from the (already pp-varying) hidden state
                # instead.
                if c == V - 1:
                    def head_loss(hh):
                        logits = _functional_call(
                            head, dict(zip(h_names, h_p_)), hh)
                        ls = loss_fn(Tensor(logits), Tensor(lab_m[f]))
                        ls = ls._data if isinstance(ls, Tensor) else ls
                        # mean over microbatches AND dp shards of each
                        # microbatch (full-manual: dp is reduced by the
                        # final psum)
                        return (ls / (M * DP)).astype(jnp.float32)

                    def no_loss(hh):
                        return (hh * 0.0).sum().astype(jnp.float32)

                    lval = lax.cond(jnp.equal(d, Pdeg - 1), head_loss,
                                    no_loss, h)
                else:
                    lval = (h * 0.0).sum().astype(jnp.float32)
                return h, lval

            zero_e = jax.tree.map(jnp.zeros_like, tuple(e_p))
            zero_h = jax.tree.map(jnp.zeros_like, tuple(h_p))

            def tick(carry, xs):
                if with_backward:
                    stash, cots, fmsg, bmsg, loss_acc, ge, gh, gb = carry
                else:
                    stash, fmsg, loss_acc = carry
                if has_wgrad and with_backward:
                    fc, fm, bc, bm, rf, rb, wc, wm = xs
                else:
                    fc, fm, bc, bm, rf, rb = xs[:6]

                # --- receive (messages sent at the end of tick t-1) ---
                f_in = jnp.where(jnp.equal(d, 0),
                                 jnp.roll(fmsg, 1, axis=0), fmsg)
                if with_backward:
                    b_in = jnp.where(jnp.equal(d, Pdeg - 1),
                                     jnp.roll(bmsg, -1, axis=0), bmsg)
                for c in range(V):
                    slot = jnp.mod(rf[c], K)
                    stash = stash.at[c, slot].set(
                        jnp.where(rf[c] >= 0, f_in[c], stash[c, slot]))
                    if with_backward:
                        slot2 = jnp.mod(rb[c], K2)
                        cots = cots.at[c, slot2].set(
                            jnp.where(rb[c] >= 0, b_in[c], cots[c, slot2]))

                # --- forward compute ---
                new_fmsg = []
                for c in range(V):
                    rows = [leaf[c * Lc:(c + 1) * Lc] for leaf in b_local]

                    def f_fire(args, c=c, rows=rows):
                        stash_, f_ = args
                        x_in = stash_[c, jnp.mod(f_, K)]
                        h, lval = chunk_fwd(c, x_in, f_, e_p, h_p, rows)
                        return h, lval

                    def f_skip(args, c=c):
                        stash_, _ = args
                        return (jnp.zeros(hshape, hdtype),
                                jnp.zeros((), jnp.float32))

                    h_out, lval = lax.cond(jnp.equal(fc, c), f_fire,
                                           f_skip, (stash, fm))
                    new_fmsg.append(h_out)
                    loss_acc = loss_acc + lval
                fmsg = jnp.stack(new_fmsg, 0)

                # --- backward compute (remat from stash) ---
                if not with_backward:
                    fmsg = lax.ppermute(fmsg, pp_axis, perm_fwd)
                    return (stash, fmsg, loss_acc), None
                new_bmsg = []
                for c in range(V):
                    rows = [leaf[c * Lc:(c + 1) * Lc] for leaf in b_local]

                    if has_wgrad:
                        # zb: B is ACTIVATION-grad only (the critical-path
                        # half); params are constants here, their grads
                        # come from the deferred W phase below
                        def bd_fire(args, c=c, rows=rows):
                            stash_, cots_, b_ = args
                            x_in = stash_[c, jnp.mod(b_, K)]
                            fn = lambda x: chunk_fwd(c, x, b_, e_p, h_p,
                                                     rows)
                            outs, vjp = jax.vjp(fn, x_in)
                            h_out, _ = outs
                            is_final = jnp.logical_and(
                                jnp.equal(d, Pdeg - 1), c == V - 1)
                            cot_h = jnp.where(
                                is_final, jnp.zeros(hshape, hdtype),
                                cots_[c, jnp.mod(b_, K2)].astype(hdtype))
                            cot_l = jnp.where(is_final, 1.0, 0.0).astype(
                                jnp.float32)
                            (d_x,) = vjp((cot_h, cot_l))
                            return d_x

                        def bd_skip(args, c=c):
                            return jnp.zeros(hshape, hdtype)

                        d_x = lax.cond(jnp.equal(bc, c), bd_fire, bd_skip,
                                       (stash, cots, bm))
                        new_bmsg.append(d_x)
                        continue

                    def b_fire(args, c=c, rows=rows):
                        stash_, cots_, b_ = args
                        x_in = stash_[c, jnp.mod(b_, K)]

                        if c == 0 and c == V - 1:
                            fn = lambda x, r, e_, h_: chunk_fwd(
                                c, x, b_, e_, h_, r)
                            outs, vjp = jax.vjp(fn, x_in, rows,
                                                tuple(e_p), tuple(h_p))
                        elif c == 0:
                            fn = lambda x, r, e_: chunk_fwd(
                                c, x, b_, e_, h_p, r)
                            outs, vjp = jax.vjp(fn, x_in, rows,
                                                tuple(e_p))
                        elif c == V - 1:
                            fn = lambda x, r, h_: chunk_fwd(
                                c, x, b_, e_p, h_, r)
                            outs, vjp = jax.vjp(fn, x_in, rows,
                                                tuple(h_p))
                        else:
                            fn = lambda x, r: chunk_fwd(c, x, b_, e_p,
                                                        h_p, r)
                            outs, vjp = jax.vjp(fn, x_in, rows)
                        h_out, _ = outs
                        is_final = jnp.logical_and(
                            jnp.equal(d, Pdeg - 1), c == V - 1)
                        cot_h = jnp.where(is_final,
                                          jnp.zeros(hshape, hdtype),
                                          cots_[c, jnp.mod(b_, K2)]
                                          .astype(hdtype))
                        cot_l = jnp.where(is_final, 1.0, 0.0).astype(
                            jnp.float32)
                        cot = vjp((cot_h, cot_l))
                        d_x = cot[0]
                        d_rows = cot[1]
                        d_e = cot[2] if c == 0 else zero_e
                        d_h = (cot[-1] if c == V - 1 else zero_h)
                        return d_x, d_rows, d_e, d_h

                    def b_skip(args, c=c, rows=rows):
                        return (jnp.zeros(hshape, hdtype),
                                jax.tree.map(jnp.zeros_like, rows),
                                zero_e, zero_h)

                    d_x, d_rows, d_e, d_h = lax.cond(
                        jnp.equal(bc, c), b_fire, b_skip,
                        (stash, cots, bm))
                    new_bmsg.append(d_x)
                    gb = [acc.at[c * Lc:(c + 1) * Lc].add(dr)
                          for acc, dr in zip(gb, d_rows)]
                    ge = jax.tree.map(jnp.add, ge, d_e)
                    gh = jax.tree.map(jnp.add, gh, d_h)
                bmsg = jnp.stack(new_bmsg, 0)

                # --- deferred weight-grad compute (zb only) ---
                if has_wgrad:
                    for c in range(V):
                        rows = [leaf[c * Lc:(c + 1) * Lc]
                                for leaf in b_local]

                        def w_fire(args, c=c, rows=rows):
                            stash_, cots_, w_ = args
                            x_in = stash_[c, jnp.mod(w_, K)]
                            if c == 0 and c == V - 1:
                                fn = lambda r, e_, h_: chunk_fwd(
                                    c, x_in, w_, e_, h_, r)
                                outs, vjp = jax.vjp(
                                    fn, rows, tuple(e_p), tuple(h_p))
                            elif c == 0:
                                fn = lambda r, e_: chunk_fwd(
                                    c, x_in, w_, e_, h_p, r)
                                outs, vjp = jax.vjp(fn, rows, tuple(e_p))
                            elif c == V - 1:
                                fn = lambda r, h_: chunk_fwd(
                                    c, x_in, w_, e_p, h_, r)
                                outs, vjp = jax.vjp(fn, rows, tuple(h_p))
                            else:
                                fn = lambda r: chunk_fwd(c, x_in, w_,
                                                         e_p, h_p, r)
                                outs, vjp = jax.vjp(fn, rows)
                            is_final = jnp.logical_and(
                                jnp.equal(d, Pdeg - 1), c == V - 1)
                            cot_h = jnp.where(
                                is_final, jnp.zeros(hshape, hdtype),
                                cots_[c, jnp.mod(w_, K2)].astype(hdtype))
                            cot_l = jnp.where(is_final, 1.0, 0.0).astype(
                                jnp.float32)
                            cot = vjp((cot_h, cot_l))
                            d_rows = cot[0]
                            d_e = cot[1] if c == 0 else zero_e
                            d_h = (cot[-1] if c == V - 1 else zero_h)
                            return d_rows, d_e, d_h

                        def w_skip(args, c=c, rows=rows):
                            return (jax.tree.map(jnp.zeros_like, rows),
                                    zero_e, zero_h)

                        d_rows, d_e, d_h = lax.cond(
                            jnp.equal(wc, c), w_fire, w_skip,
                            (stash, cots, wm))
                        gb = [acc.at[c * Lc:(c + 1) * Lc].add(dr)
                              for acc, dr in zip(gb, d_rows)]
                        ge = jax.tree.map(jnp.add, ge, d_e)
                        gh = jax.tree.map(jnp.add, gh, d_h)

                # --- ring messages (unconditional) ---
                fmsg = lax.ppermute(fmsg, pp_axis, perm_fwd)
                bmsg = lax.ppermute(bmsg, pp_axis, perm_bwd)
                return (stash, cots, fmsg, bmsg, loss_acc, ge, gh, gb), \
                    None

            stash0 = jnp.zeros((V, K) + hshape, hdtype)
            cots0 = jnp.zeros((V, K2) + hshape, hdtype)
            fmsg0 = jnp.zeros((V,) + hshape, hdtype)
            bmsg0 = jnp.zeros((V,) + hshape, hdtype)
            ge0 = jax.tree.map(jnp.zeros_like, tuple(e_p))
            gh0 = jax.tree.map(jnp.zeros_like, tuple(h_p))
            gb0 = [jnp.zeros_like(leaf) for leaf in b_local]

            tab_keys = ("fchunk", "fmb", "bchunk", "bmb", "rcvf", "rcvb")
            if has_wgrad and with_backward:
                tab_keys = tab_keys + ("wchunk", "wmb")
            d_tabs = [lax.dynamic_index_in_dim(tabs[k], d, 0,
                                               keepdims=False)
                      for k in tab_keys]
            if with_backward:
                carry0 = (stash0, cots0, fmsg0, bmsg0,
                          jnp.zeros((), jnp.float32), ge0, gh0, gb0)
            else:
                carry0 = (stash0, fmsg0, jnp.zeros((), jnp.float32))
            carry, _ = lax.scan(tick, carry0, tuple(d_tabs))
            if not with_backward:
                return lax.psum(carry[-1], red_axes)
            _, _, _, _, loss_acc, ge, gh, gb = carry
            # uniform (device-unconditional) reductions: stages' partial
            # loss / embed / head grads sum over pp, data-parallel
            # partials over dp; block grads are per-stage rows, dp-only
            loss_total = lax.psum(loss_acc, red_axes)
            ge = jax.tree.map(lambda g: lax.psum(g, red_axes), ge)
            gh = jax.tree.map(lambda g: lax.psum(g, red_axes), gh)
            if dp_axis is not None:
                gb = [lax.psum(g, dp_axis) for g in gb]
            return loss_total, ge, gh, gb

        pp_spec = P(pp_axis)
        rep = P()
        data_spec = P(None, dp_axis) if dp_axis is not None else rep
        n_e, n_h = len(e_arrs), len(h_arrs)
        in_specs = (data_spec, data_spec, tuple([rep] * n_e),
                    tuple([rep] * n_h), [pp_spec] * len(b_arrs))
        if with_backward:
            out_specs = (rep, tuple([rep] * n_e), tuple([rep] * n_h),
                         [pp_spec] * len(b_arrs))
        else:
            out_specs = rep
        out = jax.shard_map(
            body, mesh=mesh.jax_mesh,
            in_specs=in_specs, out_specs=out_specs,
            # full-manual over the whole mesh (the partial-manual pp-only
            # form trips XLA SPMD partitioner bugs when embed/head carry
            # Megatron-TP shardings on auto axes); microbatches are
            # dp-sharded manually, other axes replicated inside the
            # pipeline region
            check_vma=False,
        )(ids_micro, lab_micro, tuple(e_arrs), tuple(h_arrs), b_arrs)
        if not with_backward:
            return out, None
        loss_total, ge, gh, gb = out
        # grads back to original layer order, pad rows dropped (their
        # masked grads are exactly zero)
        unperm = jnp.asarray(self._inv_perm[:L], jnp.int32)
        gb = [g[unperm] for g in gb]
        return loss_total, (list(ge), list(gh), list(gb))
