"""Runtime capability probes for the distributed surface.

Feature detection, NOT version pins: jax moves APIs between releases
(``jax.shard_map`` graduated from ``jax.experimental``; host-pinned
memory kinds appear per backend), and a version comparison would rot the
moment a distro backports or renames. Each probe answers "can THIS
runtime do it" by looking for the feature itself, and callers — the
shard_map-dependent distributed tests above all — skip as "capability
absent" instead of failing as noise when it is missing.
"""

from __future__ import annotations

__all__ = [
    "has_jax_shard_map", "has_pinned_host_memory",
    "has_partitioning_sharding_rule", "has_multiprocess_collectives",
]


def has_jax_shard_map():
    """True when the runtime jax exposes the stable ``jax.shard_map``
    entry point (with its current kwargs surface, e.g. ``check_vma``)
    that paddle_tpu.distributed.pipeline / ring_attention and their
    tests drive. Older jax raises a deprecation-shim AttributeError
    here, which is exactly the condition tier-1 should SKIP on rather
    than fail on."""
    import jax

    try:
        return callable(getattr(jax, "shard_map", None))
    except Exception:  # noqa: BLE001 — deprecation shims raise on getattr
        return False


def has_partitioning_sharding_rule():
    """True when ``custom_partitioning.def_partition`` accepts the
    ``sharding_rule`` kwarg the Pallas flash-attention GSPMD rules pass
    (kernels/pallas/flash_attention.py) — probed from the actual call
    signature, so a backport or rename is detected either way."""
    import inspect

    try:
        from jax.experimental.custom_partitioning import custom_partitioning
        sig = inspect.signature(custom_partitioning.def_partition)
        return "sharding_rule" in sig.parameters
    except Exception:  # noqa: BLE001 — absent API means absent feature
        return False


def has_multiprocess_collectives():
    """True when this runtime's backend can execute multi-controller
    computations (the launch/elastic e2e tests spawn real worker
    processes). XLA's CPU backend rejects them outright
    ("Multiprocess computations aren't implemented on the CPU
    backend") — the capability boundary is the backend kind, not a jax
    version."""
    import jax

    try:
        return jax.default_backend() != "cpu"
    except Exception:  # noqa: BLE001 — no backend at all
        return False


def has_pinned_host_memory():
    """True when the default device can address ``pinned_host`` memory
    (the offload tests' dependency); CPU-only jax builds advertise only
    ``unpinned_host``."""
    import jax

    try:
        dev = jax.devices()[0]
        kinds = {m.kind for m in dev.addressable_memories()}
        return "pinned_host" in kinds
    except Exception:  # noqa: BLE001 — absent API means absent feature
        return False
