"""Activation recompute (gradient checkpointing).

Parity: reference `python/paddle/distributed/fleet/recompute/recompute.py`
(RecomputeFunction :109, recompute() :423 with RNG-state replay,
recompute_sequential). TPU-first: inside the compiled train step this is
`jax.checkpoint` (XLA rematerialization — the exact FLOPs-for-HBM trade
the reference implements by hand); on the eager tape we record a PyLayer
that re-runs the function in backward with the saved RNG key.
"""

from __future__ import annotations

import jax

from ..autograd.py_layer import PyLayer
from ..core import random as random_mod
from ..core.autograd import enable_grad, no_grad
from ..core.tensor import Tensor

__all__ = ["recompute", "recompute_sequential"]


def _to_memory_kind(arr, kind):
    try:
        sh = arr.sharding.with_memory_kind(kind)
    except Exception:
        return arr
    return jax.device_put(arr, sh)


class _RecomputeFunction(PyLayer):
    @staticmethod
    def forward(ctx, fn, preserve_rng, offload, kwargs, *args):
        ctx.fn = fn
        ctx.kwargs = kwargs
        ctx.preserve_rng = preserve_rng
        ctx.offload = offload
        if preserve_rng:
            ctx.rng_key = random_mod.default_generator().get_state()
        if offload:
            # recompute_hybrid.py parity: the stashed boundary activations
            # live in host memory until the backward re-forward needs
            # them; the forward itself still computes on the device args
            ctx.inputs = tuple(
                Tensor(_to_memory_kind(a._data, "pinned_host"),
                       stop_gradient=a.stop_gradient)
                if isinstance(a, Tensor) else a for a in args)
        else:
            ctx.inputs = args
        ctx.tensor_indices = [i for i, a in enumerate(args)
                              if isinstance(a, Tensor)]
        with no_grad():
            out = fn(*args, **kwargs)
        return out

    @staticmethod
    def backward(ctx, *grads):
        # re-run forward with grad recording under the saved RNG state
        detached = []
        for a in ctx.inputs:
            if isinstance(a, Tensor):
                d = a.detach()
                if ctx.offload:  # fetch the stash back to device memory
                    d = Tensor(_to_memory_kind(d._data, "device"))
                d.stop_gradient = a.stop_gradient
                detached.append(d)
            else:
                detached.append(a)
        def rerun():
            with enable_grad():
                return ctx.fn(*detached, **ctx.kwargs)

        if ctx.preserve_rng:
            with random_mod.scoped_key(ctx.rng_key):
                out = rerun()
        else:
            out = rerun()
        outs = out if isinstance(out, (tuple, list)) else [out]
        outs = [o for o in outs if isinstance(o, Tensor)]
        if all(o.stop_gradient for o in outs):  # nothing requires grad
            return tuple(None for _ in ctx.tensor_indices)
        # tape backward: accumulates into model param .grad directly
        # (reference RecomputeFunction.backward runs paddle.autograd
        # .backward on the re-forward) and into the detached inputs
        from ..core.autograd import backward as tape_backward
        tape_backward(outs, grad_tensors=list(grads), retain_graph=False)
        result = []
        for i in ctx.tensor_indices:
            t = detached[i]
            result.append(None if t.stop_gradient or t.grad is None
                          else t.grad)
        return tuple(result)


def recompute(function, *args, **kwargs):
    """paddle.distributed.fleet.recompute parity. ``use_reentrant`` and
    ``preserve_rng_state`` accepted; ``offload_to_host=True`` stashes the
    boundary activations in pinned host memory between forward and the
    backward re-forward (reference recompute_hybrid.py offload)."""
    preserve_rng = kwargs.pop("preserve_rng_state", True)
    offload = kwargs.pop("offload_to_host", False)
    kwargs.pop("use_reentrant", None)
    return _RecomputeFunction.apply(function, preserve_rng, offload,
                                    kwargs, *args)


def recompute_sequential(ctx, functions, *args, **kwargs):
    """Apply recompute over chunks of a Sequential (reference
    recompute_sequential)."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    layers = list(functions)
    chunk = max(len(layers) // segments, 1)

    def run_chunk(sub):
        def fn(x):
            for l in sub:
                x = l(x)
            return x
        return fn

    x = args[0]
    for start in range(0, len(layers), chunk):
        x = recompute(run_chunk(layers[start:start + chunk]), x, **kwargs)
    return x
