"""Distributed-namespace export fills (reference
python/paddle/distributed/__init__.py names beyond the core surface):
env/introspection classes, dtensor sharding stages + shard_optimizer,
object collectives, p2p handles, dataloader sharding, DistModel/Strategy.
"""

from __future__ import annotations

import dataclasses
import enum
import pickle

import jax
import numpy as np

from ..core.tensor import Tensor
from .placement import Replicate, Shard, named_sharding

__all__ = [
    "ParallelEnv", "ParallelMode", "ReduceType", "get_backend",
    "is_available", "destroy_process_group", "get_group", "wait",
    "isend", "irecv", "alltoall_single", "broadcast_object_list",
    "scatter_object_list", "split", "unshard_dtensor", "shard_optimizer",
    "shard_scaler", "shard_dataloader", "ShardingStage1",
    "ShardingStage2", "ShardingStage3", "Strategy", "DistAttr",
    "DistModel", "to_static", "load_state_dict", "save_state_dict",
    "InMemoryDataset", "QueueDataset", "CountFilterEntry",
    "ProbabilityEntry", "ShowClickEntry", "gloo_init_parallel_env",
    "gloo_barrier", "gloo_release",
]


class ParallelMode:
    """Reference fleet base/topology ParallelMode constants."""

    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3
    SEGMENT_PARALLEL = 4


class ReduceType(enum.IntEnum):
    kRedSum = 0
    kRedMax = 1
    kRedMin = 2
    kRedProd = 3
    kRedAvg = 4


class ParallelEnv:
    """Reference parallel.py ParallelEnv: process-level env view."""

    @property
    def rank(self):
        from .env import get_rank
        return get_rank()

    @property
    def local_rank(self):
        import os
        return int(os.environ.get("PADDLE_LOCAL_RANK", "0"))

    @property
    def world_size(self):
        from .env import get_world_size
        return get_world_size()

    nranks = world_size

    @property
    def device_id(self):
        return 0

    @property
    def device_type(self):
        return jax.default_backend()

    @property
    def current_endpoint(self):
        import os
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
        r = self.rank
        return eps[r] if r < len(eps) and eps[r] else f"127.0.0.1:{r}"

    @property
    def trainer_endpoints(self):
        import os
        return os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")


def get_backend(group=None):
    """The comm backend (reference returns NCCL/GLOO; here XLA's
    collectives over the active platform)."""
    return "XLA:" + jax.default_backend().upper()


def is_available():
    return True


def destroy_process_group(group=None):
    """Reference destroy_process_group: the coordination service owns
    comm lifetime here; dropping the handle is enough."""
    return None


def get_group(gid=0):
    from .collective import _group
    return _group(None)


class _Work:
    """Completed-work handle (XLA collectives are synchronous at the
    python boundary — by the time the call returns, the async dispatch
    is enqueued and ordering is guaranteed)."""

    def wait(self):
        return True

    def is_completed(self):
        return True


def wait(tensor, group=None, use_calc_stream=True):
    """Reference stream-sync: host-sync the value."""
    jax.block_until_ready(tensor._data if isinstance(tensor, Tensor)
                          else tensor)
    return None


def isend(tensor, dst, group=None):
    from .collective import send
    send(tensor, dst, group=group)
    return _Work()


def irecv(tensor, src=None, group=None):
    from .collective import recv
    recv(tensor, src, group=group)
    return _Work()


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    """Single-tensor all-to-all (reference communication/all_to_all.py):
    equal splits over the group axis."""
    from .collective import _group, _in_shard_map
    from ..core.dispatch import apply
    from ..ops import _inplace_from

    g = _group(group)
    if _in_shard_map(g.axis_name):
        import jax.numpy as jnp
        from jax import lax

        def fn(a):
            # axis size from the trace (the group may span a sub-mesh of
            # the world, e.g. a 4-device axis on an 8-device host)
            n = lax.axis_size(g.axis_name)
            parts = jnp.reshape(a, (n, -1) + a.shape[1:])
            return lax.all_to_all(parts, g.axis_name, 0, 0,
                                  tiled=False).reshape(a.shape)
        out = apply(fn, in_tensor, name="alltoall_single")
        return _inplace_from(out_tensor, out)
    return _inplace_from(out_tensor, in_tensor)


def _obj_store():
    from .env import get_world_size
    if get_world_size() <= 1:
        return None
    from .store import TCPStore
    return None  # multi-process object exchange rides the jax KV (below)


def broadcast_object_list(object_list, src=0, group=None):
    """Reference broadcast_object_list. Multi-process: the coordination
    service KV carries the pickled payload; single process: identity."""
    from .env import get_rank, get_world_size

    if get_world_size() <= 1:
        return object_list
    from jax._src import distributed as jdist

    client = jdist.global_state.client
    key = f"pt_bcast_obj/{_obj_seq()}"
    if get_rank() == src:
        client.key_value_set(key, pickle.dumps(object_list).hex())
    raw = client.blocking_key_value_get(key, 60_000)
    got = pickle.loads(bytes.fromhex(raw))
    object_list[:] = got
    return object_list


_SEQ = [0]


def _obj_seq():
    _SEQ[0] += 1
    return _SEQ[0]


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    from .env import get_rank, get_world_size

    ws = get_world_size()
    if ws <= 1:
        out_object_list[:] = [in_object_list[0]] if in_object_list else []
        return out_object_list
    from jax._src import distributed as jdist

    client = jdist.global_state.client
    seq = _obj_seq()
    if get_rank() == src:
        for r in range(ws):
            client.key_value_set(
                f"pt_scatter_obj/{seq}/{r}",
                pickle.dumps(in_object_list[r]).hex())
    raw = client.blocking_key_value_get(
        f"pt_scatter_obj/{seq}/{get_rank()}", 60_000)
    out_object_list[:] = [pickle.loads(bytes.fromhex(raw))]
    return out_object_list


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Reference distributed.split (model-parallel linear/embedding).
    The mesh-placement system supersedes it: build the mpu layer."""
    from .fleet import mp_layers

    if operation == "linear":
        layer = (mp_layers.ColumnParallelLinear if axis == 1 else
                 mp_layers.RowParallelLinear)(
            size[0], size[1], weight_attr=weight_attr,
            has_bias=bias_attr is not False, gather_output=gather_out)
        return layer(x)
    if operation == "embedding":
        layer = mp_layers.VocabParallelEmbedding(
            size[0], size[1], weight_attr=weight_attr)
        return layer(x)
    raise ValueError(f"unsupported split operation {operation!r}")


def unshard_dtensor(dist_tensor):
    """Reference unshard_dtensor: back to a replicated dense tensor."""
    from .api import reshard
    from .mesh import get_mesh

    mesh = None
    if dist_tensor._dist_attr is not None:
        mesh = dist_tensor._dist_attr[0]
    mesh = mesh or get_mesh()
    return reshard(dist_tensor, mesh,
                   [Replicate()] * mesh.ndim)


# -- dtensor sharding stages (reference auto_parallel/api.py:1154-1301) --

@dataclasses.dataclass
class ShardingStage1:
    """Optimizer-state sharding over the data axis."""

    mesh_dim: str = "dp"
    stage: int = 1


@dataclasses.dataclass
class ShardingStage2(ShardingStage1):
    stage: int = 2


@dataclasses.dataclass
class ShardingStage3(ShardingStage1):
    stage: int = 3


def shard_optimizer(optimizer, shard_fn=None):
    """Reference shard_optimizer: mark the optimizer for ZeRO placement.
    ShardedTrainStep reads the tag and shards slots (stage 1/2) or relies
    on param placements (stage 3)."""
    if shard_fn is None:
        shard_fn = ShardingStage1()
    optimizer._sharding_stage = getattr(shard_fn, "stage", 1)
    optimizer._sharding_axis = getattr(shard_fn, "mesh_dim", "dp")
    return optimizer


def shard_scaler(scaler):
    """Reference shard_scaler: found_inf is already a global reduction
    inside the compiled step, so the scaler works unchanged."""
    return scaler


def shard_dataloader(dataloader, meshes=None, shard_dims=None,
                     input_keys=None):
    """Reference ShardDataloader: yield batches placed on the mesh with
    the given dims sharded (default: batch dim over the first axis)."""
    from .mesh import get_mesh

    mesh = meshes if meshes is not None and not isinstance(meshes, list) \
        else (meshes[0] if meshes else get_mesh())

    class _Sharded:
        def __init__(self, dl):
            self._dl = dl

        def __len__(self):
            return len(self._dl)

        def __iter__(self):
            for batch in self._dl:
                yield jax.tree.map(
                    lambda t: _place(t, mesh, shard_dims),
                    batch,
                    is_leaf=lambda x: isinstance(x, Tensor))
    return _Sharded(dataloader)


def _place(t, mesh, shard_dims):
    if not isinstance(t, Tensor):
        return t
    dim = 0 if shard_dims is None else shard_dims
    placements = [Replicate()] * mesh.ndim
    placements[0] = Shard(dim if isinstance(dim, int) else 0)
    sh = named_sharding(mesh, placements, t.ndim)
    return Tensor(jax.device_put(t._data, sh))


# -- semi-auto static engine facade (reference DistModel/Strategy) ------

class Strategy:
    """Reference auto_parallel Strategy: knob container."""

    def __init__(self, config=None):
        self.sharding = _Knob(enable=False, stage=1, degree=8)
        self.fused_passes = _Knob(enable=False)
        self.gradient_merge = _Knob(enable=False, k_steps=1)
        self.pipeline = _Knob(enable=False, schedule_mode="1F1B",
                              micro_batch_size=1, accumulate_steps=1)
        self.amp = _Knob(enable=False, dtype="bfloat16", level="O2")
        if config:
            for k, v in config.items():
                setattr(self, k, v)


class _Knob:
    def __init__(self, **kw):
        self.__dict__.update(kw)


class DistAttr:
    """Reference auto_parallel DistAttr (python/paddle/distributed/
    auto_parallel/api.py DistAttr): a (process_mesh, sharding_specs)
    pair. sharding_specs entries are mesh-dim names (or None) per
    tensor dim; exposed as placements for the TPU mapping."""

    def __init__(self, mesh=None, sharding_specs=None):
        from .placement import Replicate, Shard

        self.process_mesh = mesh
        self.sharding_specs = list(sharding_specs or [])
        if mesh is not None:
            names = list(mesh.dim_names)
            pls = [Replicate()] * mesh.ndim
            for tdim, spec in enumerate(self.sharding_specs):
                if spec is not None:
                    pls[names.index(spec)] = Shard(tdim)
            self.placements = pls
        else:
            self.placements = []

    def __iter__(self):  # keeps the (mesh, placements) pair unpackable
        return iter((self.process_mesh, self.placements))


class DistModel:
    """Reference DistModel (engine.py to_static product): train/eval/
    predict steps compiled over the mesh."""

    def __init__(self, layer, loader, loss=None, optimizer=None,
                 strategy=None, metrics=None, mesh=None):
        from .mesh import get_mesh, init_mesh, set_mesh
        from .sharded_step import ShardedTrainStep

        self._layer = layer
        self._loader = loader
        self._loss = loss
        self._optimizer = optimizer
        self._strategy = strategy or Strategy()
        self._mode = "train"
        mesh = mesh or get_mesh()
        if mesh is None:  # default: pure DP over every visible device
            mesh = set_mesh(init_mesh([-1], ["dp"]))
        opt_axis = None
        if optimizer is not None and \
                getattr(optimizer, "_sharding_stage", None):
            opt_axis = optimizer._sharding_axis
        if optimizer is not None and loss is not None:
            self._step = ShardedTrainStep(
                layer, optimizer,
                lambda m, *xs: loss(m(*xs[:-1]), xs[-1]),
                mesh=mesh, shard_optimizer_axis=opt_axis)
        else:
            self._step = None

    def train(self):
        self._mode = "train"
        self._layer.train()

    def eval(self):
        self._mode = "eval"
        self._layer.eval()

    def predict(self):
        self._mode = "predict"
        self._layer.eval()

    def __call__(self, *args):
        if self._mode == "train" and self._step is not None:
            return self._step(*args)
        from ..core.autograd import no_grad

        with no_grad():
            out = self._layer(*args[:-1] if self._loss else args)
            if self._mode == "eval" and self._loss is not None:
                return self._loss(out, args[-1])
            return out

    def state_dict(self, mode="all"):
        return self._layer.state_dict()

    def dist_main_program(self, mode=None):
        return None  # programs are XLA executables here

    dist_startup_program = dist_main_program


def to_static(layer, loader=None, loss=None, optimizer=None,
              strategy=None, input_spec=None):
    """Reference paddle.distributed.to_static -> DistModel."""
    return DistModel(layer, loader, loss, optimizer, strategy)


# -- checkpoint aliases (reference exposes them at namespace root) ------

def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, async_save=False):
    from . import checkpoint
    return checkpoint.save_state_dict(state_dict, path, process_group,
                                      coordinator_rank)


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None,
                    offload=False):
    from . import checkpoint
    return checkpoint.load_state_dict(state_dict, path, process_group,
                                      coordinator_rank)


# -- PS-style datasets + embedding entries (reference fleet/dataset) ----

class InMemoryDataset:
    """Reference InMemoryDataset: file-list dataset loaded into memory,
    line-oriented, with shuffle."""

    def __init__(self):
        self._files = []
        self._lines = []
        self._parser = None

    def init(self, batch_size=1, use_var=None, pipe_command=None,
             **kwargs):
        self.batch_size = batch_size
        return self

    def set_filelist(self, files):
        self._files = list(files)

    def load_into_memory(self):
        self._lines = []
        for f in self._files:
            with open(f) as fh:
                self._lines.extend(fh.read().splitlines())

    def local_shuffle(self):
        import random
        random.shuffle(self._lines)

    def global_shuffle(self, fleet=None, thread_num=12):
        self.local_shuffle()

    def get_memory_data_size(self, fleet=None):
        return len(self._lines)

    def release_memory(self):
        self._lines = []

    def __iter__(self):
        return iter(self._lines)


class QueueDataset(InMemoryDataset):
    """Reference QueueDataset: streaming variant (same surface; files
    stream lazily)."""

    def load_into_memory(self):  # streaming: defer to iteration
        return None

    def __iter__(self):
        for f in self._files:
            with open(f) as fh:
                yield from fh.read().splitlines()


@dataclasses.dataclass
class CountFilterEntry:
    """Sparse-embedding admission rule (reference entry_attr)."""

    count: int = 1

    def to_string(self):
        return f"count_filter_entry:{self.count}"


@dataclasses.dataclass
class ProbabilityEntry:
    probability: float = 1.0

    def to_string(self):
        return f"probability_entry:{self.probability}"


@dataclasses.dataclass
class ShowClickEntry:
    show_name: str = "show"
    click_name: str = "click"

    def to_string(self):
        return f"show_click_entry:{self.show_name}:{self.click_name}"


# -- gloo single-host helpers (reference gloo_* trio) -------------------

def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    from .env import init_parallel_env
    init_parallel_env()


def gloo_barrier():
    from .collective import barrier
    barrier()


def gloo_release():
    return None
