"""ProcessMesh: the device-mesh abstraction.

Parity: reference `ProcessMesh` (paddle/phi/core/distributed/auto_parallel/
process_mesh.h:34, python python/paddle/distributed/auto_parallel/
process_mesh.py:85). TPU-first: a thin, faithful wrapper over
`jax.sharding.Mesh` — mesh axes ARE the reference's comm groups (dp/mp/pp/
sharding/sep axes of HybridCommunicateGroup, topology.py:65), laid out so
inner axes ride ICI and the outermost axis can span DCN slices.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh as JaxMesh

_global_mesh = None


class MeshAxisError(ValueError):
    """A requested mesh shape cannot be laid out on the visible devices.

    Structured: ``axis`` (the offending axis name, or None when the
    TOTAL product is the problem), ``size`` (the requested extent) and
    ``device_count`` — so callers (serving/mesh.py, tests, operators
    reading a traceback) see WHICH axis broke instead of a jax
    IndexError from deep inside ``Mesh`` construction."""

    def __init__(self, message, *, axis=None, size=None,
                 device_count=None):
        super().__init__(message)
        self.axis = axis
        self.size = size
        self.device_count = device_count


def validate_mesh_axes(shape, dim_names, device_count=None):
    """Validate a logical mesh shape against the visible device count
    BEFORE any jax ``Mesh`` construction: every axis size must be a
    positive integer that divides ``jax.device_count()``, and the total
    product must not exceed it. Raises :class:`MeshAxisError` naming
    the first offending axis (jax's own failure mode is an opaque
    reshape/index error deep inside ``Mesh``)."""
    if device_count is None:
        device_count = jax.device_count()
    names = list(dim_names) if dim_names is not None else \
        [f"d{i}" for i in range(len(shape))]
    total = 1
    for name, size in zip(names, shape):
        size = int(size)
        if size < 1:
            raise MeshAxisError(
                f"mesh axis {name!r} has non-positive size {size}",
                axis=name, size=size, device_count=device_count)
        if device_count % size != 0:
            raise MeshAxisError(
                f"mesh axis {name!r} size {size} does not divide the "
                f"visible device count {device_count}",
                axis=name, size=size, device_count=device_count)
        total *= size
    if total > device_count:
        raise MeshAxisError(
            f"mesh shape {'x'.join(str(int(s)) for s in shape)} needs "
            f"{total} devices but only {device_count} are visible",
            axis=None, size=total, device_count=device_count)
    return total


class ProcessMesh:
    def __init__(self, mesh, dim_names=None, shape=None):
        """``mesh``: nested list / ndarray of device (process) ids, or a
        jax.sharding.Mesh."""
        if isinstance(mesh, JaxMesh):
            self._jax_mesh = mesh
            self._ids = np.array(
                [[d.id for d in row] for row in
                 mesh.devices.reshape(mesh.devices.shape[0], -1)]
            ) if mesh.devices.ndim > 1 else np.array(
                [d.id for d in mesh.devices.flat])
            self._dim_names = list(mesh.axis_names)
            self._shape = list(mesh.devices.shape)
            return
        arr = np.asarray(mesh)
        if shape is not None:
            arr = arr.reshape(shape)
        self._ids = arr
        self._shape = list(arr.shape)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        assert len(dim_names) == arr.ndim
        self._dim_names = list(dim_names)
        devices = np.array(jax.devices(), dtype=object)[arr.reshape(-1)]
        self._jax_mesh = JaxMesh(devices.reshape(arr.shape),
                                 axis_names=tuple(self._dim_names))

    @property
    def jax_mesh(self) -> JaxMesh:
        return self._jax_mesh

    @property
    def shape(self):
        return list(self._shape)

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def process_ids(self):
        return [int(i) for i in self._ids.reshape(-1)]

    @property
    def mesh(self):
        return self._ids

    def get_dim_size(self, name):
        return self._shape[self._dim_names.index(name)]

    def get_mesh_with_dim(self, dim_name, index=None):
        """Sub-mesh with ``dim_name`` moved out (paddle parity)."""
        axis = self._dim_names.index(dim_name)
        perm = [axis] + [i for i in range(self.ndim) if i != axis]
        ids = np.transpose(self._ids, perm)
        names = [dim_name] + [n for n in self._dim_names if n != dim_name]
        if index is None:
            return ProcessMesh(ids, names)
        return ProcessMesh(ids[index], names[1:])

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh) and
                self._shape == other._shape and
                self.process_ids == other.process_ids and
                self._dim_names == other._dim_names)

    def __hash__(self):
        return hash((tuple(self._shape), tuple(self.process_ids),
                     tuple(self._dim_names)))

    def __repr__(self):
        return (f"ProcessMesh(shape={self._shape}, "
                f"dim_names={self._dim_names})")

    def __enter__(self):
        global _global_mesh
        self._prev = _global_mesh
        _global_mesh = self
        return self

    def __exit__(self, *exc):
        global _global_mesh
        _global_mesh = self._prev
        return False


def init_mesh(shape, dim_names):
    """Build a ProcessMesh over all visible devices with the given logical
    shape; `-1` infers one dimension. Axis sizes are validated against
    ``jax.device_count()`` up front (:func:`validate_mesh_axes`) so a
    bad shape raises a :class:`MeshAxisError` naming the axis instead
    of failing deep inside jax ``Mesh`` construction."""
    n = jax.device_count()
    shape = list(shape)
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        if known < 1 or n % known != 0:
            raise MeshAxisError(
                f"cannot infer the -1 axis: the known axes' product "
                f"{known} does not divide the visible device count {n}",
                axis=None, size=known, device_count=n)
        shape[shape.index(-1)] = n // known
    validate_mesh_axes(shape, dim_names, n)
    ids = np.arange(int(np.prod(shape))).reshape(shape)
    return ProcessMesh(ids, dim_names)


def get_mesh():
    return _global_mesh


def set_mesh(mesh):
    global _global_mesh
    _global_mesh = mesh
    return mesh


def auto_parallel_rank_in_mesh(mesh, axis, process_id=None):
    """This process's coordinate along ``axis`` in the mesh (reference
    HybridCommunicateGroup rank-in-group, topology.py:178).

    ``process_id`` defaults to the calling process's first addressable
    device's position in the mesh (single-controller: each jax process
    owns a contiguous block of mesh devices)."""
    import numpy as np

    jm = mesh.jax_mesh
    axis_idx = mesh.dim_names.index(axis) if isinstance(axis, str) else axis
    if process_id is None:
        import jax

        local = jax.local_devices()[0]
        flat = list(np.ravel(jm.devices))
        process_id = flat.index(local) if local in flat else 0
    coords = np.unravel_index(process_id, jm.devices.shape)
    return int(coords[axis_idx])
