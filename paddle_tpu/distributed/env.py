"""Distributed bootstrap.

Parity: reference `init_parallel_env` (python/paddle/distributed/
parallel.py:977 — env parsing, TCPStore rendezvous, ProcessGroupNCCL
creation) and the TCPStore itself (paddle/phi/core/distributed/store/
tcp_store.h:121). TPU-first: `jax.distributed.initialize` speaks to the
JAX coordination service (the TCPStore equivalent — rank-0-hosted KV +
barriers with builtin health checking); NCCL comm setup is replaced by the
runtime's ICI/DCN topology discovery, so there is nothing lazy to warm up.
"""

from __future__ import annotations

import os

import jax


_initialized = False


def init_parallel_env(strategy=None):
    """Multi-host bootstrap. Single-host (or already-initialized) is a
    no-op, mirroring paddle's idempotent init."""
    global _initialized
    if _initialized:
        return
    coord = os.environ.get("PADDLE_MASTER") or \
        os.environ.get("COORDINATOR_ADDRESS")
    nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM",
                                os.environ.get("NUM_PROCESSES", "1")))
    pid = int(os.environ.get("PADDLE_TRAINER_ID",
                             os.environ.get("PROCESS_ID", "0")))
    if coord and nprocs > 1:
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=nprocs, process_id=pid)
        # bring up the p2p store channel NOW: its server lives on rank 0,
        # and lazily starting it on rank 0's first send/recv would hang
        # p2p between two non-zero ranks (rank 0 might never call it)
        try:
            from .collective import _p2p
            _p2p()
        except Exception:  # p2p stays lazily-retried on first use
            pass
    _initialized = True


def get_rank(group=None):
    """Process rank (reference paddle.distributed.get_rank reads
    PADDLE_TRAINER_ID; here: the jax process index)."""
    if group is not None:
        return group.rank
    return jax.process_index()


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    return jax.process_count()


def is_initialized():
    return _initialized


def device_count():
    return jax.device_count()


def local_device_count():
    return jax.local_device_count()
