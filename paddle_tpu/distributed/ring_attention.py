"""Ring attention: context parallelism for long sequences.

Fills the reference's acknowledged gap (SURVEY.md §5.7: the `sep` mesh
axis exists — topology.py:65, segment_parallel.py:26 — but no ring /
blockwise attention kernel ships in the snapshot; PaddleNLP carries it).

TPU-native design: q/k/v are sequence-sharded over the `sep` mesh axis.
Inside `shard_map`, each device computes blockwise attention between its
local queries and a rotating ring of k/v chunks (`lax.ppermute` over ICI),
merging partial results with the online-softmax recurrence (the flash-
attention merge). Communication overlaps with the next chunk's compute
under XLA's async collectives; memory is O(seq/cp) per device. Causal
masking compares global positions, so chunks that are entirely in the
future are numerically masked (their contribution underflows to zero
weight) without data-dependent control flow.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core.dispatch import apply

__all__ = ["ring_attention"]


def _ring_body(q, k, v, *, axis, cp, causal, scale):
    """Runs on local shards inside shard_map: q [b, s_local, hq, d],
    k/v [b, s_local, hk, d] with hq = g*hk (native GQA — the group axis is
    carried through the einsums instead of expanding KV, so each ring hop
    moves the grouped KV chunk, g x less ICI traffic than repeat)."""
    idx = lax.axis_index(axis)
    b, sq, hq, d = q.shape
    hk = k.shape[2]
    g = hq // hk
    qg = q.reshape(b, sq, hk, g, d)
    NEG = jnp.float32(-1e30)

    pos_q = idx * sq + jnp.arange(sq, dtype=jnp.int32)  # global positions

    def partial_attn(carry, step):
        o, m, l, k_chunk, v_chunk = carry
        src = (idx - step) % cp  # which device's kv we hold this step
        pos_k = src * sq + jnp.arange(sq, dtype=jnp.int32)
        # [b, hk, g, sq_q, sq_k]
        logits = jnp.einsum("bsngd,btnd->bngst", qg, k_chunk,
                            preferred_element_type=jnp.float32) * scale
        if causal:
            mask = pos_k[None, :] <= pos_q[:, None]  # [sq, sk]
            logits = jnp.where(mask[None, None, None], logits, NEG)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        # guard: rows with no valid key yet keep m at -inf-ish
        p = jnp.exp(logits - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "bngst,btnd->bngsd", p.astype(v_chunk.dtype), v_chunk
        ).astype(jnp.float32)
        # rotate kv ring: pass our chunk to the next device
        perm = [(i, (i + 1) % cp) for i in range(cp)]
        k_next = lax.ppermute(k_chunk, axis, perm)
        v_next = lax.ppermute(v_chunk, axis, perm)
        return (o_new, m_new, l_new, k_next, v_next), None

    o0 = jnp.zeros((b, hk, g, sq, d), jnp.float32)
    m0 = jnp.full((b, hk, g, sq), NEG, jnp.float32)
    l0 = jnp.zeros((b, hk, g, sq), jnp.float32)
    (o, m, l, _, _), _ = lax.scan(
        partial_attn, (o0, m0, l0, k, v), jnp.arange(cp))
    out = o / jnp.maximum(l[..., None], 1e-20)
    # [b, hk, g, sq, d] -> [b, sq, hq, d]
    return out.transpose(0, 3, 1, 2, 4).reshape(b, sq, hq, d).astype(q.dtype)


def _ring_body_flash(q, k, v, *, axis, cp, causal, scale):
    """Pallas-kernel ring body: each ring step runs the MXU flash kernel
    on (local q, rotating kv chunk) with explicit global positions, and
    partial outputs merge through their logsumexps —
    o = o1*exp(L1-L) + o2*exp(L2-L), L = logaddexp(L1, L2)."""
    from ..kernels.pallas.flash_attention import _BIG, flash_attention

    idx = lax.axis_index(axis)
    b, sq, hq, d = q.shape
    NEG = jnp.float32(-1e30)

    pos_q = jnp.broadcast_to(
        idx * sq + jnp.arange(sq, dtype=jnp.int32), (b, sq))
    seg = jnp.zeros((b, sq), jnp.int32)

    def partial_attn(carry, step):
        o_acc, l_acc, k_chunk, v_chunk = carry
        src = (idx - step) % cp
        pos_k = jnp.broadcast_to(
            src * sq + jnp.arange(sq, dtype=jnp.int32), (b, sq))
        o_part, lse = flash_attention(
            q, k_chunk, v_chunk, causal=causal, scale=scale,
            q_segment_ids=seg, kv_segment_ids=seg,
            q_positions=pos_q, kv_positions=pos_k, return_lse=True)
        # kernel sentinel for fully-masked rows is +_BIG (so its own bwd
        # zeroes); for the cross-chunk merge that row must be -inf-like
        lse = jnp.where(lse > jnp.float32(_BIG) * 0.5, NEG, lse)
        lse = jnp.swapaxes(lse, 1, 2)  # [b, hq, sq] -> [b, sq, hq]
        l_new = jnp.logaddexp(l_acc, lse)
        w_old = jnp.exp(l_acc - l_new)[..., None]
        w_new = jnp.exp(lse - l_new)[..., None]
        o_new = o_acc * w_old + o_part.astype(jnp.float32) * w_new
        perm = [(i, (i + 1) % cp) for i in range(cp)]
        k_next = lax.ppermute(k_chunk, axis, perm)
        v_next = lax.ppermute(v_chunk, axis, perm)
        return (o_new, l_new, k_next, v_next), None

    o0 = jnp.zeros((b, sq, hq, d), jnp.float32)
    l0 = jnp.full((b, sq, hq), NEG, jnp.float32)
    (o, _, _, _), _ = lax.scan(partial_attn, (o0, l0, k, v),
                               jnp.arange(cp))
    return o.astype(q.dtype)


def ring_attention(query, key, value, mesh=None, axis="sep", causal=True,
                   scale=None, use_flash=None):
    """Context-parallel attention on Tensors [b, s, h, d] with the
    sequence dim (logically) sharded over ``axis``. Differentiable; the
    VJP is the reversed ring (jax transposes ppermute automatically).

    ``use_flash``: run each ring step through the Pallas flash kernel
    (MXU tiling + causal block skip) and merge partials by logsumexp;
    default on for TPU, off for the CPU mesh (interpret mode is slow)."""
    from .mesh import get_mesh

    mesh = mesh or get_mesh()
    cp = mesh.get_dim_size(axis)
    d = query.shape[-1]
    sm_scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if use_flash is None:
        use_flash = jax.default_backend() != "cpu"
    body_fn = _ring_body_flash if use_flash else _ring_body

    def fn(q, k, v):
        spec = P(None, axis, None, None)
        body = jax.shard_map(
            lambda a, b_, c: body_fn(a, b_, c, axis=axis, cp=cp,
                                     causal=causal, scale=sm_scale),
            mesh=mesh.jax_mesh, in_specs=(spec, spec, spec),
            out_specs=spec, check_vma=False)
        return body(q, k, v)

    return apply(fn, query, key, value, name="ring_attention")
