"""TCPStore python binding (ctypes over the native store).

Parity: reference `paddle.distributed.TCPStore`
(paddle/phi/core/distributed/store/tcp_store.h:121, bound in
pybind/communication.cc): rank-0 hosts the server; every rank connects a
client. Used for rendezvous/bootstrap next to the JAX coordination
service, and by the elastic controller.
"""

from __future__ import annotations

import ctypes

from ..core import resilience
from ..csrc.build import load_library
from ..profiler import tracing
from ..testing import faults


def _lib():
    lib = load_library("pt_store")
    lib.pt_store_server_start.restype = ctypes.c_void_p
    lib.pt_store_server_start.argtypes = [ctypes.c_int]
    lib.pt_store_server_port.restype = ctypes.c_int
    lib.pt_store_server_port.argtypes = [ctypes.c_void_p]
    lib.pt_store_server_stop.argtypes = [ctypes.c_void_p]
    lib.pt_store_client_connect.restype = ctypes.c_void_p
    lib.pt_store_client_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                            ctypes.c_int]
    lib.pt_store_client_free.argtypes = [ctypes.c_void_p]
    lib.pt_store_set.restype = ctypes.c_int
    lib.pt_store_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_char_p, ctypes.c_int]
    lib.pt_store_get.restype = ctypes.c_int
    lib.pt_store_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_char_p, ctypes.c_int]
    lib.pt_store_add.restype = ctypes.c_int64
    lib.pt_store_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_int64]
    lib.pt_store_wait.restype = ctypes.c_int
    lib.pt_store_wait.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_int64]
    lib.pt_store_check.restype = ctypes.c_int
    lib.pt_store_check.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.pt_store_delete.restype = ctypes.c_int
    lib.pt_store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    return lib


class TCPStore:
    """paddle.distributed.TCPStore parity: ``is_master`` hosts the server
    in-process; all roles hold a client connection.

    Rendezvous-robust: a non-master client racing the master's startup
    retries the connect with jittered exponential backoff under the
    ``store.connect`` policy (``FLAGS_rendezvous_deadline`` caps the
    whole loop) instead of failing the job on the first refusal. The
    master's OWN client connect targets an in-process server that is
    already listening, so its first attempt succeeds."""

    def __init__(self, host="127.0.0.1", port=0, is_master=False,
                 world_size=1, timeout=900):
        self._lib = _lib()
        self._server = None
        self._client = None
        self._timeout_ms = int(timeout * 1000)
        if is_master:
            self._server = self._lib.pt_store_server_start(port)
            if not self._server:
                raise RuntimeError(f"TCPStore: cannot bind port {port}")
            port = self._lib.pt_store_server_port(self._server)
        self.host = host
        self.port = port

        def _connect():
            faults.site("store.connect")
            # child span when a trace is active (an rpc rendezvous
            # inside a traced request) — null path otherwise
            with tracing.span("store.connect", peer=f"{host}:{port}"):
                client = self._lib.pt_store_client_connect(
                    host.encode(), port, self._timeout_ms)
                if not client:
                    raise ConnectionError(
                        f"TCPStore: cannot connect {host}:{port}")
                return client

        if is_master:
            self._client = _connect()
        else:
            self._client = resilience.retry_call(
                _connect,
                policy=resilience.policy(
                    "store.connect",
                    retry_on=(ConnectionError, OSError)))

    def set(self, key, value):
        data = value if isinstance(value, bytes) else str(value).encode()
        with tracing.span("store.set", key=key):
            if self._lib.pt_store_set(self._client, key.encode(), data,
                                      len(data)) != 0:
                raise RuntimeError("TCPStore.set failed")

    def get(self, key):
        buf = ctypes.create_string_buffer(1 << 20)
        with tracing.span("store.get", key=key):
            n = self._lib.pt_store_get(self._client, key.encode(), buf,
                                       len(buf))
        if n < 0:
            raise KeyError(key)
        return buf.raw[:n]

    def add(self, key, amount):
        with tracing.span("store.add", key=key):
            r = self._lib.pt_store_add(self._client, key.encode(),
                                       int(amount))
        if r == -(2 ** 63):
            raise RuntimeError("TCPStore.add failed")
        return int(r)

    def wait(self, keys, timeout=None):
        if isinstance(keys, str):
            keys = [keys]
        ms = int((timeout or self._timeout_ms / 1000) * 1000)
        for k in keys:
            with tracing.span("store.wait", key=k):
                if self._lib.pt_store_wait(self._client, k.encode(),
                                           ms) != 0:
                    raise TimeoutError(
                        f"TCPStore.wait timeout on {k!r}")

    def try_get(self, key):
        """``get`` that returns None instead of raising KeyError — the
        fleet-registry member scan (profiler/fleet.py) probes a dense
        key range where gaps are normal (deregistered replicas), and a
        per-gap exception would dominate the scan."""
        buf = ctypes.create_string_buffer(1 << 20)
        with tracing.span("store.get", key=key):
            n = self._lib.pt_store_get(self._client, key.encode(), buf,
                                       len(buf))
        return None if n < 0 else buf.raw[:n]

    def check(self, key):
        return bool(self._lib.pt_store_check(self._client, key.encode()))

    def delete_key(self, key):
        return bool(self._lib.pt_store_delete(self._client, key.encode()))

    def __del__(self):
        lib = getattr(self, "_lib", None)
        if lib is None:
            return
        if getattr(self, "_client", None):
            lib.pt_store_client_free(self._client)
        if getattr(self, "_server", None):
            lib.pt_store_server_stop(self._server)
