"""DataParallel + spawn.

Parity: reference python/paddle/distributed/parallel.py — `DataParallel`
(:218, wrapping a Layer with EagerReducer bucketed grad allreduce) and
`spawn.py`. TPU-first: with a mesh-sharded batch GSPMD already reduce-
scatters/all-reduces gradients inside the compiled step, so DataParallel
is a transparent wrapper that (a) records the dp group, (b) keeps the
`scale_loss`/`no_sync` API, and (c) placements-replicates params.
"""

from __future__ import annotations

import contextlib
import multiprocessing
import os

from .. import nn
from .api import apply_placement_rules
from .mesh import get_mesh

__all__ = ["DataParallel", "spawn"]


class DataParallel(nn.Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.group = group
        mesh = group.mesh if group is not None else get_mesh()
        if mesh is not None:
            apply_placement_rules(layers, [], mesh)  # replicate params

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        # grad averaging happens in the mesh reduction; identity here
        return loss

    def apply_collective_grads(self):
        pass

    @contextlib.contextmanager
    def no_sync(self):
        yield

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__["_sub_layers"]["_layers"], name)


def _worker_entry(rank, nprocs, fn, args, env):
    for k, v in env.items():
        os.environ[k] = v
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    fn(*args)


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """reference spawn.py: launch ``nprocs`` training processes."""
    if nprocs == -1:
        nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    ctx = multiprocessing.get_context("spawn")
    procs = []
    env = {k: v for k, v in os.environ.items()
           if k.startswith(("PADDLE_", "JAX_", "XLA_"))}
    for rank in range(nprocs):
        p = ctx.Process(target=_worker_entry,
                        args=(rank, nprocs, func, args, env),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        for p in procs:
            if p.exitcode != 0:
                raise RuntimeError(
                    f"spawn: worker exited with code {p.exitcode}")
    return procs
