"""Parameter-server training (workflow parity).

Parity target: reference `paddle/fluid/distributed/ps/` + python
`distributed/ps/` + `fleet/runtime/the_one_ps.py` — brpc dense/sparse
tables with async push/pull for CPU-cluster recommendation workloads.

TPU scope note: PS-style async training targets CPU parameter clusters;
on a TPU pod the same models train synchronously with mesh-sharded
embeddings. This module keeps the WORKFLOW (server hosting dense/sparse
tables, workers pulling params and pushing grads, async SGD apply) over
the native TCPStore transport so reference PS call sites have a
functional home.
"""

from __future__ import annotations

import pickle

import numpy as np

from .store import TCPStore

__all__ = ["PSServer", "PSWorker", "DenseTable", "SparseTable"]


class DenseTable:
    def __init__(self, name, shape, lr=0.01):
        self.name = name
        self.value = np.zeros(shape, np.float32)
        self.lr = lr

    def pull(self):
        return self.value

    def push_grad(self, grad):
        self.value = self.value - self.lr * grad


class SparseTable:
    """Row-sparse embedding table (reference ps/table/ sparse tables):
    rows materialize on first access (the reference's lazy init)."""

    def __init__(self, name, dim, lr=0.01, initializer=None):
        self.name = name
        self.dim = dim
        self.lr = lr
        self.rows: dict[int, np.ndarray] = {}
        self.initializer = initializer or (
            lambda: np.random.uniform(-0.01, 0.01, dim).astype(np.float32))

    def pull(self, ids):
        return np.stack([
            self.rows.setdefault(int(i), self.initializer()) for i in ids])

    def push_grad(self, ids, grads):
        for i, g in zip(ids, grads):
            i = int(i)
            row = self.rows.setdefault(i, self.initializer())
            self.rows[i] = row - self.lr * g


class PSServer:
    """Hosts tables; serves pull/push via the TCPStore KV (each request is
    a serialized message under a sequenced key — the brpc service
    analogue, minus brpc)."""

    def __init__(self, host="127.0.0.1", port=0):
        self.store = TCPStore(host, port, is_master=True)
        self.port = self.store.port
        self.tables: dict[str, object] = {}

    def add_dense_table(self, name, shape, lr=0.01):
        self.tables[name] = DenseTable(name, shape, lr)

    def add_sparse_table(self, name, dim, lr=0.01):
        self.tables[name] = SparseTable(name, dim, lr)

    def handle_once(self, req_key):
        """Process one serialized request (in-process server loop body)."""
        req = pickle.loads(self.store.get(req_key))
        table = self.tables[req["table"]]
        kind = req["op"]
        if kind == "pull_dense":
            resp = table.pull()
        elif kind == "push_dense":
            table.push_grad(req["grad"])
            resp = b"ok"
        elif kind == "pull_sparse":
            resp = table.pull(req["ids"])
        elif kind == "push_sparse":
            table.push_grad(req["ids"], req["grads"])
            resp = b"ok"
        else:
            raise ValueError(kind)
        self.store.set(req_key + "/resp", pickle.dumps(resp))


class PSWorker:
    def __init__(self, server: PSServer = None, host=None, port=None):
        # in-process mode (tests / single host): direct server reference
        self.server = server
        self._seq = 0
        if server is None:
            self.store = TCPStore(host, port, is_master=False)
        else:
            self.store = server.store

    def _rpc(self, msg):
        self._seq += 1
        key = f"psreq/{id(self)}/{self._seq}"
        self.store.set(key, pickle.dumps(msg))
        if self.server is not None:
            self.server.handle_once(key)
        self.store.wait([key + "/resp"], timeout=30)
        resp = pickle.loads(self.store.get(key + "/resp"))
        self.store.delete_key(key)
        self.store.delete_key(key + "/resp")
        return resp

    def pull_dense(self, table):
        return self._rpc({"op": "pull_dense", "table": table})

    def push_dense_grad(self, table, grad):
        return self._rpc({"op": "push_dense", "table": table,
                          "grad": np.asarray(grad, np.float32)})

    def pull_sparse(self, table, ids):
        return self._rpc({"op": "pull_sparse", "table": table,
                          "ids": list(map(int, ids))})

    def push_sparse_grad(self, table, ids, grads):
        return self._rpc({"op": "push_sparse", "table": table,
                          "ids": list(map(int, ids)),
                          "grads": np.asarray(grads, np.float32)})
