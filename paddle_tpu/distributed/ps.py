"""Parameter-server training.

Parity target: reference `paddle/fluid/distributed/ps/` + python
`distributed/ps/` + `fleet/runtime/the_one_ps.py` — brpc dense/sparse
tables with pluggable accessors (server-side optimizer rules) and async
push/pull for CPU-cluster recommendation workloads.

TPU scope note: PS-style async training targets CPU parameter clusters;
on a TPU pod the same models train synchronously with mesh-sharded
embeddings. This module keeps the WORKFLOW: a server process hosting
dense/sparse tables with SGD/Adagrad/Adam accessors (reference
ps/table/ sparse_sgd_rule.h family), workers pulling params and pushing
grads sync or async. Transport: in-process direct calls (tests/single
host) or `paddle_tpu.distributed.rpc` (the brpc service analogue) for
real multi-process clusters.
"""

from __future__ import annotations

import numpy as np

from .store import TCPStore

__all__ = ["PSServer", "PSWorker", "DenseTable", "SparseTable",
           "SGDRule", "AdagradRule", "AdamRule"]


# ---------------------------------------------------------------------------
# accessors (reference paddle/fluid/distributed/ps/table/sparse_sgd_rule.h:
# naive/adagrad/adam rules applied ON THE SERVER per push)
# ---------------------------------------------------------------------------

class SGDRule:
    def __init__(self, lr=0.01):
        self.lr = lr

    def init_state(self, shape):
        return {}

    def apply(self, value, grad, state):
        return value - self.lr * grad


class AdagradRule:
    def __init__(self, lr=0.01, epsilon=1e-8):
        self.lr = lr
        self.epsilon = epsilon

    def init_state(self, shape):
        return {"g2": np.zeros(shape, np.float32)}

    def apply(self, value, grad, state):
        state["g2"] += grad * grad
        return value - self.lr * grad / (np.sqrt(state["g2"]) +
                                         self.epsilon)


class AdamRule:
    def __init__(self, lr=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8):
        self.lr = lr
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def init_state(self, shape):
        return {"m": np.zeros(shape, np.float32),
                "v": np.zeros(shape, np.float32), "t": 0}

    def apply(self, value, grad, state):
        state["t"] += 1
        state["m"] = self.beta1 * state["m"] + (1 - self.beta1) * grad
        state["v"] = self.beta2 * state["v"] + \
            (1 - self.beta2) * grad * grad
        mhat = state["m"] / (1 - self.beta1 ** state["t"])
        vhat = state["v"] / (1 - self.beta2 ** state["t"])
        return value - self.lr * mhat / (np.sqrt(vhat) + self.epsilon)


def _make_rule(accessor, lr):
    if not isinstance(accessor, str):
        return accessor
    return {"sgd": SGDRule, "adagrad": AdagradRule,
            "adam": AdamRule}[accessor](lr)


class DenseTable:
    def __init__(self, name, shape, lr=0.01, accessor="sgd"):
        self.name = name
        self.value = np.zeros(shape, np.float32)
        self.rule = _make_rule(accessor, lr)
        self.state = self.rule.init_state(shape)

    def pull(self):
        return self.value

    def push_grad(self, grad):
        self.value = self.rule.apply(self.value, grad, self.state)


class SparseTable:
    """Row-sparse embedding table (reference ps/table/ sparse tables):
    rows materialize on first access (the reference's lazy init); each
    row carries its own accessor state."""

    def __init__(self, name, dim, lr=0.01, initializer=None,
                 accessor="sgd"):
        self.name = name
        self.dim = dim
        self.rule = _make_rule(accessor, lr)
        self.rows: dict[int, np.ndarray] = {}
        self.states: dict[int, dict] = {}
        self.initializer = initializer or (
            lambda: np.random.uniform(-0.01, 0.01, dim).astype(np.float32))

    def _row(self, i):
        i = int(i)
        if i not in self.rows:
            self.rows[i] = self.initializer()
            self.states[i] = self.rule.init_state((self.dim,))
        return self.rows[i]

    def pull(self, ids):
        return np.stack([self._row(i) for i in ids])

    def push_grad(self, ids, grads):
        for i, g in zip(ids, grads):
            i = int(i)
            row = self._row(i)
            self.rows[i] = self.rule.apply(row, g, self.states[i])


# the server process's live instance, addressed by remote workers
# through module-level functions (picklable by reference)
_SERVER: "PSServer | None" = None


def _serve(msg):
    if _SERVER is None:
        raise RuntimeError("no PSServer running in this process")
    return _SERVER._handle(msg)


class PSServer:
    """Hosts tables. Two service modes:

    - in-process (tests / single host): workers call _handle directly;
    - cross-process: `serve_rpc(name, ...)` joins the rpc world and
      workers address the tables with rpc_sync/rpc_async (the brpc
      service analogue).
    """

    def __init__(self, host="127.0.0.1", port=0, use_store=True):
        import threading
        self.store = TCPStore(host, port, is_master=True) if use_store \
            else None
        self.port = self.store.port if self.store else None
        self.tables: dict[str, object] = {}
        # rpc serves requests from a thread pool; table updates are
        # read-modify-write — serialize them (the reference shards by
        # key across brpc threads; one coarse lock is the honest
        # single-host equivalent)
        self._mu = threading.Lock()

    def add_dense_table(self, name, shape, lr=0.01, accessor="sgd"):
        self.tables[name] = DenseTable(name, shape, lr, accessor)

    def add_sparse_table(self, name, dim, lr=0.01, accessor="sgd"):
        self.tables[name] = SparseTable(name, dim, lr, accessor=accessor)

    def _handle(self, req):
        table = self.tables[req["table"]]
        kind = req["op"]
        with self._mu:
            if kind == "pull_dense":
                return table.pull().copy()
            if kind == "push_dense":
                table.push_grad(req["grad"])
                return b"ok"
            if kind == "pull_sparse":
                return table.pull(req["ids"])
            if kind == "push_sparse":
                table.push_grad(req["ids"], req["grads"])
                return b"ok"
        raise ValueError(kind)

    # -- cross-process service over distributed.rpc -------------------
    def serve_rpc(self, name="ps0", rank=None, world_size=None,
                  master_endpoint=None):
        """Join the rpc world as ``name`` and expose the tables; returns
        after rendezvous (requests are served by the rpc agent's
        threads). Call `paddle_tpu.distributed.rpc.shutdown()` to stop.
        """
        global _SERVER
        from . import rpc
        _SERVER = self
        rpc.init_rpc(name, rank=rank, world_size=world_size,
                     master_endpoint=master_endpoint)

    # legacy store-keyed request path (kept for API compat)
    def handle_once(self, req_key):
        import pickle
        if self.store is None:
            raise RuntimeError(
                "handle_once needs the TCPStore transport; this server "
                "was built with use_store=False (rpc mode)")
        req = pickle.loads(self.store.get(req_key))
        resp = self._handle(req)
        self.store.set(req_key + "/resp", pickle.dumps(resp))


class PSWorker:
    """Pull/push client. Modes: direct (in-process `server=`), or rpc
    (`ps_name=` after the worker's own `rpc.init_rpc`)."""

    def __init__(self, server: PSServer = None, host=None, port=None,
                 ps_name=None):
        self.server = server
        self.ps_name = ps_name
        self._seq = 0
        if server is None and ps_name is None:
            self.store = TCPStore(host, port, is_master=False)
        elif server is not None:
            self.store = server.store

    def _rpc(self, msg):
        if self.server is not None:
            return self.server._handle(msg)
        if self.ps_name is not None:
            from . import rpc
            return rpc.rpc_sync(self.ps_name, _serve, args=(msg,))
        import pickle
        self._seq += 1
        key = f"psreq/{id(self)}/{self._seq}"
        self.store.set(key, pickle.dumps(msg))
        self.store.wait([key + "/resp"], timeout=30)
        resp = pickle.loads(self.store.get(key + "/resp"))
        self.store.delete_key(key)
        self.store.delete_key(key + "/resp")
        return resp

    def _rpc_async(self, msg):
        """Async push (reference async/geo-SGD mode): returns a future.
        In direct in-process mode the push is applied immediately and a
        completed future is returned (same contract, no thread)."""
        from . import rpc
        if self.ps_name is not None:
            return rpc.rpc_async(self.ps_name, _serve, args=(msg,))
        result = self._rpc(msg)

        class _Done:
            def wait(self, timeout=None):
                return result

            def done(self):
                return True

        return _Done()

    def pull_dense(self, table):
        return self._rpc({"op": "pull_dense", "table": table})

    def push_dense_grad(self, table, grad, sync=True):
        msg = {"op": "push_dense", "table": table,
               "grad": np.asarray(grad, np.float32)}
        return self._rpc(msg) if sync else self._rpc_async(msg)

    def pull_sparse(self, table, ids):
        return self._rpc({"op": "pull_sparse", "table": table,
                          "ids": list(map(int, ids))})

    def push_sparse_grad(self, table, ids, grads, sync=True):
        msg = {"op": "push_sparse", "table": table,
               "ids": list(map(int, ids)),
               "grads": np.asarray(grads, np.float32)}
        return self._rpc(msg) if sync else self._rpc_async(msg)
