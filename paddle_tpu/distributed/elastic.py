"""Elastic training manager.

Parity: reference `python/paddle/distributed/fleet/elastic/manager.py` —
ElasticManager (:124): node registration + lease heartbeat (:253), host
watching, fault-tolerance vs scale-in/out (:456,:483,:506), relaunch via
LauncherInterface. TPU-first: the native TCPStore replaces etcd for
registration/heartbeat (the launch CLI supplies process restart; on Cloud
TPU the platform handles node replacement, so the manager's job is
membership tracking + restart signaling).
"""

from __future__ import annotations

import threading
import time

from ..core import resilience
from .store import TCPStore

__all__ = ["ElasticManager", "ElasticStatus"]


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    def __init__(self, host="127.0.0.1", port=0, np=1, node_id=0,
                 is_master=False, heartbeat_interval=2.0,
                 lease_ttl=10.0):
        self.np = np
        self.node_id = node_id
        self.heartbeat_interval = heartbeat_interval
        self.lease_ttl = lease_ttl
        self.store = TCPStore(host, port, is_master=is_master,
                              world_size=np)
        self.port = self.store.port
        self._stop = threading.Event()
        self._hb_thread = None
        self._last_status = ElasticStatus.HOLD
        self.enabled = True

    # -- registration + heartbeat (reference manager.py:253) --------------
    def register(self):
        # registration is the node's rendezvous: a store hiccup here
        # must not drop the node from the job, so it rides the
        # elastic.store retry policy (idempotent set; the membership
        # add runs once, after the lease is durably published)
        def _publish():
            self.store.set(f"node/{self.node_id}", str(time.time()))
        resilience.retry_call(
            _publish, policy=resilience.policy(
                "elastic.store", retry_on=(RuntimeError, OSError)))
        self.store.add("nodes", 1)
        self._hb_thread = threading.Thread(target=self._heartbeat,
                                           daemon=True)
        self._hb_thread.start()

    def _heartbeat(self):
        while not self._stop.is_set():
            self.store.set(f"node/{self.node_id}", str(time.time()))
            self._stop.wait(self.heartbeat_interval)

    def alive_nodes(self, expect=None):
        """Nodes whose lease is fresh."""
        n = expect or self.np
        now = time.time()
        alive = []
        for i in range(n):
            try:
                ts = float(self.store.get(f"node/{i}"))
            except KeyError:
                continue
            if now - ts < self.lease_ttl:
                alive.append(i)
        return alive

    # -- failure classification (reference :456,:483,:506) ----------------
    def watch(self, expect=None):
        """Classify the current membership: HOLD (all present), RESTART
        (fault tolerance: same np possible after relaunch), EXIT (cannot
        recover)."""
        n = expect or self.np
        alive = self.alive_nodes(n)
        status = ElasticStatus.HOLD if len(alive) == n else \
            ElasticStatus.RESTART if alive else ElasticStatus.EXIT
        # a membership TRANSITION is a degradation event: count it and
        # flight-record which nodes went missing so a later hang report
        # shows the history. Per-transition, not per-poll — a node down
        # for minutes of 2s polls must not flood the flight ring
        if status != self._last_status and status != ElasticStatus.HOLD:
            missing = sorted(set(range(n)) - set(alive))
            resilience.degrade(f"elastic.{status}",
                               detail=f"missing nodes {missing} of {n}")
        self._last_status = status
        return status

    def signal_restart(self):
        self.store.add("restart_epoch", 1)

    def restart_epoch(self):
        try:
            return int(self.store.get("restart_epoch"))
        except KeyError:
            return 0

    def exit(self, completed=True):
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2)
        self.store.set(f"node/{self.node_id}/status",
                       ElasticStatus.COMPLETED if completed else
                       ElasticStatus.ERROR)
