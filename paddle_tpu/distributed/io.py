"""`paddle.distributed.io` (reference distributed/io.py: persistables
save/load for distributed programs — here the sharded checkpoint)."""

from __future__ import annotations

from .checkpoint import (  # noqa: F401
    async_save_state_dict, load_state_dict, save_state_dict,
)


def save_persistables(executor=None, dirname=None, main_program=None,
                      filename=None):
    raise NotImplementedError(
        "static programs do not exist in this build; use "
        "paddle_tpu.distributed.checkpoint.save_state_dict")


def load_persistables(executor=None, dirname=None, main_program=None,
                      filename=None):
    raise NotImplementedError(
        "static programs do not exist in this build; use "
        "paddle_tpu.distributed.checkpoint.load_state_dict")
