"""Collective watchdog: per-step timeout detection + flight records +
coordination-service heartbeats.

Capability parity with the reference's comm watchdog
(`paddle/phi/core/distributed/comm_task_manager.h:37` background thread,
`nccl_comm_task.cc:234` IsTimeout, `comm_task_manager.cc:142-180`
store-based flight records for hang diagnosis).

TPU mapping: collectives live inside compiled XLA programs, so the unit of
supervision is the STEP (one dispatched executable), not one NCCL kernel.
The watchdog arms a timer around each watched step; if the step's outputs
do not become ready within `FLAGS_distributed_timeout` seconds it dumps a
diagnosis — the flight-record ring (recent steps with timings and mesh
info), every Python thread's stack, and peer heartbeat ages — then either
aborts the process (`fatal=True`, the reference's store-teardown analogue)
or keeps waiting with the diagnosis logged.

Heartbeats: in multi-process runs a daemon thread publishes
`heartbeat/<rank>` through the TCPStore (or any dict-like store) every
`interval` seconds; the timeout report shows each peer's last-seen age so
a hang can be attributed (all peers alive = deadlock/slow collective; a
dead peer = failed host).
"""

from __future__ import annotations

import faulthandler
import io
import json
import os
import sys
import threading
import time
from collections import deque

from ..core.flags import get_flags

__all__ = ["FlightRecorder", "CollectiveWatchdog", "get_watchdog",
           "watch_step", "flight_recorder", "record_event"]


def _active_trace_id():
    """The ambient request trace_id (profiler/tracing.py), or None.
    Lazy-bound: the watchdog must import standalone (launcher helpers)
    without dragging the profiler package in."""
    try:
        from ..profiler import tracing
    except Exception:  # noqa: BLE001 — telemetry probe, never fatal
        return None
    return tracing.current_trace_id()


class FlightRecorder:
    """Ring buffer of recent step records (the reference's store-based
    flight recording, comm_task_manager.cc:142). Records are stamped
    with the active trace_id when one exists, so a timeout dump (or
    the "Recent incidents" summary view) links each event back to the
    request that was in flight."""

    def __init__(self, capacity=64):
        self._buf = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = 0

    def start(self, tag, meta=None):
        tid = _active_trace_id()
        with self._lock:
            self._seq += 1
            rec = {"seq": self._seq, "tag": tag, "start": time.time(),
                   "end": None, "status": "running", **(meta or {})}
            if tid is not None and "trace" not in rec:
                rec["trace"] = tid
            self._buf.append(rec)
            return rec

    def finish(self, rec, status="done"):
        with self._lock:
            rec["end"] = time.time()
            rec["status"] = status

    def records(self):
        with self._lock:
            return [dict(r) for r in self._buf]

    def dump(self, file=None):
        out = file or sys.stderr
        now = time.time()
        for r in self.records():
            dur = (r["end"] or now) - r["start"]
            print(f"  [flight {r['seq']}] {r['tag']}: {r['status']} "
                  f"{dur:.1f}s" + (
                      f" meta={json.dumps({k: v for k, v in r.items() if k not in ('seq', 'tag', 'start', 'end', 'status')})}"
                      if len(r) > 5 else ""), file=out)


class _Heartbeat(threading.Thread):
    def __init__(self, store, rank, world, interval):
        super().__init__(daemon=True, name="paddle-tpu-heartbeat")
        self.store = store
        self.rank = rank
        self.world = world
        self.interval = interval
        self._stop = threading.Event()

    def run(self):
        while not self._stop.is_set():
            try:
                self.store.set(f"heartbeat/{self.rank}",
                               str(time.time()).encode())
            except Exception:
                pass
            self._stop.wait(self.interval)

    def stop(self):
        self._stop.set()

    def peer_ages(self):
        ages = {}
        now = time.time()
        for r in range(self.world):
            try:
                raw = self.store.get(f"heartbeat/{r}", timeout=1)
                ages[r] = now - float(raw.decode())
            except Exception:
                ages[r] = None  # never seen / unreachable
        return ages


class CollectiveWatchdog:
    """Supervises watched steps; see module docstring."""

    def __init__(self, timeout=None, store=None, rank=0, world=1,
                 heartbeat_interval=10.0, fatal=False, out=None):
        flag_timeout = get_flags("FLAGS_distributed_timeout")[
            "FLAGS_distributed_timeout"]
        self.timeout = float(timeout if timeout is not None
                             else flag_timeout)
        self.recorder = FlightRecorder()
        self.fatal = fatal
        self.out = out
        self.timed_out = threading.Event()
        self._hb = None
        if store is not None and world > 1:
            self._hb = _Heartbeat(store, rank, world, heartbeat_interval)
            self._hb.start()

    def close(self):
        if self._hb is not None:
            self._hb.stop()

    # -- supervision ------------------------------------------------------

    def watch(self, tag, meta=None):
        return _Watch(self, tag, meta)

    def _on_timeout(self, rec):
        self.timed_out.set()
        out = self.out or sys.stderr
        print(f"\n=== paddle_tpu collective watchdog: step "
              f"'{rec['tag']}' exceeded {self.timeout:.0f}s ===", file=out)
        print("flight records (most recent last):", file=out)
        self.recorder.dump(out)
        if self._hb is not None:
            print("peer heartbeat ages (s):", self._hb.peer_ages(),
                  file=out)
        print("python thread stacks:", file=out)
        buf = io.StringIO()
        try:
            faulthandler.dump_traceback(file=buf)
        except Exception:
            pass
        print(buf.getvalue(), file=out)
        print("=== end watchdog report ===", file=out, flush=True)
        if self.fatal:
            os._exit(113)


class _Watch:
    def __init__(self, wd, tag, meta):
        self.wd = wd
        self.tag = tag
        self.meta = meta
        self.rec = None
        self.timer = None

    def __enter__(self):
        self.rec = self.wd.recorder.start(self.tag, self.meta)
        self.timer = threading.Timer(self.wd.timeout,
                                     self.wd._on_timeout, (self.rec,))
        self.timer.daemon = True
        self.timer.start()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.timer.cancel()
        self.wd.recorder.finish(
            self.rec, "done" if exc_type is None else "error")
        return False


_global = [None]

# standalone ring for processes that never arm a watchdog: degradation
# events (core/resilience.degrade) must always land in SOME flight
# recorder, or single-process post-mortems lose the fallback history
_standalone_recorder = FlightRecorder(capacity=128)


def flight_recorder():
    """The global watchdog's recorder when one exists, else the
    standalone module ring. Event producers (resilience.degrade,
    checkpoint quarantine) call this per event, so records migrate to
    the watchdog's ring as soon as one is configured."""
    if _global[0] is not None:
        return _global[0].recorder
    return _standalone_recorder


def record_event(tag, meta=None, status="degraded"):
    """Append a point-in-time (already finished) flight record — the
    degradation-event hook; ``status`` labels it in dumps."""
    rec = flight_recorder().start(tag, meta)
    flight_recorder().finish(rec, status)
    return rec


def get_watchdog(**kwargs):
    """Process-global watchdog (created on first use). Pass kwargs on the
    first call to configure; subsequent calls return the instance."""
    if _global[0] is None:
        _global[0] = CollectiveWatchdog(**kwargs)
    return _global[0]


def watch_step(tag="step", meta=None):
    """Context manager supervising one training/eval step with the global
    watchdog. Enabled when FLAGS_enable_collective_watchdog is on or a
    watchdog was explicitly configured; otherwise a no-op."""
    flags = get_flags(["FLAGS_enable_collective_watchdog"])
    if _global[0] is None and \
            not flags.get("FLAGS_enable_collective_watchdog"):
        import contextlib
        return contextlib.nullcontext()
    return get_watchdog().watch(tag, meta)
