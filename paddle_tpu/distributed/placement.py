"""Placements: Shard / Replicate / Partial.

Parity: reference placement types (paddle/phi/core/distributed/
auto_parallel/placement_types.h; python placement_type.py) and
`TensorDistAttr.dims_mapping` (dist_attr.h:81). TPU mapping: a list of
placements (one per mesh dim) converts exactly to a
`jax.sharding.PartitionSpec` (one entry per TENSOR dim) — the same duality
the reference maintains between placements and dims_mapping.
"""

from __future__ import annotations

from jax.sharding import NamedSharding, PartitionSpec


class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicated(self):
        return False

    def is_partial(self):
        return False


class Replicate(Placement):
    def is_replicated(self):
        return True

    def __repr__(self):
        return "Replicate()"

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("R")


class Shard(Placement):
    def __init__(self, dim):
        self.dim = int(dim)

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def get_dim(self):
        return self.dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("S", self.dim))


class Partial(Placement):
    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __repr__(self):
        return f"Partial({self.reduce_type})"

    def __eq__(self, other):
        return isinstance(other, Partial) and \
            other.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("P", self.reduce_type))


def placements_to_spec(placements, mesh, ndim):
    """[placement per MESH dim] -> PartitionSpec (per TENSOR dim).

    The inverse of the reference's dims_mapping: placements[i]=Shard(d)
    means tensor dim d is split over mesh axis i. Multiple mesh axes on one
    tensor dim stack (GSPMD tuple spec). Partial has no PartitionSpec form —
    it only exists transiently inside computations (XLA handles it)."""
    entries = [None] * ndim
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            axis_name = mesh.dim_names[mesh_dim]
            cur = entries[pl.dim]
            if cur is None:
                entries[pl.dim] = axis_name
            elif isinstance(cur, tuple):
                entries[pl.dim] = cur + (axis_name,)
            else:
                entries[pl.dim] = (cur, axis_name)
    while entries and entries[-1] is None:
        entries.pop()
    return PartitionSpec(*entries)


def spec_to_placements(spec, mesh, ndim):
    """PartitionSpec -> [placement per mesh dim]."""
    placements = [Replicate() for _ in range(mesh.ndim)]
    for tdim, entry in enumerate(spec):
        if entry is None:
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        for name in names:
            placements[mesh.dim_names.index(name)] = Shard(tdim)
    return placements


def named_sharding(mesh, placements, ndim):
    return NamedSharding(mesh.jax_mesh,
                         placements_to_spec(placements, mesh, ndim))
