"""Graph-optimization passes for the deferred elementwise IR.

The compile-pipeline layer between chain capture and ``jax.jit``
(core/deferred.flush): DCE, hash-cons CSE, constant folding, and
algebraic canonicalization over the immutable linearized chain graph.
The reference stack dedicates `paddle/pir` pass infrastructure + the
CINN compiler to this role; here the IR is the `_linearize` postorder
form and every rewrite must be IEEE-bitwise-exact (docs/PASSES.md).

Toggle: ``FLAGS_deferred_passes`` / env ``PADDLE_TPU_PASSES=0`` reverts
flush to the verbatim (unoptimized) compile path.
"""

from .ir import CONST, LEAF, NODE, Graph, GraphNode  # noqa: F401
from .batch import (BatchedFn, BatchIdenticalSubtrees,  # noqa: F401
                    BatchSlice)
from .canon import Canonicalize  # noqa: F401
from .cse import HashConsCSE  # noqa: F401
from .dce import DeadCodeElim  # noqa: F401
from .fold import ConstantFold  # noqa: F401
from .fuse import FusedFn, FuseElementwise  # noqa: F401
from .manager import (PassError, PassManager, default_manager,  # noqa: F401
                      default_passes)

__all__ = [
    "CONST", "LEAF", "NODE", "Graph", "GraphNode",
    "Canonicalize", "ConstantFold", "HashConsCSE", "DeadCodeElim",
    "BatchIdenticalSubtrees", "BatchedFn", "BatchSlice",
    "FuseElementwise", "FusedFn",
    "PassError", "PassManager", "default_manager", "default_passes",
]
