"""Hash-cons common-subexpression elimination.

``_linearize`` dedupes chain nodes by python ``id`` only, so two
structurally identical subtrees built as distinct Expr objects (a loop
body re-applied per branch, two tensors mapped through the same formula)
each occupy nodes, re-execute, and — worse — produce DIFFERENT jit cache
keys for chains that compute the same program. One topological sweep
hash-conses every node on ``(node_key, resolved args)``: later
duplicates alias to the first occurrence, consumers rewire, and the
orphaned husks fall to DCE.

Merging identical applications of a pure fn to identical inputs is
value-exact by construction (same computation, computed once), and
because the hash key is STRUCTURAL (fn behavior key + argument wiring,
never python object identity), structurally equal chains from different
Python objects canonicalize to one cache key — one compile, then hits.
"""

from __future__ import annotations

from .ir import NODE, resolve


class HashConsCSE:
    """metric: passes.cse.merged"""

    name = "cse"
    metric_name = "passes.cse.merged"

    def run(self, graph):
        alias = {}
        seen = {}
        new_nodes = []
        count = 0
        for i, n in enumerate(graph.nodes):
            args = tuple(resolve(a, alias) for a in n.args)
            try:
                key = (n.node_key, args)
                hash(key)
            except TypeError:
                new_nodes.append(n.with_args(args))
                continue  # unhashable structural key: never merged
            first = seen.get(key)
            if first is not None:
                alias[(NODE, i)] = (NODE, first)
                count += 1
            else:
                seen[key] = i
            new_nodes.append(n.with_args(args))
        if not count:
            return graph, 0
        outputs = tuple(resolve(o, alias) for o in graph.outputs)
        return graph.replace(nodes=new_nodes, outputs=outputs), count
