"""PassManager: the ordered rewrite pipeline core/deferred.flush runs
between ``_linearize`` and jit-cache lookup.

A pass is any object with ``name`` (short slug), ``metric_name`` (the
``profiler.metrics`` counter fed with its rewrite count) and
``run(graph) -> (graph, n_rewrites)`` honoring the contracts in
``ir.Graph``'s docstring (topo order, bitwise value preservation,
structural determinism). Adding a pass is: write the class, append an
instance to ``default_passes()`` at the right point in the order (see
docs/PASSES.md for the ordering rationale), done — the manager handles
counters and timing uniformly.

Default order:

1. ``canon``  — identity elimination + commutative ordering (creates
   dead husks, exposes duplicate structure)
2. ``fold``   — const-only subtrees to folded leaves
3. ``cse``    — hash-cons merge (benefits from canonical operand order)
4. ``batch``  — (fusion tier) identical distinct-leaf subtrees to one
   batched call (after cse so same-input duplicates are already merged)
5. ``fuse``   — (fusion tier) single-consumer runs to super-nodes
   (last rewrite: it collapses the structure batch matches on)
6. ``dce``    — one sweep collects everything the others orphaned

Per-run cost lands in the ``passes.total_us`` histogram (the gate in
tools/passes_gate.py budgets it); each pass's rewrite count lands in its
own counter (``passes.dce.removed``, ``passes.cse.merged``, ...), and
``passes.runs`` counts pipeline invocations.
"""

from __future__ import annotations

import time

from ..profiler import metrics as _metrics
from .batch import BatchIdenticalSubtrees
from .canon import Canonicalize
from .cse import HashConsCSE
from .dce import DeadCodeElim
from .fold import ConstantFold
from .fuse import FuseElementwise

_C_RUNS = _metrics.counter("passes.runs")
_C_ERRORS = _metrics.counter("passes.errors")
_H_TOTAL_US = _metrics.histogram(
    "passes.total_us", bounds=(10, 50, 100, 500, 1000, 5000, 10_000))


class PassError(RuntimeError):
    """A rewrite pass raised. Carries the pass name so the flush
    degradation ladder's flight record (core/deferred.py rung 1) names
    the culprit instead of an anonymous pipeline failure."""


class PassManager:
    """Runs passes in order over an ``ir.Graph``; counts and times."""

    def __init__(self, passes):
        self.passes = list(passes)
        self._counters = [_metrics.counter(p.metric_name)
                          for p in self.passes]

    def run(self, graph):
        t0 = time.perf_counter_ns()
        for p, c in zip(self.passes, self._counters):
            try:
                graph, n = p.run(graph)
            except Exception as e:
                _C_ERRORS.inc()
                raise PassError(
                    f"pass '{p.name}' failed: "
                    f"{type(e).__name__}: {e}") from e
            if n:
                c.inc(n)
        _C_RUNS.inc()
        _H_TOTAL_US.observe((time.perf_counter_ns() - t0) / 1000.0)
        return graph


def default_passes(fusion=False):
    """The cleanup pipeline, optionally extended with the fusion tier.

    Ordering rationale (docs/PASSES.md): batch runs AFTER cse (CSE
    merges same-input duplicates first, so batch only sees genuinely
    distinct-leaf towers — and canonical operand order makes towers
    structurally comparable) and BEFORE fuse (fusion collapses the
    per-node structure batch matches on); dce last sweeps every husk
    the earlier tiers orphaned."""
    ps = [Canonicalize(), ConstantFold(), HashConsCSE()]
    if fusion:
        ps += [BatchIdenticalSubtrees(), FuseElementwise()]
    ps.append(DeadCodeElim())
    return ps


_DEFAULT = None
_DEFAULT_FUSION = None


def default_manager(fusion=False):
    """Process-wide manager instances — one cleanup-only pipeline, one
    with the fusion tier (passes are stateless; a benign construction
    race just builds an equivalent pipeline). The flush picks by
    ``FLAGS_deferred_fusion`` and keys the jit cache ``passes/v2`` for
    the fusion pipeline so fused and unfused programs never collide."""
    global _DEFAULT, _DEFAULT_FUSION
    if fusion:
        if _DEFAULT_FUSION is None:
            _DEFAULT_FUSION = PassManager(default_passes(fusion=True))
        return _DEFAULT_FUSION
    if _DEFAULT is None:
        _DEFAULT = PassManager(default_passes())
    return _DEFAULT
