"""Constant folding: collapse const-only subtrees into single values.

A node whose arguments are ALL consts computes the same value every
execution of the structure *for the same scalar inputs* — so it is
evaluated once here (exactly as the jitted program would: each const
becomes a 0-d array at the CHAIN dtype via core/deferred._const_arr, and
the node's own fn runs on those), and the result joins the graph as a
fresh 0-d LEAF. Leaves, like consts, ride as jit call arguments, so the
folded VALUE stays out of the compile cache key — the fold decision is
purely structural and deterministic, keeping cache keys canonical.

Evaluation is memoized on (node structural key, const value reprs,
dtype) — ``repr`` keeps ``-0.0`` distinct from ``0.0`` exactly like the
const memo in core/deferred.py — so steady-state loops over the same
scalars never re-dispatch the fold.

Note the engine's own capture rules (core/deferred.try_defer rejects ops
with no tensor argument) mean chains built through the public op surface
contain no const-only nodes today; the pass earns its place on graphs
canonicalization produces and on IR constructed by other front ends
(tests build such graphs directly).
"""

from __future__ import annotations

import threading

from .ir import CONST, LEAF, NODE, resolve

_FOLD_MEMO: dict = {}
_FOLD_MEMO_MAX = 4096
_FOLD_LOCK = threading.Lock()


def _eval_const_node(node, cvals, dtype):
    """fn(*consts-as-0d-arrays) at the chain dtype, memoized; None when
    the op refuses (fold then simply leaves the node in place)."""
    try:
        key = (node.node_key, tuple(repr(c) for c in cvals), str(dtype))
    except TypeError:
        return None
    out = _FOLD_MEMO.get(key)
    if out is None:
        from ..core.deferred import _const_arr
        try:
            fresh = node.fn(*[_const_arr(c, dtype) for c in cvals],
                            **node.kwargs)
        except Exception:  # noqa: BLE001 — unfoldable op: skip, don't break
            return None
        if getattr(fresh, "shape", None) != () or \
                getattr(fresh, "dtype", None) != dtype:
            return None  # op changed rank/dtype: not a chain-safe fold
        with _FOLD_LOCK:
            if len(_FOLD_MEMO) > _FOLD_MEMO_MAX:
                _FOLD_MEMO.clear()
            out = _FOLD_MEMO.setdefault(key, fresh)
    return out


class ConstantFold:
    """metric: passes.fold.folded"""

    name = "fold"
    metric_name = "passes.fold.folded"

    def run(self, graph):
        alias = {}
        new_nodes = []
        leaves = list(graph.leaves)
        leaf_ix = {id(v): i for i, v in enumerate(leaves)}
        count = 0
        for i, n in enumerate(graph.nodes):
            args = tuple(resolve(a, alias) for a in n.args)
            if args and all(k == CONST for k, _ in args):
                val = _eval_const_node(
                    n, [graph.consts[ix] for _, ix in args], graph.dtype)
                if val is not None:
                    # memo returns one array object per (structure,
                    # values): reuse its leaf slot across the graph
                    ix = leaf_ix.get(id(val))
                    if ix is None:
                        ix = leaf_ix[id(val)] = len(leaves)
                        leaves.append(val)
                    alias[(NODE, i)] = (LEAF, ix)
                    count += 1
            new_nodes.append(n.with_args(args))
        if not count:
            return graph, 0
        outputs = tuple(resolve(o, alias) for o in graph.outputs)
        return graph.replace(nodes=new_nodes, leaves=leaves,
                             outputs=outputs), count
