"""Algebraic canonicalization: IEEE-exact identity elimination plus
commutative-operand ordering.

Two jobs, both about CACHE-KEY unification as much as program size:

- identity ops vanish (``x * 1.0``, ``x / 1.0``, ``x - 0.0``,
  ``x + (-0.0)``, ``neg(neg(x))``) — the consumer rewires to the
  operand, DCE sweeps the husk;
- commutative ops (``add``, ``multiply``) order their two operands
  canonically (consts < leaves < nodes, then by index) so ``x * y`` and
  ``y * x`` compile once between them.

Only BITWISE-exact rewrites are admitted — the deferred engine promises
flag-off-identical results, so fast-math algebra is out of bounds:

- ``x + 0.0`` is NOT eliminated: for ``x = -0.0`` IEEE-754 addition
  yields ``+0.0``, not ``x``. Only the sign-preserving ``x + (-0.0)``
  and ``x - (+0.0)`` are identities.
- ``x * 1.0``, ``x / 1.0`` are exact for every input (signed zeros,
  infinities, NaN).
- ``neg(neg(x))`` is a double sign-bit flip — exact including NaN.
- Known, accepted exception: SIGNALING NaN payloads. Eliminating an
  identity op returns the input array itself, while actually executing
  the op quiets an sNaN (0x7f800001 -> 0x7fc00001), so a chain fed
  sNaN bits (bitcast/corrupted data — no public op produces them)
  differs from the verbatim path in the quiet bit. Quieting is
  hardware-dependent anyway; sNaN transparency is out of scope for the
  whole engine, matching IEEE 754 §6.2's latitude on NaN propagation.
- ``maximum``/``minimum`` do NOT commute bitwise (``np.maximum(0., -0.)``
  is ``-0.0`` but ``np.maximum(-0., 0.)`` is ``+0.0``) and are excluded.

Rewrite decisions read CONST VALUES (which ride as jit arguments, outside
the cache key) — that is sound because the decision itself reshapes the
graph, so a chain where the scalar happens to be 1.0 simply maps to a
different (smaller) cache entry than the same chain at 2.0.

Ops are recognized by the identity of the fn the op library dispatches
(jnp ufunc singletons); wrapper closures like ``scale``/``clip`` keys
are deliberately NOT matched — their semantics live in python code this
pass does not inspect.
"""

from __future__ import annotations

import math

from .ir import CONST, NODE, ref_sort_key, resolve

_TABLES = None


def _tables():
    """(commutative fn set, rule dispatch) — built lazily so importing
    the pass package never forces jax initialization ordering."""
    global _TABLES
    if _TABLES is None:
        import jax.numpy as jnp
        commutative = (jnp.add, jnp.multiply)
        _TABLES = {
            "commutative": commutative,
            "add": jnp.add, "sub": jnp.subtract,
            "mul": jnp.multiply, "div": jnp.divide,
            "neg": jnp.negative,
        }
    return _TABLES


def _is_neg_zero(c):
    return c == 0.0 and math.copysign(1.0, c) < 0


def _is_pos_zero(c):
    return c == 0.0 and math.copysign(1.0, c) > 0


def _identity_target(fn, args, consts, t):
    """The reference this node is an identity of, or None."""
    if fn is t["neg"]:
        return None  # unary: handled by the double-neg rule in run()
    if len(args) != 2:
        return None
    (k0, i0), (k1, i1) = args
    if fn is t["add"]:
        # x + (-0.0) == x bitwise for every x; x + (+0.0) flips -0.0
        if k1 == CONST and _is_neg_zero(consts[i1]):
            return args[0]
        if k0 == CONST and _is_neg_zero(consts[i0]):
            return args[1]
    elif fn is t["sub"]:
        if k1 == CONST and _is_pos_zero(consts[i1]):
            return args[0]
    elif fn is t["mul"]:
        if k1 == CONST and consts[i1] == 1.0:
            return args[0]
        if k0 == CONST and consts[i0] == 1.0:
            return args[1]
    elif fn is t["div"]:
        if k1 == CONST and consts[i1] == 1.0:
            return args[0]
    return None


class Canonicalize:
    """metric: passes.canon.rewrites"""

    name = "canon"
    metric_name = "passes.canon.rewrites"

    def run(self, graph):
        t = _tables()
        alias = {}
        new_nodes = []
        count = 0
        for i, n in enumerate(graph.nodes):
            args = tuple(resolve(a, alias) for a in n.args)
            if not n.kwargs:
                # identity elimination: alias this node away
                target = _identity_target(n.fn, args, graph.consts, t)
                if target is None and n.fn is t["neg"] and len(args) == 1 \
                        and args[0][0] == NODE:
                    inner = new_nodes[args[0][1]]
                    if inner.fn is t["neg"] and not inner.kwargs \
                            and len(inner.args) == 1:
                        target = inner.args[0]  # already resolved
                if target is not None:
                    alias[(NODE, i)] = target
                    count += 1
                    # keep the (now dead) husk so indices stay stable;
                    # DCE renumbers in one sweep at the end of the pipe
                    new_nodes.append(n.with_args(args))
                    continue
                if n.fn in t["commutative"] and len(args) == 2:
                    ordered = tuple(sorted(args, key=ref_sort_key))
                    if ordered != args:
                        count += 1
                        args = ordered
            new_nodes.append(n.with_args(args))
        if not count:
            return graph, 0
        outputs = tuple(resolve(o, alias) for o in graph.outputs)
        return graph.replace(nodes=new_nodes, outputs=outputs), count
