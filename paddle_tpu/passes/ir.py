"""The deferred-chain IR the graph-optimization passes rewrite.

This is the `_linearize` postorder form of core/deferred.py lifted into
an immutable value: a topologically ordered tuple of ``GraphNode``s whose
arguments are ``(kind, index)`` references into the node list, the leaf
list (concrete jax arrays, the jit's array arguments) or the const list
(python floats that ride as 0-d jit arguments so their VALUES stay out
of the compile cache key).

Contracts every pass must preserve (see docs/PASSES.md):

- topological order: a node's ``("node", j)`` references satisfy j < i;
- value semantics: for any leaf/const assignment, evaluating the
  rewritten graph yields BITWISE-identical values for every output slot
  (passes may only apply IEEE-exact rewrites — no fast-math). Sole
  carve-out: signaling-NaN payloads, which executing an op quiets but
  an identity elimination passes through untouched (see canon.py —
  no public op produces sNaN bits, and quieting is hardware-dependent);
- output arity and order: ``outputs[k]`` of the rewritten graph computes
  the same value as ``outputs[k]`` of the input graph (the reference may
  move between kinds, e.g. a node collapsing to a leaf);
- structural determinism: the rewritten graph is a function of the input
  STRUCTURE plus const values only — never of leaf array contents or
  python object identity — so structurally equal chains map to equal
  ``cache_key()``s.

Reference analogue: `paddle/pir` keeps one Program the passes mutate in
place under a rewrite driver; here graphs are tiny (<= DEFER_CAP nodes)
so passes return fresh immutable graphs instead, which keeps every pass
trivially thread-safe (chains are built and flushed from worker threads).
"""

from __future__ import annotations

NODE = "node"
LEAF = "leaf"
CONST = "const"

# canonical operand order for commutative-op sorting: consts first, then
# leaves, then nodes, each by index — stable across structurally equal
# chains because indices are discovery-ordered
_KIND_RANK = {CONST: 0, LEAF: 1, NODE: 2}


def ref_sort_key(ref):
    kind, ix = ref
    return (_KIND_RANK[kind], ix)


def resolve(ref, alias):
    """Chase an alias map ``{ref: ref}`` to its fixed point. Pass
    implementations record rewrites as aliases and resolve argument /
    output references through this — a single topological sweep then
    handles arbitrarily nested rewrites (e.g. neg(neg(neg(x))))."""
    while ref in alias:
        ref = alias[ref]
    return ref


class GraphNode:
    """One op application: ``fn(*argrefs, **kwargs)``.

    ``node_key`` is the structural identity of the op — the
    ``(fn_key, frozen kwargs)`` pair core/deferred.py precomputes per
    Expr — and is what the jit cache key and CSE hash on; ``fn`` and
    ``kwargs`` are carried for execution and constant folding."""

    __slots__ = ("fn", "node_key", "kwargs", "args")

    def __init__(self, fn, node_key, kwargs, args):
        self.fn = fn
        self.node_key = node_key
        self.kwargs = kwargs
        self.args = tuple(args)

    def with_args(self, args):
        args = tuple(args)
        if args == self.args:
            return self
        return GraphNode(self.fn, self.node_key, self.kwargs, args)

    def __repr__(self):
        name = getattr(self.fn, "__name__", None) or repr(self.fn)
        return f"GraphNode({name}, args={self.args!r})"


class Graph:
    """Immutable linearized chain: nodes + leaves + consts + outputs.

    ``outputs`` is a tuple of references, one per requested result (the
    flush's live-owned Exprs, root included) — duplicates allowed (CSE
    may merge two requested nodes into one), and any kind allowed (a
    canonicalized-away root IS its argument leaf)."""

    __slots__ = ("nodes", "leaves", "consts", "outputs", "dtype")

    def __init__(self, nodes, leaves, consts, outputs, dtype):
        self.nodes = tuple(nodes)
        self.leaves = tuple(leaves)
        self.consts = tuple(consts)
        self.outputs = tuple(outputs)
        self.dtype = dtype

    @classmethod
    def from_linearized(cls, nodes, leaves, consts, out_ixs, dtype):
        """Build from core/deferred._linearize output: ``nodes`` is the
        postorder ``[(Expr, spec)]`` list, ``out_ixs`` the node indices
        to return (in stamping order)."""
        gnodes = [GraphNode(e.fn, e.node_key, e.kwargs, spec)
                  for e, spec in nodes]
        return cls(gnodes, leaves, consts,
                   tuple((NODE, i) for i in out_ixs), dtype)

    def cache_key(self):
        """Structural identity for the jit cache: node ops + wiring +
        output references. Leaf/const VALUES are excluded by design —
        they are call arguments, so loop-varying scalars and fresh
        device buffers reuse the compiled program."""
        return (tuple((n.node_key, n.args) for n in self.nodes),
                self.outputs)

    def replace(self, **kw):
        return Graph(kw.get("nodes", self.nodes),
                     kw.get("leaves", self.leaves),
                     kw.get("consts", self.consts),
                     kw.get("outputs", self.outputs),
                     kw.get("dtype", self.dtype))

    def validate(self):
        """Structural invariants (tests / debugging — not on the hot
        path): topo order, reference bounds, output bounds."""
        for i, n in enumerate(self.nodes):
            for kind, ix in n.args:
                if kind == NODE:
                    if not 0 <= ix < i:
                        raise ValueError(
                            f"node {i} breaks topo order: arg node {ix}")
                elif kind == LEAF:
                    if not 0 <= ix < len(self.leaves):
                        raise ValueError(f"node {i}: leaf {ix} OOB")
                elif kind == CONST:
                    if not 0 <= ix < len(self.consts):
                        raise ValueError(f"node {i}: const {ix} OOB")
                else:
                    raise ValueError(f"node {i}: unknown kind {kind!r}")
        for kind, ix in self.outputs:
            bound = {NODE: len(self.nodes), LEAF: len(self.leaves),
                     CONST: len(self.consts)}[kind]
            if not 0 <= ix < bound:
                raise ValueError(f"output ({kind}, {ix}) OOB")
        return self

    def __repr__(self):
        return (f"Graph(nodes={len(self.nodes)}, leaves="
                f"{len(self.leaves)}, consts={len(self.consts)}, "
                f"outputs={self.outputs!r})")
