"""Dead-code elimination: drop everything the outputs cannot reach.

Every node ``_linearize`` emits feeds the flush root by construction, so
on a raw capture this pass is a no-op — its real job is sweeping the
husks the OTHER passes orphan (CSE-merged duplicates, canonicalized-away
identities, folded const subtrees) plus any leaf/const slots those husks
were the last consumer of. Pruning matters beyond program size: dead
slots would otherwise linger in the jit argument list (device transfers)
and dead nodes in the cache key (spurious compile-cache misses between
chains that optimize to the same program).

Renumbering is order-preserving (surviving nodes/leaves/consts keep
their relative order), so the output graph is a deterministic function
of the input structure — a requirement for cache-key canonicalization.
"""

from __future__ import annotations

from .ir import CONST, LEAF, NODE


class DeadCodeElim:
    """metric: passes.dce.removed"""

    name = "dce"
    metric_name = "passes.dce.removed"

    def run(self, graph):
        nodes = graph.nodes
        live = set()
        stack = [ix for kind, ix in graph.outputs if kind == NODE]
        while stack:
            i = stack.pop()
            if i in live:
                continue
            live.add(i)
            for kind, ix in nodes[i].args:
                if kind == NODE and ix not in live:
                    stack.append(ix)
        removed = len(nodes) - len(live)
        # leaves/consts referenced by live nodes or directly by outputs
        used_leaves, used_consts = set(), set()
        for i in live:
            for kind, ix in nodes[i].args:
                if kind == LEAF:
                    used_leaves.add(ix)
                elif kind == CONST:
                    used_consts.add(ix)
        for kind, ix in graph.outputs:
            if kind == LEAF:
                used_leaves.add(ix)
            elif kind == CONST:
                used_consts.add(ix)
        if not removed and len(used_leaves) == len(graph.leaves) \
                and len(used_consts) == len(graph.consts):
            return graph, 0
        node_map = {}
        leaf_map = {old: new for new, old in enumerate(sorted(used_leaves))}
        const_map = {old: new for new, old in enumerate(sorted(used_consts))}

        def remap(ref):
            kind, ix = ref
            if kind == NODE:
                return (NODE, node_map[ix])
            if kind == LEAF:
                return (LEAF, leaf_map[ix])
            return (CONST, const_map[ix])

        new_nodes = []
        for i, n in enumerate(nodes):
            if i not in live:
                continue
            node_map[i] = len(new_nodes)
            new_nodes.append(n.with_args(remap(a) for a in n.args))
        return graph.replace(
            nodes=new_nodes,
            leaves=[graph.leaves[old] for old in sorted(used_leaves)],
            consts=[graph.consts[old] for old in sorted(used_consts)],
            outputs=tuple(remap(o) for o in graph.outputs)), removed
