"""Batch structurally-identical independent subtrees into one call.

CSE merges duplicated subtrees over the SAME inputs; this pass handles
the sibling case it cannot touch — the same op tower applied to
DIFFERENT leaves (``(a*2).tanh() + (b*2).tanh()``, a loop body mapped
over per-branch tensors). N structurally identical, disjoint,
leaf-rooted subtrees become ONE batched super-node that stacks each
abstract input slot across the members along a fresh leading axis, runs
the tower ONCE on the stacked arrays, and N cheap slice nodes that hand
each member its lane back.

Exactness: every member op is elementwise over its lane — stacking adds
a leading batch axis that no op reduces over or reassociates across, so
lane ``k`` of the batched computation applies the same ops to the same
values as member ``k`` did, element for element (0-d inputs are
reshaped to ``(N, 1, ..., 1)`` so they broadcast per-lane exactly as a
scalar broadcasts per-member). IEEE ops are value-deterministic per
element, so the results are bitwise identical.

A subtree qualifies as a member only when:

- every op is CORRECTLY ROUNDED per IEEE 754 (add/sub/mul/div/sqrt,
  sign ops, min/max, rounding ops): their per-element result is a
  function of the element value alone, independent of array extent.
  Approximated transcendentals (exp, tanh, sigmoid, ...) are EXCLUDED
  — XLA:CPU lowers them to vectorized polynomials whose scalar
  remainder loop can round the last elements differently than the
  vector body, so the same value in a ``(N, *S)`` stacked array and an
  ``S`` member array may differ by 1 ulp (measured on exp). Fusing
  them would break the bitwise contract;
- every argument is a LEAF or a CONST (towers over concrete inputs —
  node-boundary inputs would need static shape info the IR doesn't
  carry) and every leaf exposes ``shape``/``dtype``;
- every interior node has exactly one consumer, inside the subtree, and
  is not a flush output (the root may be consumed anywhere);
- abstract input slots agree in shape and dtype across members, and
  CONST references agree by index (const values ride as jit arguments;
  a differing const slot is a different structure);
- members are pairwise disjoint.

Groups need >= 2 members and >= 2 nodes per member — below that the
stack/slice overhead buys nothing.
"""

from __future__ import annotations

from .ir import CONST, LEAF, NODE, GraphNode

_BATCH_TAG = "__batch1__"
_SLICE_TAG = "__bslice1__"

_EXACT_FNS = None


def _exact_fns():
    """The correctly-rounded op set (see module docstring): batching is
    bitwise-safe only for ops whose per-element result cannot depend on
    vectorization extent. Built lazily (jnp ufunc singletons — the same
    identity-matching discipline as canon)."""
    global _EXACT_FNS
    if _EXACT_FNS is None:
        import jax.numpy as jnp
        _EXACT_FNS = frozenset({
            jnp.add, jnp.subtract, jnp.multiply, jnp.divide, jnp.sqrt,
            jnp.negative, jnp.abs, jnp.sign, jnp.maximum, jnp.minimum,
            jnp.floor, jnp.ceil, jnp.trunc, jnp.round, jnp.square,
        })
    return _EXACT_FNS


class BatchedFn:
    """Runs ``ops`` (the shared tower, args referencing ("slot", s),
    ("val", m) member results or ("const", c) shared 0-d constants) over
    ``n_members`` lanes. Positional args are slot-major member leaves
    (``args[s * n + k]`` = member k's array for slot s) followed by the
    shared const arrays; each slot is stacked on a fresh leading axis,
    0-d slots reshaped to broadcast per-lane (a shared 0-d const
    broadcasts over ``(n, *S)`` as-is — identical per lane); returns
    the stacked tower output (shape ``(n, *S)``)."""

    __slots__ = ("ops", "n_members", "n_slots", "scalar_slots", "rank",
                 "__name__")

    def __init__(self, ops, n_members, n_slots, scalar_slots, rank):
        self.ops = tuple(ops)
        self.n_members = n_members
        self.n_slots = n_slots
        self.scalar_slots = frozenset(scalar_slots)
        self.rank = rank
        self.__name__ = f"batched[{n_members}x{len(self.ops)}]"

    def __call__(self, *args):
        import jax.numpy as jnp
        n = self.n_members
        cargs = args[self.n_slots * n:]
        slots = []
        for s in range(self.n_slots):
            stacked = jnp.stack(args[s * n:(s + 1) * n])
            if s in self.scalar_slots and self.rank:
                stacked = stacked.reshape((n,) + (1,) * self.rank)
            slots.append(stacked)
        vals = []
        for fn, spec, kw in self.ops:
            argv = [slots[ix] if kind == "slot" else
                    vals[ix] if kind == "val" else cargs[ix]
                    for kind, ix in spec]
            vals.append(fn(*argv, **kw))
        return vals[-1]

    def __repr__(self):
        return f"BatchedFn(members={self.n_members}, ops={len(self.ops)})"


class BatchSlice:
    """Member ``k``'s lane of a batched super-node output."""

    __slots__ = ("k", "__name__")

    def __init__(self, k):
        self.k = k
        self.__name__ = f"bslice[{k}]"

    def __call__(self, stacked):
        return stacked[self.k]

    def __repr__(self):
        return f"BatchSlice({self.k})"


def _consumers(graph):
    n = len(graph.nodes)
    count = [0] * n
    for node in graph.nodes:
        for kind, ix in node.args:
            if kind == NODE:
                count[ix] += 1
    out_nodes = {ix for kind, ix in graph.outputs if kind == NODE}
    return count, out_nodes


def _subtree(graph, root, count, out_nodes):
    """Member candidate rooted at ``root``: (sorted node indices) or
    None when an interior node is shared/output or an arg is a NODE
    boundary. Leaf-rooted towers only (see module docstring)."""
    nodes = graph.nodes
    exact = _exact_fns()
    members, stack = set(), [root]
    while stack:
        i = stack.pop()
        if i in members:
            continue
        if nodes[i].fn not in exact or nodes[i].kwargs:
            return None  # not bitwise-safe under a batch axis
        if i != root and (count[i] != 1 or i in out_nodes):
            return None
        members.add(i)
        for kind, ix in nodes[i].args:
            if kind == NODE:
                if ix not in members:
                    stack.append(ix)
    # interior single-consumer + reachability-from-root together imply
    # the consumer IS a member: the edge that discovered the node
    return tuple(sorted(members))


def _signature(graph, members, root):
    """(key, slot_refs): the pattern abstracts LEAF refs to occurrence
    slots (stacked per member) and CONST refs to shared const slots
    whose GRAPH index is part of the key (consts are deduped by value
    repr at linearize time, so index equality pins value equality —
    members adding different scalars never batch together); slot_refs
    lists the actual leaf indices in occurrence order. None when a leaf
    has no shape/dtype."""
    local = {j: m for m, j in enumerate(members)}
    pattern, slot_refs, slot_meta = [], [], []
    const_refs, const_slot = [], {}
    for j in members:
        node = graph.nodes[j]
        spec = []
        for kind, ix in node.args:
            if kind == NODE:
                spec.append(("val", local[ix]))
            elif kind == CONST:
                c = const_slot.get(ix)
                if c is None:
                    c = const_slot[ix] = len(const_refs)
                    const_refs.append(ix)
                spec.append(("const", c))
            else:
                leaf = graph.leaves[ix]
                shape = getattr(leaf, "shape", None)
                dtype = getattr(leaf, "dtype", None)
                if shape is None or dtype is None:
                    return None
                s = len(slot_refs)
                slot_refs.append(ix)
                slot_meta.append((tuple(shape), str(dtype)))
                spec.append(("slot", s))
        try:
            pattern.append((node.node_key, tuple(spec)))
        except TypeError:
            return None
    return (tuple(pattern), tuple(slot_meta), tuple(const_refs)), \
        tuple(slot_refs)


class BatchIdenticalSubtrees:
    """metric: passes.batch.merged (member subtrees merged beyond the
    first of each group)"""

    name = "batch"
    metric_name = "passes.batch.merged"

    def run(self, graph):
        nodes = graph.nodes
        if len(nodes) < 4:  # 2 members x 2 nodes minimum
            return graph, 0
        count, out_nodes = _consumers(graph)
        # cheap O(n) pre-filter: a bottom-up structural hash with leaves
        # abstracted — two batchable subtrees MUST collide here, so any
        # root with a unique hash skips the expensive signature build
        # (a linear chain's prefixes all differ in size, so the common
        # eager shape pays one hash per node and nothing else)
        exact = _exact_fns()
        sh = []
        for node in nodes:
            if node.fn not in exact or node.kwargs:
                sh.append(None)  # poisons every subtree containing it
                continue
            marks = []
            for kind, ix in node.args:
                if kind == NODE:
                    marks.append(sh[ix])
                elif kind == CONST:
                    marks.append(("C", ix))
                else:
                    marks.append("L")
            if None in marks:
                sh.append(None)
                continue
            try:
                sh.append(hash((node.node_key, tuple(marks))))
            except TypeError:
                sh.append(None)
        freq = {}
        for h in sh:
            if h is not None:
                freq[h] = freq.get(h, 0) + 1
        groups = {}   # sig -> [(root, members, slot_refs)]
        for root in range(len(nodes)):
            if sh[root] is None or freq[sh[root]] < 2:
                continue
            sub = _subtree(graph, root, count, out_nodes)
            if sub is None or len(sub) < 2:
                continue
            sig = _signature(graph, sub, root)
            if sig is None:
                continue
            key, slot_refs = sig
            try:
                hash(key)
            except TypeError:
                continue
            groups.setdefault(key, []).append((root, sub, slot_refs))
        # deterministic: groups ordered by their first root index;
        # members claimed greedily, disjoint from anything already taken
        plans = []
        taken = set()
        for key, cands in sorted(groups.items(),
                                 key=lambda kv: kv[1][0][0]):
            chosen = []
            for root, sub, slot_refs in cands:
                if taken.isdisjoint(sub):
                    chosen.append((root, sub, slot_refs))
                    taken.update(sub)
            if len(chosen) >= 2:
                plans.append((key, chosen))
            else:
                for _, sub, _ in chosen:
                    taken.difference_update(sub)
        if not plans:
            return graph, 0

        merged = 0
        # rebuild with insertion: batched + slice nodes land at the
        # FIRST member root's position; all member subtree nodes drop
        drop, emit_at = set(), {}
        for key, chosen in plans:
            for _, sub, _ in chosen:
                drop.update(sub)
            emit_at[min(r for r, _, _ in chosen)] = (key, chosen)
            merged += len(chosen) - 1
        index_map, alias, new_nodes = {}, {}, []

        def remap(ref):
            # old-index NODE ref -> new index (member roots to their
            # slice node); args always point at earlier nodes, so both
            # maps are complete by the time a consumer is emitted
            kind, ix = ref
            if kind != NODE:
                return ref
            if ix in alias:
                return (NODE, alias[ix])
            return (NODE, index_map[ix])

        for i, node in enumerate(nodes):
            plan = emit_at.get(i)
            if plan is not None:
                key, chosen = plan
                (pattern, slot_meta, const_refs) = key
                n = len(chosen)
                chain_shapes = [s for s, _ in slot_meta if s != ()]
                rank = len(chain_shapes[0]) if chain_shapes else 0
                scalar_slots = tuple(s for s, (shp, _)
                                     in enumerate(slot_meta) if shp == ())
                ops = []
                members0 = chosen[0][1]
                for m, j in enumerate(members0):
                    node_j = nodes[j]
                    ops.append((node_j.fn, pattern[m][1], node_j.kwargs))
                # slot-major args: slot s contributes each member's
                # leaf, then the shared consts ride once at the end
                args = []
                for s in range(len(slot_meta)):
                    for _, _, slot_refs in chosen:
                        args.append((LEAF, slot_refs[s]))
                args.extend((CONST, ix) for ix in const_refs)
                bnode = GraphNode(
                    BatchedFn(ops, n, len(slot_meta), scalar_slots,
                              rank),
                    (_BATCH_TAG, pattern, slot_meta, const_refs, n),
                    {}, tuple(args))
                b_ix = len(new_nodes)
                new_nodes.append(bnode)
                for k, (root, _, _) in enumerate(chosen):
                    snode = GraphNode(BatchSlice(k), (_SLICE_TAG, k), {},
                                      ((NODE, b_ix),))
                    alias[root] = len(new_nodes)
                    new_nodes.append(snode)
            if i in drop:
                continue
            index_map[i] = len(new_nodes)
            new_nodes.append(node.with_args(remap(a) for a in node.args))
        return graph.replace(
            nodes=new_nodes,
            outputs=tuple(remap(o) for o in graph.outputs)), merged
