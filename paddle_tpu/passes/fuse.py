"""Elementwise fusion: group contiguous single-consumer runs into one
fused super-node.

``_eval_chain`` interprets the deferred chain one node at a time — under
jit TRACING that is one python frame, one ``_fn_key``-sized cache-key
entry and one argument-resolution list per op. A linear run where every
intermediate feeds exactly its successor (the dominant eager shape:
``y = y * a + b`` in a loop) carries no information the boundary nodes
don't: this pass collapses each maximal such run into a single
``GraphNode`` whose fn is a :class:`FusedFn` replaying the member ops in
capture order over the run's external inputs.

Exactness is trivial by construction: the fused fn applies THE SAME ops,
in THE SAME order, to THE SAME operands — no reassociation, operand
order pinned — so under jit tracing it emits the identical primitive
sequence the unfused graph would (the XLA program is equal, hence the
outputs bitwise equal). What changes is the host side: the graph the
flush hashes, caches and interprets shrinks from O(chain ops) nodes to
O(fused regions), and the ``passes/v2`` jit-cache namespace
canonicalizes across fused forms (a chain and its refused equivalent
share one key).

Fusion conditions for absorbing node ``j`` into the region ending at its
successor ``i``:

- ``i`` consumes ``(NODE, j)`` (the run is connected);
- ``j`` has exactly ONE consumer (nothing outside the region reads it);
- ``j`` is not a flush output (an owner Tensor needs its value stamped).

Regions of size 1 are left untouched (nothing to win).
"""

from __future__ import annotations

from .ir import NODE, GraphNode

# structural tag for fused node_keys — versioned so a future change to
# FusedFn evaluation invalidates old passes/v2 cache keys by key shape
_FUSE_TAG = "__fuse1__"
EXT = "ext"
INT = "int"


class FusedFn:
    """Callable replaying ``ops`` (``(fn, spec, kwargs)`` tuples, spec
    referencing (EXT, k) external inputs or (INT, m) member results)
    over positional external inputs; returns the last member's value.
    Under jit tracing this inlines to exactly the member primitives."""

    __slots__ = ("ops", "__name__")

    def __init__(self, ops):
        self.ops = tuple(ops)
        self.__name__ = f"fused[{len(self.ops)}]"

    def __call__(self, *ext):
        vals = []
        for fn, spec, kw in self.ops:
            argv = [ext[ix] if kind == EXT else vals[ix]
                    for kind, ix in spec]
            vals.append(fn(*argv, **kw))
        return vals[-1]

    def __repr__(self):
        return f"FusedFn(n={len(self.ops)})"


def _consumer_stats(graph):
    """(consumer_count, sole_consumer) per node index; outputs count as
    an extra (external) consumer so they can never be absorbed."""
    n = len(graph.nodes)
    count = [0] * n
    sole = [None] * n
    for i, node in enumerate(graph.nodes):
        for kind, ix in node.args:
            if kind == NODE:
                count[ix] += 1
                sole[ix] = i
    for kind, ix in graph.outputs:
        if kind == NODE:
            count[ix] += 2  # poison: an output is never interior
    return count, sole


class FuseElementwise:
    """metric: passes.fuse.grouped (nodes absorbed into super-nodes)"""

    name = "fuse"
    metric_name = "passes.fuse.grouped"

    def run(self, graph):
        nodes = graph.nodes
        if len(nodes) < 2:
            return graph, 0
        count, sole = _consumer_stats(graph)
        # maximal single-consumer runs, greedy over topo order
        regions, cur = [], [0]
        for i in range(1, len(nodes)):
            prev = cur[-1]
            if sole[prev] == i and count[prev] == 1 \
                    and (NODE, prev) in nodes[i].args:
                cur.append(i)
            else:
                regions.append(cur)
                cur = [i]
        regions.append(cur)
        if all(len(r) == 1 for r in regions):
            return graph, 0
        absorbed = 0
        new_nodes = list(nodes)
        for region in regions:
            if len(region) == 1:
                continue
            local = {j: m for m, j in enumerate(region)}
            ext_refs, ext_ix = [], {}
            ops, keyspec = [], []
            for j in region:
                node = nodes[j]
                spec = []
                for ref in node.args:
                    kind, ix = ref
                    if kind == NODE and ix in local:
                        spec.append((INT, local[ix]))
                        continue
                    k = ext_ix.get(ref)
                    if k is None:
                        k = ext_ix[ref] = len(ext_refs)
                        ext_refs.append(ref)
                    spec.append((EXT, k))
                spec = tuple(spec)
                ops.append((node.fn, spec, node.kwargs))
                keyspec.append((node.node_key, spec))
            fused = GraphNode(FusedFn(ops), (_FUSE_TAG, tuple(keyspec)),
                              {}, tuple(ext_refs))
            # the super-node takes the LAST member's slot: every external
            # ref precedes the region (topo), every consumer follows it;
            # interior members become husks DCE sweeps
            new_nodes[region[-1]] = fused
            absorbed += len(region) - 1
        if not absorbed:
            return graph, 0
        return graph.replace(nodes=new_nodes), absorbed
