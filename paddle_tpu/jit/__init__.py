"""`paddle.jit`: the compiled path.

Parity target: the reference's whole compiled stack — `paddle.jit.to_static`
(SOT bytecode translator + AST transformer, python/paddle/jit/), the PIR
program + PirInterpreter executor (paddle/fluid/framework/new_executor/),
and the CINN fusion compiler (paddle/cinn/). TPU-first collapse: the eager
tape already runs under `jax.jit` tracing (Tensor payloads become tracers),
so "dygraph→static" is one retrace — XLA is the IR, the scheduler and the
fusion compiler. `TracedLayer`/`to_static` wrap inference; `TrainStep`
compiles forward+backward+optimizer into ONE donated XLA executable (the
analogue of a whole PirInterpreter Plan, minus the per-op dispatch loop).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..core import random as random_mod
from ..core.autograd import backward as tape_backward
from ..core.tensor import Parameter, Tensor

__all__ = ["to_static", "TrainStep", "save", "load", "no_retrace",
           "TranslatedLayer", "enable_to_static", "ignore_module",
           "set_code_level", "set_verbosity"]


def _tree_wrap(x):
    return Tensor(x) if isinstance(x, (jax.Array, jax.core.Tracer)) else x


# tracer-leak errors that mean "python branched on a tensor value"
_GRAPH_BREAK_ERRORS = (
    jax.errors.TracerBoolConversionError,
    jax.errors.TracerArrayConversionError,
    jax.errors.TracerIntegerConversionError,
    jax.errors.ConcretizationTypeError,
)


def _tree_unwrap(x):
    return x._data if isinstance(x, Tensor) else x


class _Segment:
    """A differentiable compiled segment: one child layer's forward,
    jitted, dispatched through ``apply`` so the eager tape flows through
    it (params get grads, training keeps working around a graph break).

    This is the subgraph half of the reference SOT's graph-break story
    (`python/paddle/jit/sot/opcode_translator/executor/
    opcode_executor.py:1594` keeps compiled subgraphs around a break):
    when a frame breaks, the frame itself runs eager python but every
    direct child layer call stays one compiled XLA program. A segment
    that itself breaks demotes recursively — its frame goes eager and
    ITS children become segments."""

    def __init__(self, child, name):
        self._child = child
        self._name = name
        self._fwd = type(child).forward  # unbound original
        self._broken = False
        self.traces = 0   # trace counter (tests / introspection)
        self.calls = 0
        self._jit_cache = {}

    def __call__(self, *args, **kwargs):
        self.calls += 1
        if self._broken or not _TO_STATIC_ENABLED:
            return self._fwd(self._child, *args, **kwargs)
        try:
            return self._compiled_call(args, kwargs)
        except _GRAPH_BREAK_ERRORS as e:
            import warnings

            warnings.warn(
                f"to_static: graph break in segment {self._name!r} "
                f"({type(e).__name__}); its frame runs eager, child "
                f"layers stay compiled.", RuntimeWarning, stacklevel=2)
            _segmentize(self._child)
            self._broken = True
            return self._fwd(self._child, *args, **kwargs)
        except TypeError:
            # unhashable static arg etc: run this frame eager, no cache
            return self._fwd(self._child, *args, **kwargs)

    def _compiled_call(self, args, kwargs):
        from ..core.dispatch import apply

        child = self._child
        leaves, treedef = jax.tree.flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        t_pos = [i for i, l in enumerate(leaves)
                 if isinstance(l, (Tensor, jax.Array))]
        statics = tuple((i, l) for i, l in enumerate(leaves)
                        if i not in t_pos)
        param_items = list(child.named_parameters())
        buffer_items = list(child.named_buffers())
        ckey = (treedef, tuple(t_pos), statics, child.training,
                len(param_items), len(buffer_items))
        hash(ckey)  # unhashable statics -> TypeError -> eager frame
        entry = self._jit_cache.get(ckey)
        if entry is None:
            n_in = len(t_pos)
            n_p = len(param_items)
            out_meta = {}

            def seg_pure(key, *arrs):
                self.traces += 1
                in_arrs = arrs[:n_in]
                p_arrs = arrs[n_in:n_in + n_p]
                b_arrs = arrs[n_in + n_p:]
                restore = []
                try:
                    for (_, p), arr in zip(param_items, p_arrs):
                        restore.append((p, p._data))
                        p._data = arr
                    for (_, b), arr in zip(buffer_items, b_arrs):
                        restore.append((b, b._data))
                        b._data = arr
                    full = [None] * len(leaves)
                    for i, l in statics:
                        full[i] = l
                    for pos, a in zip(t_pos, in_arrs):
                        full[pos] = Tensor(a)
                    a2, k2 = jax.tree.unflatten(treedef, full)
                    with random_mod.scoped_key(key):
                        out = self._fwd(child, *a2, **k2)
                    out_leaves, out_td = jax.tree.flatten(
                        out, is_leaf=lambda x: isinstance(x, Tensor))
                    o_pos = [i for i, l in enumerate(out_leaves)
                             if isinstance(l, Tensor)]
                    out_meta["treedef"] = out_td
                    out_meta["t_pos"] = o_pos
                    out_meta["statics"] = [
                        (i, l) for i, l in enumerate(out_leaves)
                        if i not in o_pos]
                    arrs_out = [out_leaves[i]._data for i in o_pos]
                    new_bufs = [b._data for _, b in buffer_items]
                    return tuple(arrs_out) + tuple(new_bufs)
                finally:
                    for obj, arr in restore:
                        obj._data = arr

            entry = (jax.jit(seg_pure), out_meta)
            self._jit_cache[ckey] = entry
        jit_seg, out_meta = entry

        in_tensors = [leaves[i] if isinstance(leaves[i], Tensor)
                      else Tensor(leaves[i]) for i in t_pos]
        buf_tensors = [b for _, b in buffer_items]
        param_tensors = [p for _, p in param_items]
        key = random_mod.next_key()
        outs = apply(jit_seg, key, *in_tensors, *param_tensors,
                     *buf_tensors, name=f"segment:{self._name}")
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        n_out = len(out_meta["t_pos"])
        out_ts, new_bufs = outs[:n_out], outs[n_out:]
        for (_, b), t in zip(buffer_items, new_bufs):
            b._rebind(t._data)
        full = [None] * (len(out_meta["t_pos"]) +
                         len(out_meta["statics"]))
        for i, l in out_meta["statics"]:
            full[i] = l
        for pos, t in zip(out_meta["t_pos"], out_ts):
            full[pos] = t
        return jax.tree.unflatten(out_meta["treedef"], full)


def _segmentize(layer):
    """Wrap every direct child layer's forward in a compiled _Segment
    (idempotent). Returns the segments."""
    segs = []
    for name, child in layer.named_children():
        cur = child.__dict__.get("forward")
        if isinstance(cur, _Segment):
            segs.append(cur)
            continue
        seg = _Segment(child, name)
        child.forward = seg
        segs.append(seg)
    return segs


class _StaticFunction:
    """A jitted wrapper around a python function of Tensors (and/or a Layer
    forward). Retraces per input signature, like the reference's SOT guard
    cache (python/paddle/jit/sot/ guards)."""

    def __init__(self, fn, static_argnums=(), donate_argnums=()):
        self._fn = fn
        self._layer = None
        self._graph_broken = False
        self._segments = []
        if hasattr(fn, "forward") and hasattr(fn, "parameters"):
            self._layer = fn
            self._fn = type(fn).forward

        def pure(params, buffers, key, tree_args, tree_kwargs):
            layer = self._layer
            restore = []
            try:
                if layer is not None:
                    for (_, p), arr in zip(self._param_items, params):
                        restore.append((p, p._data))
                        p._data = arr
                    for (_, b), arr in zip(self._buffer_items, buffers):
                        restore.append((b, b._data))
                        b._data = arr
                args = jax.tree.map(_tree_wrap, tree_args)
                kwargs = jax.tree.map(_tree_wrap, tree_kwargs)
                with random_mod.scoped_key(key):
                    if layer is not None:
                        out = self._fn(layer, *args, **kwargs)
                    else:
                        out = self._fn(*args, **kwargs)
                out_arrays = jax.tree.map(
                    _tree_unwrap, out,
                    is_leaf=lambda x: isinstance(x, Tensor))
                new_buffers = [b._data for _, b in self._buffer_items]
                return out_arrays, new_buffers
            finally:
                for obj, arr in restore:
                    obj._data = arr

        self._jitted = jax.jit(pure, static_argnums=())

    @property
    def _param_items(self):
        return list(self._layer.named_parameters()) if self._layer else []

    @property
    def _buffer_items(self):
        return list(self._layer.named_buffers()) if self._layer else []

    def _eager_call(self, *args, **kwargs):
        if self._layer is not None:
            return self._fn(self._layer, *args, **kwargs)
        return self._fn(*args, **kwargs)

    def __call__(self, *args, **kwargs):
        if not _TO_STATIC_ENABLED or self._graph_broken:
            # reference enable_to_static(False) / SOT graph-break
            # fallback: run the original eager code (no tracers, python
            # control flow works)
            return self._eager_call(*args, **kwargs)
        params = [p._data for _, p in self._param_items]
        buffers = [b._data for _, b in self._buffer_items]
        tree_args = jax.tree.map(_tree_unwrap, args,
                                 is_leaf=lambda x: isinstance(x, Tensor))
        tree_kwargs = jax.tree.map(_tree_unwrap, kwargs,
                                   is_leaf=lambda x: isinstance(x, Tensor))
        key = random_mod.next_key()
        try:
            out, new_buffers = self._jitted(params, buffers, key,
                                            tree_args, tree_kwargs)
        except _GRAPH_BREAK_ERRORS as e:
            # Graph break: the traced function branched on a tensor VALUE
            # (data-dependent python control flow). The reference's SOT
            # translator falls back per-op on breaks (sot/opcode_translator/
            # executor/opcode_executor.py:1594); the retrace design falls
            # back to eager for THIS function, once, with a warning —
            # the user's program keeps running instead of dying.
            import warnings

            name = getattr(self._fn, "__qualname__",
                           getattr(self._fn, "__name__", "<fn>"))
            if self._layer is not None:
                # subgraph split (reference SOT keeps compiled subgraphs
                # around a break): this frame runs eager python; each
                # direct child layer call stays one compiled XLA segment
                # dispatched through the tape (grads flow; training
                # works). Child segments that break demote recursively.
                self._segments = _segmentize(self._layer)
                warnings.warn(
                    f"to_static: graph break in {name!r} "
                    f"(data-dependent control flow: {type(e).__name__}); "
                    f"splitting: this frame runs eager, its "
                    f"{len(self._segments)} child layers stay compiled. "
                    f"Rewrite with paddle.where / lax.cond-style ops to "
                    f"compile the whole function.", RuntimeWarning,
                    stacklevel=2)
            else:
                warnings.warn(
                    f"to_static: graph break in {name!r} "
                    f"(data-dependent control flow: {type(e).__name__}); "
                    f"falling back to eager execution for this function. "
                    f"Rewrite with paddle.where / lax.cond-style ops to "
                    f"keep it compiled.", RuntimeWarning, stacklevel=2)
            self._graph_broken = True
            return self._eager_call(*args, **kwargs)
        for (_, b), arr in zip(self._buffer_items, new_buffers):
            b._rebind(arr)
        return jax.tree.map(_tree_wrap, out)

    def graph_break_report(self):
        """Introspection: split state + per-segment trace counters."""
        def seg_row(s):
            return {"name": s._name, "broken": s._broken,
                    "traces": s.traces, "calls": s.calls,
                    "children": [seg_row(c) for c in (
                        _collect_segments(s._child) if s._broken else [])]}
        return {"broken": self._graph_broken,
                "segments": [seg_row(s) for s in self._segments]}


def _collect_segments(layer):
    return [c.__dict__["forward"] for _, c in layer.named_children()
            if isinstance(c.__dict__.get("forward"), _Segment)]


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """Compile a function or Layer for static execution (reference
    python/paddle/jit/api.py:197 `to_static`). Decorator or call form."""
    def wrap(fn):
        sf = _StaticFunction(fn)
        if hasattr(fn, "forward") and hasattr(fn, "parameters"):
            # Layer: return the layer with a compiled __call__ shim
            layer = fn
            layer._static_function = sf
            layer._input_spec = input_spec  # jit.save uses it
            orig_class_call = type(layer).__call__

            def compiled_call(*args, **kw):
                return sf(*args, **kw)
            layer.forward_static = compiled_call
            layer.__dict__["__call__"] = compiled_call
            # keep Layer instance; calling layer(...) goes through class
            # __call__ → forward, so also swap forward:
            layer.forward = compiled_call
            return layer
        functools.wraps(fn)(sf)
        sf._input_spec = input_spec
        return sf
    if function is None:
        return wrap
    return wrap(function)


class TrainStep:
    """Whole-train-step compiler: forward + tape backward + grad clip +
    optimizer update + buffer updates in ONE donated XLA program.

    ``step_fn(model, *batch) -> loss`` (or ``-> (loss, aux...)``).

    This is the TPU answer to the reference's big-ticket runtime work
    (PirInterpreter instruction scheduling, fused_adam multi-tensor kernels,
    interpreter GC): parameters and optimizer slots are donated, so updates
    are in-place in HBM; XLA schedules and fuses everything.
    """

    def __init__(self, model, optimizer, step_fn=None, donate=True):
        self._model = model
        self._opt = optimizer
        self._step_fn = step_fn or (lambda m, *batch: m(*batch))
        self._params = list(model.named_parameters())
        self._buffers = list(model.named_buffers())
        self._pg = optimizer._param_groups_flat()
        by_id = {id(p): g for p, g in self._pg}
        self._groups_for_params = [by_id.get(id(p)) for _, p in self._params]
        self._donate = donate
        self._jitted = None

    def _build(self):
        opt = self._opt
        param_objs = [p for _, p in self._params]
        buffer_objs = [b for _, b in self._buffers]
        groups = self._groups_for_params

        def pure(param_arrays, slot_states, buffer_arrays, t, lr, key,
                 batch):
            param_arrays, slot_states = self._prepare_state(
                param_arrays, slot_states)
            restore = []
            try:
                for p, arr in zip(param_objs, param_arrays):
                    restore.append((p, p._data, p._node, p.grad,
                                    p.stop_gradient))
                    p._data = arr
                    p._node = None
                    p.grad = None
                for b, arr in zip(buffer_objs, buffer_arrays):
                    restore.append((b, b._data, b._node, b.grad,
                                    b.stop_gradient))
                    b._data = arr

                batch_t = jax.tree.map(_tree_wrap, batch)
                with random_mod.scoped_key(key):
                    out = self._step_fn(self._model, *batch_t)
                loss = out[0] if isinstance(out, (tuple, list)) else out
                aux = out[1:] if isinstance(out, (tuple, list)) else ()

                grad_store = {}
                tape_backward([loss], [None], retain_graph=False,
                              _into=grad_store)

                grads = [grad_store.get(id(p)) for p in param_objs]
                # grad clip (pure form)
                if opt._grad_clip is not None:
                    have = [i for i, g in enumerate(grads) if g is not None]
                    clipped = opt._grad_clip._clip_arrays(
                        [grads[i] for i in have],
                        [getattr(param_objs[i], "need_clip", True)
                         for i in have])
                    for i, g in zip(have, clipped):
                        grads[i] = g

                from ..optimizer.optimizer import _lr_mult

                opt._t = t
                new_params = []
                new_slots = []
                for p, g, st, group in zip(param_objs, grads, slot_states,
                                           groups):
                    if g is None or group is None:
                        new_params.append(p._data)
                        new_slots.append(st)
                        continue
                    lr_p = lr * group["lr_mult"] * _lr_mult(p)
                    p32 = st["master"] if st.get("master") is not None \
                        else p._data.astype(jnp.float32)
                    g32 = g.astype(jnp.float32)
                    np_, nst = opt._apply_param(p32, g32, st, lr_p, group,
                                                param=p)
                    if st.get("master") is not None:
                        nst["master"] = np_
                    new_params.append(np_.astype(p._data.dtype))
                    new_slots.append(nst)
                new_buffers = [b._data for b in buffer_objs]
                aux_arrays = jax.tree.map(
                    _tree_unwrap, tuple(aux),
                    is_leaf=lambda x: isinstance(x, Tensor))
                return (loss._data, aux_arrays, new_params, new_slots,
                        new_buffers)
            finally:
                for obj, arr, node, grad, sg in restore:
                    obj._data = arr
                    obj._node = node
                    obj.grad = grad
                    obj.stop_gradient = sg

        donate = (0, 1) if self._donate else ()
        self._pure = pure
        self._jitted = jax.jit(pure, donate_argnums=donate,
                               out_shardings=self._out_shardings())

    def _out_shardings(self):
        """None everywhere (XLA's choice); ShardedTrainStep pins params."""
        return None

    def _prepare_state(self, param_arrays, slot_states):
        """Hook run inside the traced step before any compute; sharded
        subclasses use it to stream offloaded (host-memory) state onto the
        device."""
        return param_arrays, slot_states

    def __call__(self, *batch):
        if self._jitted is None:
            self._build()
        opt = self._opt
        param_objs = [p for _, p in self._params]
        # materialize slot dicts in param order
        slot_states = [opt._slots_for(p) for p in param_objs]
        param_arrays = [p._data for p in param_objs]
        buffer_arrays = [b._data for _, b in self._buffers]
        opt._global_step += 1
        if opt._lr_scheduler is not None:
            lr = opt._lr_scheduler.last_lr
        else:
            lr = opt._lr
        t = jnp.asarray(opt._global_step, jnp.float32)
        key = random_mod.next_key()
        batch_arrays = jax.tree.map(_tree_unwrap, batch,
                                    is_leaf=lambda x: isinstance(x, Tensor))
        from ..distributed.watchdog import watch_step
        with watch_step("TrainStep") as w:
            loss, aux, new_params, new_slots, new_buffers = self._jitted(
                param_arrays, slot_states, buffer_arrays, t,
                jnp.asarray(lr, jnp.float32), key, batch_arrays)
            if w is not None:  # watchdog on: surface hangs at this step
                jax.block_until_ready(loss)
        for p, arr, st in zip(param_objs, new_params, new_slots):
            p._rebind(arr)
            opt._state[id(p)] = st
        for (_, b), arr in zip(self._buffers, new_buffers):
            b._rebind(arr)
        loss_t = Tensor(loss)
        if aux:
            return (loss_t,) + tuple(jax.tree.map(_tree_wrap, aux))
        return loss_t


def no_retrace(fn):
    """Marker passthrough (API parity with paddle.jit.not_to_static)."""
    return fn


not_to_static = no_retrace


def _specs_to_structs(input_spec):
    """static.InputSpec / Tensor / shape-list specs ->
    jax.ShapeDtypeStructs; -1/None dims become export symbolic dims
    (dynamic batch), all created in ONE scope as jax.export requires."""
    from jax import export as jexport

    from ..core import dtype as dtype_mod
    shapes, dtypes, n_dyn = [], [], 0
    for spec in input_spec:
        if isinstance(spec, Tensor):
            shapes.append(list(spec._data.shape))
            dtypes.append(spec._data.dtype)
            continue
        if hasattr(spec, "shape"):
            shapes.append(list(spec.shape))
            dtypes.append(dtype_mod.convert_dtype(
                getattr(spec, "dtype", "float32")))
        else:
            shapes.append(list(spec))
            dtypes.append(jnp.float32)
        n_dyn += sum(1 for d in shapes[-1]
                     if d is None or (isinstance(d, int) and d < 0))
    syms = iter(jexport.symbolic_shape(
        ", ".join(f"d{i}" for i in range(n_dyn))) if n_dyn else ())
    structs = []
    for shape, dtype in zip(shapes, dtypes):
        dims = tuple(next(syms) if d is None or
                     (isinstance(d, int) and d < 0) else int(d)
                     for d in shape)
        structs.append(jax.ShapeDtypeStruct(dims, dtype))
    return structs


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save (reference python/paddle/jit/api.py jit.save →
    TranslatedLayer): persists the state_dict AND, when ``input_spec`` is
    given (or recorded by to_static), the traced forward as serialized
    StableHLO (jax.export) — the TPU-native serialized program, loadable
    without the model class."""
    import pickle

    from jax import export as jexport

    from .. import framework
    state = layer.state_dict() if hasattr(layer, "state_dict") else {}
    framework.io.save(state, path + ".pdparams")
    if input_spec is None:
        input_spec = getattr(layer, "_input_spec", None)
    if input_spec is None:
        return
    items = list(state.items())
    names = [n for n, _ in items]
    arrs = [t._data if isinstance(t, Tensor) else jnp.asarray(t)
            for _, t in items]

    def pure(params, *inputs):
        bound = dict(zip(names, params))
        restore = []
        for kind in ("named_parameters", "named_buffers"):
            for n, t in getattr(layer, kind, lambda: ())():
                if n in bound:
                    restore.append((t, t._data))
                    t._data = bound[n]
        global _TO_STATIC_ENABLED
        prev_ts = _TO_STATIC_ENABLED
        was_training = getattr(layer, "training", False)
        try:
            # trace the original eager forward — routing through the
            # to_static jit shim here would nest jit inside the export
            # trace and leak its RNG-key side channel
            _TO_STATIC_ENABLED = False
            if hasattr(layer, "eval"):
                layer.eval()
            out = layer(*[Tensor(x) for x in inputs])
            return out._data if isinstance(out, Tensor) else \
                jax.tree.map(lambda t: t._data if isinstance(t, Tensor)
                             else t, out)
        finally:
            _TO_STATIC_ENABLED = prev_ts
            if was_training and hasattr(layer, "train"):
                layer.train()
            for t, d in restore:
                t._data = d

    structs = _specs_to_structs(input_spec)
    exported = jexport.export(jax.jit(pure))(tuple(arrs), *structs)
    with open(path + ".pdmodel", "wb") as f:
        pickle.dump({"stablehlo": exported.serialize(),
                     "param_names": names}, f)


class TranslatedLayer:
    """A layer rebuilt from a serialized program (reference
    python/paddle/jit/translated_layer.py): forward = the deserialized
    StableHLO executable, no Python model class needed."""

    def __init__(self, exported, names, state):
        self._exported = exported
        self._names = names
        self._params = tuple(
            state[n]._data if isinstance(state[n], Tensor)
            else jnp.asarray(state[n]) for n in names)
        self.training = False

    def __call__(self, *inputs):
        return self.forward(*inputs)

    def forward(self, *inputs):
        args = [x._data if isinstance(x, Tensor) else jnp.asarray(x)
                for x in inputs]
        out = self._exported.call(self._params, *args)
        return jax.tree.map(_tree_wrap, out)

    def eval(self):
        self.training = False
        return self

    def train(self):
        raise RuntimeError(
            "TranslatedLayer is an inference program (the reference's "
            "TranslatedLayer supports fine-tune via program grads; here "
            "re-instantiate the Python model and load the .pdparams)")

    def state_dict(self):
        return {n: Tensor(p) for n, p in zip(self._names, self._params)}


def load(path, **configs):
    """paddle.jit.load (reference jit/api.py load → TranslatedLayer):
    deserializes the StableHLO program saved by jit.save."""
    import os
    import pickle

    from jax import export as jexport

    from .. import framework
    if not os.path.exists(path + ".pdmodel"):
        raise FileNotFoundError(
            f"{path}.pdmodel not found — jit.save with input_spec writes "
            "it; without a serialized program use paddle_tpu.load + "
            "Layer.set_state_dict")
    with open(path + ".pdmodel", "rb") as f:
        blob = pickle.load(f)
    exported = jexport.deserialize(blob["stablehlo"])
    state = framework.io.load(path + ".pdparams")
    return TranslatedLayer(exported, blob["param_names"], state)


_TO_STATIC_ENABLED = True


def enable_to_static(flag):
    """Globally toggle to_static compilation (reference
    jit/api.py enable_to_static); disabled => traced wrappers run eagerly.
    """
    global _TO_STATIC_ENABLED
    _TO_STATIC_ENABLED = bool(flag)


_IGNORED_MODULES = []


def ignore_module(modules):
    """Register modules the bytecode translator must skip (reference
    jit/sot: paddle.jit.ignore_module). Retrace-based to_static has no
    bytecode pass, so this only records them for API compat."""
    _IGNORED_MODULES.extend(modules if isinstance(modules, (list, tuple))
                            else [modules])


_CODE_LEVEL = 0


def set_code_level(level=100, also_to_stdout=False):
    """Log translated code at ``level`` (reference jit/logging_utils).
    Retrace-based to_static has no generated code; the setting is
    recorded and the jit logger verbosity follows it."""
    import logging
    global _CODE_LEVEL
    _CODE_LEVEL = level
    logger = logging.getLogger("paddle_tpu.jit")
    logger.setLevel(logging.DEBUG if level > 0 else logging.WARNING)
    if also_to_stdout and not logger.handlers:
        logger.addHandler(logging.StreamHandler())


def set_verbosity(level=0, also_to_stdout=False):
    """Set to_static logging verbosity (reference jit/logging_utils)."""
    import logging
    logging.getLogger("paddle_tpu.jit").setLevel(
        logging.DEBUG if level > 0 else logging.WARNING)
