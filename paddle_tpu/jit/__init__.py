"""`paddle.jit`: the compiled path.

Parity target: the reference's whole compiled stack — `paddle.jit.to_static`
(SOT bytecode translator + AST transformer, python/paddle/jit/), the PIR
program + PirInterpreter executor (paddle/fluid/framework/new_executor/),
and the CINN fusion compiler (paddle/cinn/). TPU-first collapse: the eager
tape already runs under `jax.jit` tracing (Tensor payloads become tracers),
so "dygraph→static" is one retrace — XLA is the IR, the scheduler and the
fusion compiler. `TracedLayer`/`to_static` wrap inference; `TrainStep`
compiles forward+backward+optimizer into ONE donated XLA executable (the
analogue of a whole PirInterpreter Plan, minus the per-op dispatch loop).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..core import random as random_mod
from ..core.autograd import backward as tape_backward
from ..core.tensor import Parameter, Tensor

__all__ = ["to_static", "TrainStep", "save", "load", "no_retrace"]


def _tree_wrap(x):
    return Tensor(x) if isinstance(x, (jax.Array, jax.core.Tracer)) else x


def _tree_unwrap(x):
    return x._data if isinstance(x, Tensor) else x


class _StaticFunction:
    """A jitted wrapper around a python function of Tensors (and/or a Layer
    forward). Retraces per input signature, like the reference's SOT guard
    cache (python/paddle/jit/sot/ guards)."""

    def __init__(self, fn, static_argnums=(), donate_argnums=()):
        self._fn = fn
        self._layer = None
        if hasattr(fn, "forward") and hasattr(fn, "parameters"):
            self._layer = fn
            self._fn = type(fn).forward

        def pure(params, buffers, key, tree_args, tree_kwargs):
            layer = self._layer
            restore = []
            try:
                if layer is not None:
                    for (_, p), arr in zip(self._param_items, params):
                        restore.append((p, p._data))
                        p._data = arr
                    for (_, b), arr in zip(self._buffer_items, buffers):
                        restore.append((b, b._data))
                        b._data = arr
                args = jax.tree.map(_tree_wrap, tree_args)
                kwargs = jax.tree.map(_tree_wrap, tree_kwargs)
                with random_mod.scoped_key(key):
                    if layer is not None:
                        out = self._fn(layer, *args, **kwargs)
                    else:
                        out = self._fn(*args, **kwargs)
                out_arrays = jax.tree.map(
                    _tree_unwrap, out,
                    is_leaf=lambda x: isinstance(x, Tensor))
                new_buffers = [b._data for _, b in self._buffer_items]
                return out_arrays, new_buffers
            finally:
                for obj, arr in restore:
                    obj._data = arr

        self._jitted = jax.jit(pure, static_argnums=())

    @property
    def _param_items(self):
        return list(self._layer.named_parameters()) if self._layer else []

    @property
    def _buffer_items(self):
        return list(self._layer.named_buffers()) if self._layer else []

    def __call__(self, *args, **kwargs):
        params = [p._data for _, p in self._param_items]
        buffers = [b._data for _, b in self._buffer_items]
        tree_args = jax.tree.map(_tree_unwrap, args,
                                 is_leaf=lambda x: isinstance(x, Tensor))
        tree_kwargs = jax.tree.map(_tree_unwrap, kwargs,
                                   is_leaf=lambda x: isinstance(x, Tensor))
        key = random_mod.next_key()
        out, new_buffers = self._jitted(params, buffers, key, tree_args,
                                        tree_kwargs)
        for (_, b), arr in zip(self._buffer_items, new_buffers):
            b._rebind(arr)
        return jax.tree.map(_tree_wrap, out)


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """Compile a function or Layer for static execution (reference
    python/paddle/jit/api.py:197 `to_static`). Decorator or call form."""
    def wrap(fn):
        sf = _StaticFunction(fn)
        if hasattr(fn, "forward") and hasattr(fn, "parameters"):
            # Layer: return the layer with a compiled __call__ shim
            layer = fn
            layer._static_function = sf
            orig_class_call = type(layer).__call__

            def compiled_call(*args, **kw):
                return sf(*args, **kw)
            layer.forward_static = compiled_call
            layer.__dict__["__call__"] = compiled_call
            # keep Layer instance; calling layer(...) goes through class
            # __call__ → forward, so also swap forward:
            layer.forward = compiled_call
            return layer
        functools.wraps(fn)(sf)
        return sf
    if function is None:
        return wrap
    return wrap(function)


class TrainStep:
    """Whole-train-step compiler: forward + tape backward + grad clip +
    optimizer update + buffer updates in ONE donated XLA program.

    ``step_fn(model, *batch) -> loss`` (or ``-> (loss, aux...)``).

    This is the TPU answer to the reference's big-ticket runtime work
    (PirInterpreter instruction scheduling, fused_adam multi-tensor kernels,
    interpreter GC): parameters and optimizer slots are donated, so updates
    are in-place in HBM; XLA schedules and fuses everything.
    """

    def __init__(self, model, optimizer, step_fn=None, donate=True):
        self._model = model
        self._opt = optimizer
        self._step_fn = step_fn or (lambda m, *batch: m(*batch))
        self._params = list(model.named_parameters())
        self._buffers = list(model.named_buffers())
        self._pg = optimizer._param_groups_flat()
        by_id = {id(p): g for p, g in self._pg}
        self._groups_for_params = [by_id.get(id(p)) for _, p in self._params]
        self._donate = donate
        self._jitted = None

    def _build(self):
        opt = self._opt
        param_objs = [p for _, p in self._params]
        buffer_objs = [b for _, b in self._buffers]
        groups = self._groups_for_params

        def pure(param_arrays, slot_states, buffer_arrays, t, lr, key,
                 batch):
            param_arrays, slot_states = self._prepare_state(
                param_arrays, slot_states)
            restore = []
            try:
                for p, arr in zip(param_objs, param_arrays):
                    restore.append((p, p._data, p._node, p.grad,
                                    p.stop_gradient))
                    p._data = arr
                    p._node = None
                    p.grad = None
                for b, arr in zip(buffer_objs, buffer_arrays):
                    restore.append((b, b._data, b._node, b.grad,
                                    b.stop_gradient))
                    b._data = arr

                batch_t = jax.tree.map(_tree_wrap, batch)
                with random_mod.scoped_key(key):
                    out = self._step_fn(self._model, *batch_t)
                loss = out[0] if isinstance(out, (tuple, list)) else out
                aux = out[1:] if isinstance(out, (tuple, list)) else ()

                grad_store = {}
                tape_backward([loss], [None], retain_graph=False,
                              _into=grad_store)

                grads = [grad_store.get(id(p)) for p in param_objs]
                # grad clip (pure form)
                if opt._grad_clip is not None:
                    have = [i for i, g in enumerate(grads) if g is not None]
                    clipped = opt._grad_clip._clip_arrays(
                        [grads[i] for i in have],
                        [param_objs[i].need_clip for i in have])
                    for i, g in zip(have, clipped):
                        grads[i] = g

                opt._t = t
                new_params = []
                new_slots = []
                for p, g, st, group in zip(param_objs, grads, slot_states,
                                           groups):
                    if g is None or group is None:
                        new_params.append(p._data)
                        new_slots.append(st)
                        continue
                    lr_p = (lr * group["lr_mult"] *
                            p.optimize_attr.get("learning_rate", 1.0))
                    p32 = st["master"] if st.get("master") is not None \
                        else p._data.astype(jnp.float32)
                    g32 = g.astype(jnp.float32)
                    np_, nst = opt._apply_param(p32, g32, st, lr_p, group,
                                                param=p)
                    if st.get("master") is not None:
                        nst["master"] = np_
                    new_params.append(np_.astype(p._data.dtype))
                    new_slots.append(nst)
                new_buffers = [b._data for b in buffer_objs]
                aux_arrays = jax.tree.map(
                    _tree_unwrap, tuple(aux),
                    is_leaf=lambda x: isinstance(x, Tensor))
                return (loss._data, aux_arrays, new_params, new_slots,
                        new_buffers)
            finally:
                for obj, arr, node, grad, sg in restore:
                    obj._data = arr
                    obj._node = node
                    obj.grad = grad
                    obj.stop_gradient = sg

        donate = (0, 1) if self._donate else ()
        self._pure = pure
        self._jitted = jax.jit(pure, donate_argnums=donate,
                               out_shardings=self._out_shardings())

    def _out_shardings(self):
        """None everywhere (XLA's choice); ShardedTrainStep pins params."""
        return None

    def _prepare_state(self, param_arrays, slot_states):
        """Hook run inside the traced step before any compute; sharded
        subclasses use it to stream offloaded (host-memory) state onto the
        device."""
        return param_arrays, slot_states

    def __call__(self, *batch):
        if self._jitted is None:
            self._build()
        opt = self._opt
        param_objs = [p for _, p in self._params]
        # materialize slot dicts in param order
        slot_states = [opt._slots_for(p) for p in param_objs]
        param_arrays = [p._data for p in param_objs]
        buffer_arrays = [b._data for _, b in self._buffers]
        opt._global_step += 1
        if opt._lr_scheduler is not None:
            lr = opt._lr_scheduler.last_lr
        else:
            lr = opt._lr
        t = jnp.asarray(opt._global_step, jnp.float32)
        key = random_mod.next_key()
        batch_arrays = jax.tree.map(_tree_unwrap, batch,
                                    is_leaf=lambda x: isinstance(x, Tensor))
        from ..distributed.watchdog import watch_step
        with watch_step("TrainStep") as w:
            loss, aux, new_params, new_slots, new_buffers = self._jitted(
                param_arrays, slot_states, buffer_arrays, t,
                jnp.asarray(lr, jnp.float32), key, batch_arrays)
            if w is not None:  # watchdog on: surface hangs at this step
                jax.block_until_ready(loss)
        for p, arr, st in zip(param_objs, new_params, new_slots):
            p._rebind(arr)
            opt._state[id(p)] = st
        for (_, b), arr in zip(self._buffers, new_buffers):
            b._rebind(arr)
        loss_t = Tensor(loss)
        if aux:
            return (loss_t,) + tuple(jax.tree.map(_tree_wrap, aux))
        return loss_t


def no_retrace(fn):
    """Marker passthrough (API parity with paddle.jit.not_to_static)."""
    return fn


not_to_static = no_retrace


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save parity: persists state_dict (+ a marker). Full
    serialized-program export (TranslatedLayer) is deferred to the
    inference module."""
    from .. import framework
    framework.io.save(layer.state_dict(), path + ".pdparams")


def load(path, **configs):
    raise NotImplementedError(
        "paddle_tpu.jit.load: use paddle_tpu.load + Layer.set_state_dict "
        "(TranslatedLayer import lands with the inference module)")
