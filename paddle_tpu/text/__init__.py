"""`paddle.text` (reference: python/paddle/text/ — datasets + viterbi
decode op)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["viterbi_decode", "ViterbiDecoder", "UCIHousing", "Imikolov",
           "Imdb", "Movielens", "Conll05st", "WMT14", "WMT16"]

from .datasets import (  # noqa: F401,E402
    Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16,
)


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """CRF Viterbi decode (reference paddle.text.viterbi_decode over the
    phi viterbi_decode kernel, cpu/viterbi_decode_kernel.cc:158).

    potentials: [b, t, n] emissions; transition_params: [n, n] — with
    ``include_bos_eos_tag`` the bos/eos tags are part of those n tags:
    row n-1 is the start (bos->tag) scores, row n-2 the stop scores
    added at each sequence's final step (kernel splits the matrix at
    :225-236). lengths: [b] int; positions past a sequence's length are
    masked out of the recurrence, path entries there are 0, and the
    returned paths are trimmed to max(lengths) like the kernel's
    batch_path. Returns (scores [b], paths [b, min(t, max(lengths))])."""
    import numpy as np

    from ..core.dispatch import unwrap

    if lengths is None:
        t_full = unwrap(potentials).shape[1]
        lens_host = None
    else:
        lens_host = np.asarray(unwrap(lengths)).astype("int64")
        t_full = unwrap(potentials).shape[1]

    def fn(emis, trans, *rest):
        b, t, n = emis.shape
        lens = rest[0].astype(jnp.int32) if rest else \
            jnp.full((b,), t, jnp.int32)

        if include_bos_eos_tag:
            start = trans[n - 1]  # bos -> tag row
            stop = trans[n - 2]   # stop scores row
        else:
            start = jnp.zeros((n,), emis.dtype)
            stop = jnp.zeros((n,), emis.dtype)

        alpha = emis[:, 0] + start[None]
        left = lens
        alpha = alpha + jnp.where(left == 1, 1.0, 0.0)[:, None] * \
            stop[None]
        left = left - 1

        def step(carry, e_t):
            alpha, left = carry
            scores = alpha[:, :, None] + trans[None]
            bp = jnp.argmax(scores, axis=1)          # [b, n]
            nxt = jnp.max(scores, axis=1) + e_t
            active = (left > 0)[:, None]
            alpha2 = jnp.where(active, nxt, alpha)
            alpha2 = alpha2 + jnp.where(left == 1, 1.0, 0.0)[:, None] \
                * stop[None]
            return (alpha2, left - 1), bp

        (alpha, _), backptrs = jax.lax.scan(
            step, (alpha, left), jnp.swapaxes(emis[:, 1:], 0, 1))
        last = jnp.argmax(alpha, axis=-1)
        score = jnp.max(alpha, axis=-1)

        batch = jnp.arange(b)

        def backtrace(carry, x):
            bp_t, i = x
            cur = carry
            final_here = (i == lens - 1)
            cur = jnp.where(final_here, last, cur)
            out = jnp.where(i <= lens - 1, cur, 0)
            prev = bp_t[batch, cur]
            nxt = jnp.where(i <= lens - 1, prev, cur)
            return nxt, out

        if t > 1:
            tag0, path_rest = jax.lax.scan(
                backtrace, last, (backptrs, jnp.arange(1, t)),
                reverse=True)
            p0 = jnp.where(0 <= lens - 1, jnp.where(lens == 1, last,
                                                    tag0), 0)
            path = jnp.concatenate([p0[None], path_rest], axis=0)
        else:
            path = jnp.where(lens >= 1, last, 0)[None]
        return score, jnp.swapaxes(path, 0, 1).astype(jnp.int64)

    args = (potentials, transition_params) if lengths is None else \
        (potentials, transition_params, lengths)
    score, path = apply(fn, *args, name="viterbi_decode")
    if lens_host is not None:
        t_trim = int(min(t_full, int(lens_host.max()) if lens_host.size
                         else 0))
        path = path[:, :t_trim]
    return score, path


class ViterbiDecoder:
    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
