"""`paddle.text` (reference: python/paddle/text/ — datasets + viterbi
decode op)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["viterbi_decode", "ViterbiDecoder", "UCIHousing", "Imikolov",
           "Imdb", "Movielens", "Conll05st", "WMT14", "WMT16"]

from .datasets import (  # noqa: F401,E402
    Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16,
)


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """CRF Viterbi decode (reference paddle.text.viterbi_decode /
    phi viterbi_decode kernel). potentials: [b, t, n] emissions,
    transition_params: [n, n] (+2 with bos/eos tags at [-2]=bos, [-1]=eos).
    Returns (scores [b], paths [b, t])."""

    def fn(emis, trans):
        b, t, n = emis.shape

        if include_bos_eos_tag:
            start = trans[-2, :][None, :]  # bos -> tag
            stop = trans[:, -1]
        else:
            start = jnp.zeros((1, n), emis.dtype)
            stop = jnp.zeros((n,), emis.dtype)

        alpha0 = emis[:, 0] + start  # [b, n]

        def step(alpha, e_t):
            # scores[b, i, j] = alpha[b, i] + trans[i, j]
            scores = alpha[:, :, None] + trans[None, :n, :n]
            best_prev = jnp.argmax(scores, axis=1)  # [b, n]
            alpha_new = jnp.max(scores, axis=1) + e_t
            return alpha_new, best_prev

        alpha, backptrs = jax.lax.scan(step, alpha0,
                                       jnp.swapaxes(emis[:, 1:], 0, 1))
        alpha = alpha + stop[None, :]
        last = jnp.argmax(alpha, axis=-1)  # [b]
        score = jnp.max(alpha, axis=-1)

        def backtrace(carry, bp_t):
            tag = carry
            prev = jnp.take_along_axis(bp_t, tag[:, None], 1)[:, 0]
            return prev, tag

        tag0, path_rest = jax.lax.scan(backtrace, last, backptrs,
                                       reverse=True)
        # path_rest[k] = tag at step k+1; tag0 = tag at step 0
        path = jnp.concatenate([tag0[None], path_rest], axis=0) if t > 1 \
            else last[None]
        return score, jnp.swapaxes(path, 0, 1).astype(jnp.int64)

    return apply(fn, potentials, transition_params, name="viterbi_decode")


class ViterbiDecoder:
    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
