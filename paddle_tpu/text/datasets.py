"""`paddle.text` datasets (reference: python/paddle/text/datasets/ —
conll05.py, imdb.py, imikolov.py, movielens.py, uci_housing.py,
wmt14.py, wmt16.py).

Same contract as the vision datasets: a local ``data_file`` (the same
archive/format the reference downloads) is parsed directly; without one,
download is attempted from the reference URLs (which requires network
egress — pass local files in hermetic environments).
"""

from __future__ import annotations

import gzip
import io
import os
import re
import tarfile

import numpy as np

from ..io import Dataset

__all__ = ["UCIHousing", "Imikolov", "Imdb", "Movielens", "Conll05st",
           "WMT14", "WMT16"]

_HOME = os.path.expanduser(os.environ.get(
    "PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


def _fetch(url, path):
    import urllib.request

    os.makedirs(os.path.dirname(path), exist_ok=True)
    try:
        urllib.request.urlretrieve(url, path)
    except Exception as e:  # pragma: no cover - no egress in CI
        raise RuntimeError(
            f"could not download {url} ({e}); pass data_file= with a "
            "local copy") from e


def _resolve(data_file, name, url):
    if data_file is not None:
        return data_file
    path = os.path.join(_HOME, name, os.path.basename(url))
    if not os.path.exists(path):
        _fetch(url, path)
    return path


class UCIHousing(Dataset):
    """Boston housing regression (reference uci_housing.py): 13 features
    + price, whitespace-separated; features min-max normalized over the
    whole set, first 80% train / rest test."""

    URL = ("http://paddlemodels.bj.bcebos.com/uci_housing/housing.data")

    def __init__(self, data_file=None, mode="train", download=True):
        assert mode in ("train", "test")
        path = data_file or _resolve(None, "uci_housing", self.URL)
        raw = np.loadtxt(path).astype("float32")
        feats, target = raw[:, :-1], raw[:, -1:]
        mn, mx = feats.min(0), feats.max(0)
        feats = (feats - mn) / np.maximum(mx - mn, 1e-12)
        split = int(len(raw) * 0.8)
        if mode == "train":
            self.data = np.concatenate([feats[:split], target[:split]], 1)
        else:
            self.data = np.concatenate([feats[split:], target[split:]], 1)

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)


class Imikolov(Dataset):
    """PTB language-model dataset (reference imikolov.py): builds a word
    dict with a frequency cutoff and yields n-grams ('NGRAM') or whole
    sequences ('SEQ') of word ids."""

    URL = "https://dataset.bj.bcebos.com/imikolov%2Fsimple-examples.tgz"

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=True):
        assert data_type in ("NGRAM", "SEQ")
        assert mode in ("train", "test")
        self.data_type = data_type
        self.window_size = window_size
        path = data_file or _resolve(None, "imikolov", self.URL)
        train_name = "./simple-examples/data/ptb.train.txt"
        test_name = "./simple-examples/data/ptb.valid.txt"
        with tarfile.open(path) as tf:
            names = tf.getnames()

            def read(name):
                for n in names:
                    if n.endswith(name.lstrip("./")) or n == name:
                        return tf.extractfile(n).read().decode()
                raise KeyError(name)
            train_txt = read(train_name)
            test_txt = read(test_name)
        self.word_idx = self._build_dict(train_txt, min_word_freq)
        txt = train_txt if mode == "train" else test_txt
        self.data = self._to_ids(txt)

    def _build_dict(self, text, cutoff):
        freq = {}
        for line in text.splitlines():
            for w in line.strip().split():
                freq[w] = freq.get(w, 0) + 1
        freq = {w: c for w, c in freq.items() if c > cutoff}
        freq.pop("<unk>", None)
        words = sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
        word_idx = {w: i for i, (w, _) in enumerate(words)}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def _to_ids(self, text):
        unk = self.word_idx["<unk>"]
        out = []
        for line in text.splitlines():
            words = ["<s>"] + line.strip().split() + ["<e>"]
            ids = [self.word_idx.get(w, unk) for w in words]
            if self.data_type == "SEQ":
                if len(ids) > 2:
                    out.append(np.asarray(ids, np.int64))
                continue
            n = self.window_size
            if len(ids) >= n:
                for i in range(n, len(ids) + 1):
                    out.append(np.asarray(ids[i - n:i], np.int64))
        return out

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """IMDB sentiment (reference imdb.py): aclImdb tarball, pos/neg text
    files tokenized into word ids + 0/1 label."""

    URL = "https://dataset.bj.bcebos.com/imdb%2FaclImdb_v1.tar.gz"

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True):
        assert mode in ("train", "test")
        path = data_file or _resolve(None, "imdb", self.URL)
        pat = re.compile(f"aclImdb/{mode}/(pos|neg)/.*\\.txt$")
        train_pat = re.compile("aclImdb/train/(pos|neg)/.*\\.txt$")
        tok = re.compile(r"[a-z]+")
        docs, labels = [], []
        freq = {}
        with tarfile.open(path) as tf:
            members = [m for m in tf.getmembers() if m.isfile()]
            for m in members:
                if train_pat.search(m.name):
                    words = tok.findall(
                        tf.extractfile(m).read().decode(
                            "utf-8", "ignore").lower())
                    for w in words:
                        freq[w] = freq.get(w, 0) + 1
            freq = {w: c for w, c in freq.items() if c > cutoff}
            words_sorted = sorted(freq.items(),
                                  key=lambda kv: (-kv[1], kv[0]))
            self.word_idx = {w: i for i, (w, _) in enumerate(words_sorted)}
            self.word_idx["<unk>"] = len(self.word_idx)
            unk = self.word_idx["<unk>"]
            for m in members:
                match = pat.search(m.name)
                if not match:
                    continue
                words = tok.findall(
                    tf.extractfile(m).read().decode(
                        "utf-8", "ignore").lower())
                docs.append(np.asarray(
                    [self.word_idx.get(w, unk) for w in words], np.int64))
                labels.append(0 if match.group(1) == "pos" else 1)
        self.docs = docs
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Movielens(Dataset):
    """MovieLens-1M ratings (reference movielens.py): yields (user_id,
    gender, age, job, movie_id, category ids, title ids, rating)."""

    URL = "https://dataset.bj.bcebos.com/movielens%2Fml-1m.zip"

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        import zipfile

        assert mode in ("train", "test")
        path = data_file or _resolve(None, "movielens", self.URL)
        with zipfile.ZipFile(path) as zf:
            def read(name):
                for n in zf.namelist():
                    if n.endswith(name):
                        return zf.read(n).decode("latin1")
                raise KeyError(name)
            movies_raw = read("movies.dat")
            users_raw = read("users.dat")
            ratings_raw = read("ratings.dat")

        self.categories = {}
        self.title_words = {}
        movies = {}
        for line in movies_raw.splitlines():
            mid, title, cats = line.strip().split("::")
            title = re.sub(r"\(\d{4}\)$", "", title).strip()
            cat_ids = []
            for c in cats.split("|"):
                cat_ids.append(self.categories.setdefault(
                    c, len(self.categories)))
            tw = []
            for w in title.lower().split():
                tw.append(self.title_words.setdefault(
                    w, len(self.title_words)))
            movies[int(mid)] = (np.asarray(cat_ids, np.int64),
                                np.asarray(tw, np.int64))
        users = {}
        for line in users_raw.splitlines():
            uid, gender, age, job, _zip = line.strip().split("::")
            users[int(uid)] = (0 if gender == "M" else 1, int(age),
                               int(job))
        rows = []
        for line in ratings_raw.splitlines():
            uid, mid, rating, _ts = line.strip().split("::")
            uid, mid = int(uid), int(mid)
            if mid not in movies or uid not in users:
                continue
            rows.append((uid, mid, float(rating)))
        rng = np.random.default_rng(rand_seed)
        test_mask = rng.random(len(rows)) < test_ratio
        keep = [r for r, t in zip(rows, test_mask)
                if (t if mode == "test" else not t)]
        self.users = users
        self.movies = movies
        self.rows = keep

    def __getitem__(self, idx):
        uid, mid, rating = self.rows[idx]
        gender, age, job = self.users[uid]
        cats, title = self.movies[mid]
        return (np.int64(uid), np.int64(gender), np.int64(age),
                np.int64(job), np.int64(mid), cats, title,
                np.float32(rating))

    def __len__(self):
        return len(self.rows)


class _ParallelCorpus(Dataset):
    """Shared machinery for WMT14/WMT16-style parallel corpora: a
    tarball holding src/trg token files + vocabulary files; yields
    (src_ids, trg_ids, trg_ids_next) like the reference."""

    def __init__(self, path, src_name, trg_name, src_dict_name,
                 trg_dict_name, dict_size=-1):
        with tarfile.open(path) as tf:
            names = tf.getnames()

            def read(suffix):
                for n in names:
                    if n.endswith(suffix):
                        return tf.extractfile(n).read().decode(
                            "utf-8", "ignore")
                raise KeyError(suffix)
            self.src_dict = self._load_dict(read(src_dict_name), dict_size)
            self.trg_dict = self._load_dict(read(trg_dict_name), dict_size)
            src_lines = read(src_name).splitlines()
            trg_lines = read(trg_name).splitlines()
        s_unk = self.src_dict.get("<unk>", len(self.src_dict) - 1)
        t_unk = self.trg_dict.get("<unk>", len(self.trg_dict) - 1)
        start = self.trg_dict.get("<s>", 0)
        end = self.trg_dict.get("<e>", 1)
        self.data = []
        for s, t in zip(src_lines, trg_lines):
            if not s.strip() or not t.strip():
                continue
            sid = [self.src_dict.get(w, s_unk) for w in s.split()]
            tid = [self.trg_dict.get(w, t_unk) for w in t.split()]
            self.data.append((
                np.asarray(sid, np.int64),
                np.asarray([start] + tid, np.int64),
                np.asarray(tid + [end], np.int64)))

    @staticmethod
    def _load_dict(text, dict_size):
        words = [w.strip().split("\t")[0] for w in text.splitlines()
                 if w.strip()]
        if dict_size > 0:
            words = words[:dict_size]
        d = {w: i for i, w in enumerate(words)}
        for tok in ("<s>", "<e>", "<unk>"):
            if tok not in d:
                d[tok] = len(d)
        return d

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class WMT14(_ParallelCorpus):
    """WMT14 en-fr (reference wmt14.py)."""

    URL = ("http://paddlemodels.bj.bcebos.com/wmt/wmt14.tgz")

    def __init__(self, data_file=None, mode="train", dict_size=-1,
                 download=True):
        assert mode in ("train", "test", "gen")
        path = data_file or _resolve(None, "wmt14", self.URL)
        super().__init__(path, f"{mode}.src", f"{mode}.trg", "src.dict",
                         "trg.dict", dict_size)


class WMT16(_ParallelCorpus):
    """WMT16 en-de (reference wmt16.py)."""

    URL = "http://paddlemodels.bj.bcebos.com/wmt/wmt16.tar.gz"

    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en", download=True):
        assert mode in ("train", "test", "val")
        path = data_file or _resolve(None, "wmt16", self.URL)
        super().__init__(path, f"{mode}.{lang}",
                         f"{mode}.{'de' if lang == 'en' else 'en'}",
                         f"{lang}.dict",
                         f"{'de' if lang == 'en' else 'en'}.dict",
                         max(src_dict_size, trg_dict_size))


class Conll05st(Dataset):
    """CoNLL-2005 SRL (reference conll05.py): per-token rows of
    (word, predicate, labels...) separated by blank lines; yields word
    ids, predicate id and label ids using the bundled dictionaries."""

    URL = "http://paddlemodels.bj.bcebos.com/conll05st/conll05st-tests.tar.gz"

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None,
                 download=True):
        path = data_file or _resolve(None, "conll05st", self.URL)
        with tarfile.open(path) as tf:
            names = tf.getnames()

            def read(suffix):
                for n in names:
                    if n.endswith(suffix):
                        raw = tf.extractfile(n).read()
                        if n.endswith(".gz"):
                            raw = gzip.decompress(raw)
                        return raw.decode("utf-8", "ignore")
                raise KeyError(suffix)
            self.word_dict = self._load_list(read("wordDict.txt"))
            self.verb_dict = self._load_list(read("verbDict.txt"))
            self.label_dict = self._load_list(read("targetDict.txt"))
            text = read("test.wsj.words.gz") if any(
                n.endswith("test.wsj.words.gz") for n in names) \
                else read("data.txt")
            props = read("test.wsj.props.gz") if any(
                n.endswith("test.wsj.props.gz") for n in names) else None
        self.data = self._parse(text, props)

    @staticmethod
    def _load_list(text):
        return {w.strip(): i for i, w in enumerate(text.splitlines())
                if w.strip()}

    def _parse(self, words_text, props_text):
        w_unk = self.word_dict.get("<unk>", 0)
        sents = [s.split("\n") for s in words_text.strip().split("\n\n")]
        out = []
        for sent in sents:
            toks = [t.strip() for t in sent if t.strip()]
            ids = np.asarray([self.word_dict.get(t.lower(), w_unk)
                              for t in toks], np.int64)
            out.append(ids)
        return out

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)
