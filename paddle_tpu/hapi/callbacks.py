"""hapi callbacks (reference: python/paddle/hapi/callbacks.py)."""

from __future__ import annotations

import numbers
import os


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = []
            for k, v in (logs or {}).items():
                if isinstance(v, numbers.Number):
                    items.append(f"{k}: {v:.4f}")
                else:
                    items.append(f"{k}: {v}")
            print(f"Epoch {self.epoch}: step {step}/{self.steps or '?'} - "
                  + " - ".join(items))


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir or "checkpoint"

    def on_epoch_end(self, epoch, logs=None):
        if epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = self.model._optimizer
        return opt._lr_scheduler if opt else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.best = baseline
        self.wait = 0
        self.stopped_epoch = 0
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self._cmp = lambda cur, best: cur > best + self.min_delta
        else:
            self._cmp = lambda cur, best: cur < best - self.min_delta

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self.best is None or self._cmp(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class ReduceLROnPlateau(Callback):
    """Reduce the optimizer LR when a monitored metric plateaus
    (reference callbacks.py ReduceLROnPlateau)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10,
                 verbose=1, mode="auto", min_delta=1e-4, cooldown=0,
                 min_lr=0.0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.best = float("-inf") if mode == "max" else float("inf")
        self.wait = 0
        self.cooldown_counter = 0

    def _better(self, cur):
        if self.mode == "max":
            return cur > self.best + self.min_delta
        return cur < self.best - self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        cur = float(cur)
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if self._better(cur):
            self.best = cur
            self.wait = 0
            return
        self.wait += 1
        if self.wait >= self.patience:
            opt = getattr(self.model, "_optimizer", None)
            if opt is None:
                return
            old = opt.get_lr()
            new = max(old * self.factor, self.min_lr)
            if old - new > 1e-12:
                if opt._lr_scheduler is not None:
                    opt._lr_scheduler.base_lr = new
                    opt._lr_scheduler.last_lr = new
                else:
                    opt.set_lr(new)
                if self.verbose:
                    print(f"ReduceLROnPlateau: lr {old:.3g} -> {new:.3g}")
            self.cooldown_counter = self.cooldown
            self.wait = 0


class VisualDL(Callback):
    """Scalar logging callback (reference callbacks.py VisualDL). The
    visualdl package isn't vendored; scalars append to a jsonl file under
    log_dir that its UI (or anything else) can tail."""

    def __init__(self, log_dir="./log"):
        super().__init__()
        self.log_dir = log_dir
        self._step = 0
        self._f = None

    def _writer(self):
        if self._f is None:
            os.makedirs(self.log_dir, exist_ok=True)
            self._f = open(os.path.join(self.log_dir, "vdl_scalars.jsonl"),
                           "a")
        return self._f

    def _log(self, tag, logs, step):
        import json
        logs = logs or {}
        w = self._writer()
        for k, v in logs.items():
            if isinstance(v, (list, tuple)):
                v = v[0] if v else None
            if isinstance(v, numbers.Number):
                w.write(json.dumps({"tag": f"{tag}/{k}", "step": step,
                                    "value": float(v)}) + "\n")
        w.flush()

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        if self._step % 10 == 0:
            self._log("train", logs, self._step)

    def on_epoch_end(self, epoch, logs=None):
        self._log("train_epoch", logs, epoch)

    def on_eval_end(self, logs=None):
        self._log("eval", logs, self._step)

    def on_train_end(self, logs=None):
        if self._f is not None:
            self._f.close()
            self._f = None


class WandbCallback(Callback):
    """Weights & Biases logging (reference callbacks.py WandbCallback).
    Uses the wandb package when importable; otherwise degrades to the
    same jsonl scalar log as VisualDL."""

    def __init__(self, project=None, dir=None, **kwargs):
        super().__init__()
        self.project = project
        self.dir = dir or "./wandb"
        self.kwargs = kwargs
        try:
            import wandb
            self._wandb = wandb
        except ImportError:
            self._wandb = None
            self._fallback = VisualDL(log_dir=self.dir)
        self._run = None

    def on_train_begin(self, logs=None):
        if self._wandb is not None:
            self._run = self._wandb.init(project=self.project,
                                         dir=self.dir, **self.kwargs)

    def on_train_batch_end(self, step, logs=None):
        if self._wandb is not None and self._run is not None:
            self._run.log({k: v for k, v in (logs or {}).items()
                           if isinstance(v, numbers.Number)})
        elif self._wandb is None:
            self._fallback.on_train_batch_end(step, logs)

    def on_train_end(self, logs=None):
        if self._run is not None:
            self._run.finish()
        elif self._wandb is None:
            self._fallback.on_train_end(logs)
