"""hapi callbacks (reference: python/paddle/hapi/callbacks.py)."""

from __future__ import annotations

import numbers
import os


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = []
            for k, v in (logs or {}).items():
                if isinstance(v, numbers.Number):
                    items.append(f"{k}: {v:.4f}")
                else:
                    items.append(f"{k}: {v}")
            print(f"Epoch {self.epoch}: step {step}/{self.steps or '?'} - "
                  + " - ".join(items))


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir or "checkpoint"

    def on_epoch_end(self, epoch, logs=None):
        if epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = self.model._optimizer
        return opt._lr_scheduler if opt else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.best = baseline
        self.wait = 0
        self.stopped_epoch = 0
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self._cmp = lambda cur, best: cur > best + self.min_delta
        else:
            self._cmp = lambda cur, best: cur < best - self.min_delta

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self.best is None or self._cmp(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
