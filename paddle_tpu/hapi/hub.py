"""`paddle.hub` backend (parity: reference python/paddle/hapi/hub.py:
list/help/load over a repo's hubconf.py entrypoints; sources github /
gitee / local). Hermetic environments use source='local'; remote
sources download+cache a repo archive (requires egress)."""

from __future__ import annotations

import importlib.util
import os
import sys
import zipfile

_HUB_DIR = os.path.expanduser(os.environ.get(
    "PADDLE_TPU_HUB_DIR", "~/.cache/paddle_tpu/hub"))


def _fetch_repo(repo, source, force_reload):
    owner_repo, _, branch = repo.partition(":")
    branch = branch or "main"
    name = owner_repo.replace("/", "_") + "_" + branch
    target = os.path.join(_HUB_DIR, name)
    if os.path.isdir(target) and not force_reload:
        return target
    host = {"github": "https://github.com/{}/archive/{}.zip",
            "gitee": "https://gitee.com/{}/repository/archive/{}.zip"}[
        source]
    url = host.format(owner_repo, branch)
    os.makedirs(_HUB_DIR, exist_ok=True)
    zpath = target + ".zip"
    import urllib.request
    try:
        urllib.request.urlretrieve(url, zpath)
    except Exception as e:
        raise RuntimeError(
            f"paddle.hub: cannot download {url} ({e}); in hermetic "
            "environments pass source='local' with a local repo_dir "
            "containing hubconf.py") from e
    import shutil
    with zipfile.ZipFile(zpath) as zf:
        roots = {n.split("/", 1)[0] for n in zf.namelist()}
        zf.extractall(_HUB_DIR)
    # force_reload refreshes an existing cache entry: clear it first
    # (os.rename onto a non-empty dir raises ENOTEMPTY)
    shutil.rmtree(target, ignore_errors=True)
    os.rename(os.path.join(_HUB_DIR, roots.pop()), target)
    return target


def _hubconf(repo_dir, source, force_reload):
    if source not in ("github", "gitee", "local"):
        raise ValueError(
            f"unknown source {source!r}: expected github/gitee/local")
    path = repo_dir if source == "local" else _fetch_repo(
        repo_dir, source, force_reload)
    conf = os.path.join(path, "hubconf.py")
    if not os.path.exists(conf):
        raise RuntimeError(f"no hubconf.py under {path}")
    spec = importlib.util.spec_from_file_location(
        f"paddle_tpu_hubconf_{abs(hash(conf))}", conf)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, path)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.remove(path)
    return mod


def _entrypoints(mod):
    return {n: f for n, f in vars(mod).items()
            if callable(f) and not n.startswith("_")}


def list(repo_dir, source="github", force_reload=False):  # noqa: A001
    """Names of the callable entrypoints exported by the repo's
    hubconf.py (reference hapi/hub.py:182)."""
    return sorted(_entrypoints(_hubconf(repo_dir, source, force_reload)))


def help(repo_dir, model, source="github", force_reload=False):  # noqa: A002
    """The entrypoint's docstring (reference hapi/hub.py:232)."""
    eps = _entrypoints(_hubconf(repo_dir, source, force_reload))
    if model not in eps:
        raise RuntimeError(f"no entrypoint {model!r}; have "
                           f"{sorted(eps)}")
    return eps[model].__doc__


def load(repo_dir, model, source="github", force_reload=False, **kwargs):
    """Call the entrypoint and return its model
    (reference hapi/hub.py:280)."""
    eps = _entrypoints(_hubconf(repo_dir, source, force_reload))
    if model not in eps:
        raise RuntimeError(f"no entrypoint {model!r}; have "
                           f"{sorted(eps)}")
    return eps[model](**kwargs)
