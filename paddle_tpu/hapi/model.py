"""`paddle.Model` (reference: python/paddle/hapi/model.py).

fit/evaluate/predict drive the eager layers through the compiled
TrainStep when possible (single loss tensor), falling back to eager
stepping for multi-metric loops.
"""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..io import DataLoader, Dataset
from .callbacks import Callback, ProgBarLogger


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self.stop_training = False
        self._optimizer = None
        self._loss = None
        self._metrics = []

    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _to_list(metrics)
        return self

    # -- core steps --------------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        outputs = self.network(*[_as_tensor(x) for x in inputs])
        losses = self._compute_loss(outputs, labels)
        total = losses[0]
        for l in losses[1:]:
            total = total + l
        total.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = [float(l) for l in losses]
        return metrics if len(metrics) > 1 else metrics[0]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        from ..core.autograd import no_grad
        with no_grad():
            outputs = self.network(*[_as_tensor(x) for x in inputs])
            losses = self._compute_loss(outputs, labels)
        return [float(l) for l in losses]

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = _to_list(inputs)
        from ..core.autograd import no_grad
        with no_grad():
            out = self.network(*[_as_tensor(x) for x in inputs])
        outs = _to_list(out)
        return [o.numpy() for o in outs]

    def _compute_loss(self, outputs, labels):
        outs = _to_list(outputs)
        if self._loss is None:
            return outs
        labels = [_as_tensor(l) for l in labels]
        loss = self._loss(*(outs + labels))
        return _to_list(loss)

    # -- loops -------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None, accumulate_grad_batches=1, num_iters=None):
        loader = self._as_loader(train_data, batch_size, shuffle, drop_last,
                                 num_workers)
        eval_loader = self._as_loader(eval_data, batch_size, False, False,
                                      num_workers) if eval_data is not None \
            else None
        cbks = _to_list(callbacks) or [ProgBarLogger(log_freq,
                                                     verbose=verbose)]
        for cb in cbks:
            cb.set_model(self)
            cb.set_params({"epochs": epochs, "steps": _safe_len(loader),
                           "verbose": verbose})
        self.stop_training = False
        for cb in cbks:
            cb.on_train_begin()
        it = 0
        for epoch in range(epochs):
            for cb in cbks:
                cb.on_epoch_begin(epoch)
            for step, batch in enumerate(loader):
                for cb in cbks:
                    cb.on_train_batch_begin(step)
                inputs, labels = _split_batch(batch)
                loss = self.train_batch(inputs, labels)
                logs = {"loss": loss}
                for cb in cbks:
                    cb.on_train_batch_end(step, logs)
                it += 1
                if num_iters is not None and it >= num_iters:
                    break
            for cb in cbks:
                cb.on_epoch_end(epoch, logs if "logs" in dir() else None)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self._run_eval(eval_loader, cbks)
            if save_dir is not None and (epoch + 1) % save_freq == 0:
                self.save(f"{save_dir}/{epoch}")
            if self.stop_training or (num_iters is not None and
                                      it >= num_iters):
                break
        for cb in cbks:
            cb.on_train_end()
        return self

    def _run_eval(self, loader, cbks):
        for cb in cbks:
            cb.on_eval_begin()
        total, count = 0.0, 0
        for step, batch in enumerate(loader):
            inputs, labels = _split_batch(batch)
            losses = self.eval_batch(inputs, labels)
            total += losses[0]
            count += 1
        logs = {"loss": total / max(count, 1)}
        for cb in cbks:
            cb.on_eval_end(logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = self._as_loader(eval_data, batch_size, False, False,
                                 num_workers)
        total, count = 0.0, 0
        for batch in loader:
            inputs, labels = _split_batch(batch)
            losses = self.eval_batch(inputs, labels)
            total += losses[0]
            count += 1
        return {"loss": total / max(count, 1)}

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = self._as_loader(test_data, batch_size, False, False,
                                 num_workers)
        outputs = []
        # with a prepared loss the dataset is assumed labeled (paddle
        # semantics follow the declared input specs; we use loss presence)
        has_label = self._loss is not None
        for batch in loader:
            inputs, _ = _split_batch(batch, has_label=has_label)
            outputs.append(self.predict_batch(inputs))
        if stack_outputs and outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs])
                    for i in range(n_out)]
        return outputs

    # -- persistence -------------------------------------------------------
    def save(self, path, training=True):
        from ..framework.io import save as _save
        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as _load
        self.network.set_state_dict(_load(path + ".pdparams"))
        import os
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(_load(path + ".pdopt"))
        return self

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary
        return _summary(self.network, input_size, dtypes=dtype)

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _as_loader(data, batch_size, shuffle, drop_last, num_workers):
        if data is None or isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              drop_last=drop_last, num_workers=num_workers)
        return data  # assume iterable of batches


def _as_tensor(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def _safe_len(loader):
    try:
        return len(loader)
    except TypeError:
        return None


def _split_batch(batch, has_label=True):
    if isinstance(batch, (list, tuple)):
        if has_label and len(batch) >= 2:
            return list(batch[:-1]), [batch[-1]]
        return list(batch), []
    return [batch], []
