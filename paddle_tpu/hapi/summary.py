"""Model summary (reference: python/paddle/hapi/model_summary.py)."""

from __future__ import annotations

import numpy as np

from ..core import dtype as dtype_mod
from ..core.tensor import Tensor


def summary(net, input_size=None, dtypes=None, input=None):
    """Print a layer table; returns {'total_params', 'trainable_params'}."""
    rows = []
    hooks = []
    order = []

    def register(layer, name):
        def hook(l, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (tuple, list)) else \
                outputs
            shape = list(out.shape) if isinstance(out, Tensor) else "-"
            n_params = sum(p.size for p in l._parameters.values()
                           if p is not None)
            rows.append((name or type(l).__name__, str(shape), n_params))
        hooks.append(layer.register_forward_post_hook(hook))

    for name, layer in net.named_sublayers(include_self=False):
        if not layer._sub_layers:  # leaves only
            register(layer, f"{type(layer).__name__}-{name}")

    if input is None and input_size is not None:
        dt = dtypes or dtype_mod.get_default_dtype()
        shapes = input_size if isinstance(input_size, list) and \
            isinstance(input_size[0], (list, tuple)) else [input_size]
        input = [Tensor(np.zeros(s, np.dtype("float32")), dtype=dt)
                 for s in shapes]
    if input is not None:
        args = input if isinstance(input, (list, tuple)) else [input]
        net(*args)
    for h in hooks:
        h.remove()

    total = sum(p.size for p in net.parameters())
    trainable = sum(p.size for p in net.parameters() if p.trainable)

    line = "{:<32} {:<24} {:>12}"
    print("-" * 70)
    print(line.format("Layer (type)", "Output Shape", "Param #"))
    print("=" * 70)
    for r in rows:
        print(line.format(*[str(c) for c in r]))
    print("=" * 70)
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print("-" * 70)
    return {"total_params": total, "trainable_params": trainable}
