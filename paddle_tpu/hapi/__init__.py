"""`paddle.hapi` / `paddle.Model` high-level API.

Parity: reference python/paddle/hapi/model.py (Model.prepare/fit/evaluate/
predict), callbacks (callbacks.py: ProgBarLogger, ModelCheckpoint,
EarlyStopping, LRScheduler), summary (model_summary.py).
"""

from .model import Model  # noqa: F401
from .callbacks import (  # noqa: F401
    Callback, EarlyStopping, LRScheduler, ModelCheckpoint, ProgBarLogger,
    ReduceLROnPlateau, VisualDL, WandbCallback,
)
from .summary import summary  # noqa: F401
