// TCPStore: rank-0-hosted key-value rendezvous store.
//
// Capability parity with the reference's native store
// (paddle/phi/core/distributed/store/tcp_store.h:121 TCPStore,
// tcp_utils.cc socket plumbing): set/get/wait/add/check with blocking
// waiters, serving distributed bootstrap (the reference broadcasts NCCL
// unique ids through it; here it backs paddle_tpu.distributed bootstrap
// and elastic coordination alongside the JAX coordination service).
//
// Build: g++ -O2 -shared -fPIC -o libpt_store.so tcp_store.cc -lpthread
// Exposed as a C ABI consumed via ctypes (paddle_tpu/distributed/store.py).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <algorithm>
#include <thread>
#include <vector>

namespace {

enum class Command : uint8_t { SET = 0, GET = 1, ADD = 2, WAIT = 3,
                               CHECK = 4, DELETE = 5 };

// ---- framing helpers ----------------------------------------------------
bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_bytes(int fd, const std::string& s) {
  uint32_t len = static_cast<uint32_t>(s.size());
  return send_all(fd, &len, 4) && (len == 0 || send_all(fd, s.data(), len));
}

bool recv_bytes(int fd, std::string* out) {
  uint32_t len = 0;
  if (!recv_all(fd, &len, 4)) return false;
  out->resize(len);
  return len == 0 || recv_all(fd, out->data(), len);
}

// ---- server -------------------------------------------------------------
class StoreServer {
 public:
  explicit StoreServer(int port) : port_(port) {}

  bool start() {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return false;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port_));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) != 0)
      return false;
    if (port_ == 0) {  // report kernel-chosen port
      socklen_t len = sizeof(addr);
      ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
      port_ = ntohs(addr.sin_port);
    }
    if (::listen(listen_fd_, 128) != 0) return false;
    running_.store(true);
    accept_thread_ = std::thread([this] { accept_loop(); });
    return true;
  }

  void stop() {
    running_.store(false);
    if (listen_fd_ >= 0) {
      ::shutdown(listen_fd_, SHUT_RDWR);
      ::close(listen_fd_);
      listen_fd_ = -1;
    }
    if (accept_thread_.joinable()) accept_thread_.join();
    // handler threads block in recv()/cv-wait on live peer connections
    // (other processes' clients); force them out so join cannot hang
    {
      std::lock_guard<std::mutex> lk(conn_mu_);
      for (int fd : conn_fds_) ::shutdown(fd, SHUT_RDWR);
    }
    // take mu_ once so a WAIT handler that read running_==true has
    // entered its wait before the notify (otherwise the wakeup is lost
    // and the join below blocks for the client's full wait timeout)
    { std::lock_guard<std::mutex> lk(mu_); }
    cv_.notify_all();
    // join without holding conn_mu_ — exiting handlers take it to
    // deregister their fd
    std::vector<std::thread> hs;
    {
      std::lock_guard<std::mutex> lk(conn_mu_);
      hs.swap(handlers_);
    }
    for (auto& t : hs)
      if (t.joinable()) t.join();
  }

  int port() const { return port_; }

  ~StoreServer() { stop(); }

 private:
  void accept_loop() {
    while (running_.load()) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {
        if (!running_.load()) break;
        continue;
      }
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> lk(conn_mu_);
      conn_fds_.push_back(fd);
      handlers_.emplace_back([this, fd] { serve(fd); });
    }
  }

  void serve(int fd) {
    serve_loop(fd);
    // Deregister BEFORE close: once closed, the fd number can be reused by
    // another thread, and a concurrent stop() iterating conn_fds_ would
    // shutdown() an unrelated descriptor.
    {
      std::lock_guard<std::mutex> lk(conn_mu_);
      conn_fds_.erase(std::remove(conn_fds_.begin(), conn_fds_.end(), fd),
                      conn_fds_.end());
    }
    ::close(fd);
  }

  void serve_loop(int fd) {
    while (true) {
      uint8_t cmd;
      if (!recv_all(fd, &cmd, 1)) break;
      std::string key;
      if (!recv_bytes(fd, &key)) break;
      switch (static_cast<Command>(cmd)) {
        case Command::SET: {
          std::string value;
          if (!recv_bytes(fd, &value)) return;
          {
            std::lock_guard<std::mutex> lk(mu_);
            data_[key] = value;
          }
          cv_.notify_all();
          uint8_t ok = 1;
          send_all(fd, &ok, 1);
          break;
        }
        case Command::GET: {
          std::unique_lock<std::mutex> lk(mu_);
          auto it = data_.find(key);
          std::string value = it == data_.end() ? "" : it->second;
          uint8_t found = it != data_.end();
          lk.unlock();
          send_all(fd, &found, 1);
          send_bytes(fd, value);
          break;
        }
        case Command::ADD: {
          int64_t delta;
          if (!recv_all(fd, &delta, 8)) return;
          int64_t result;
          {
            std::lock_guard<std::mutex> lk(mu_);
            int64_t cur = 0;
            auto it = data_.find(key);
            if (it != data_.end())
              cur = std::stoll(it->second);
            result = cur + delta;
            data_[key] = std::to_string(result);
          }
          cv_.notify_all();
          send_all(fd, &result, 8);
          break;
        }
        case Command::WAIT: {
          int64_t timeout_ms;
          if (!recv_all(fd, &timeout_ms, 8)) return;
          std::unique_lock<std::mutex> lk(mu_);
          cv_.wait_for(
              lk, std::chrono::milliseconds(timeout_ms),
              [&] { return data_.count(key) > 0 || !running_.load(); });
          bool ok = data_.count(key) > 0;  // stop-wakeup is not success
          lk.unlock();
          uint8_t r = ok ? 1 : 0;
          send_all(fd, &r, 1);
          break;
        }
        case Command::CHECK: {
          std::lock_guard<std::mutex> lk(mu_);
          uint8_t r = data_.count(key) > 0 ? 1 : 0;
          send_all(fd, &r, 1);
          break;
        }
        case Command::DELETE: {
          size_t n;
          {
            std::lock_guard<std::mutex> lk(mu_);
            n = data_.erase(key);
          }
          uint8_t r = n > 0 ? 1 : 0;
          send_all(fd, &r, 1);
          break;
        }
      }
    }
  }

  int port_;
  int listen_fd_ = -1;
  std::atomic<bool> running_{false};
  std::thread accept_thread_;
  std::mutex conn_mu_;
  std::vector<int> conn_fds_;
  std::vector<std::thread> handlers_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::map<std::string, std::string> data_;
};

// ---- client -------------------------------------------------------------
class StoreClient {
 public:
  bool connect_to(const char* host, int port, int timeout_ms) {
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms);
    while (std::chrono::steady_clock::now() < deadline) {
      fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
      sockaddr_in addr{};
      addr.sin_family = AF_INET;
      addr.sin_port = htons(static_cast<uint16_t>(port));
      ::inet_pton(AF_INET, host, &addr.sin_addr);
      if (::connect(fd_, reinterpret_cast<sockaddr*>(&addr),
                    sizeof(addr)) == 0) {
        int one = 1;
        ::setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        return true;
      }
      ::close(fd_);
      fd_ = -1;
      std::this_thread::sleep_for(std::chrono::milliseconds(50));
    }
    return false;
  }

  bool set(const std::string& key, const std::string& value) {
    std::lock_guard<std::mutex> lk(mu_);
    uint8_t cmd = static_cast<uint8_t>(Command::SET);
    if (!send_all(fd_, &cmd, 1) || !send_bytes(fd_, key) ||
        !send_bytes(fd_, value))
      return false;
    uint8_t ok;
    return recv_all(fd_, &ok, 1) && ok == 1;
  }

  bool get(const std::string& key, std::string* value) {
    std::lock_guard<std::mutex> lk(mu_);
    uint8_t cmd = static_cast<uint8_t>(Command::GET);
    if (!send_all(fd_, &cmd, 1) || !send_bytes(fd_, key)) return false;
    uint8_t found;
    if (!recv_all(fd_, &found, 1)) return false;
    if (!recv_bytes(fd_, value)) return false;
    return found == 1;
  }

  bool add(const std::string& key, int64_t delta, int64_t* result) {
    std::lock_guard<std::mutex> lk(mu_);
    uint8_t cmd = static_cast<uint8_t>(Command::ADD);
    if (!send_all(fd_, &cmd, 1) || !send_bytes(fd_, key) ||
        !send_all(fd_, &delta, 8))
      return false;
    return recv_all(fd_, result, 8);
  }

  bool wait(const std::string& key, int64_t timeout_ms) {
    std::lock_guard<std::mutex> lk(mu_);
    uint8_t cmd = static_cast<uint8_t>(Command::WAIT);
    if (!send_all(fd_, &cmd, 1) || !send_bytes(fd_, key) ||
        !send_all(fd_, &timeout_ms, 8))
      return false;
    uint8_t ok;
    return recv_all(fd_, &ok, 1) && ok == 1;
  }

  bool check(const std::string& key) {
    std::lock_guard<std::mutex> lk(mu_);
    uint8_t cmd = static_cast<uint8_t>(Command::CHECK);
    if (!send_all(fd_, &cmd, 1) || !send_bytes(fd_, key)) return false;
    uint8_t ok;
    return recv_all(fd_, &ok, 1) && ok == 1;
  }

  bool del(const std::string& key) {
    std::lock_guard<std::mutex> lk(mu_);
    uint8_t cmd = static_cast<uint8_t>(Command::DELETE);
    if (!send_all(fd_, &cmd, 1) || !send_bytes(fd_, key)) return false;
    uint8_t ok;
    return recv_all(fd_, &ok, 1) && ok == 1;
  }

  ~StoreClient() {
    if (fd_ >= 0) ::close(fd_);
  }

 private:
  int fd_ = -1;
  std::mutex mu_;  // one request at a time per connection
};

}  // namespace

// ---- C ABI --------------------------------------------------------------
extern "C" {

void* pt_store_server_start(int port) {
  auto* s = new StoreServer(port);
  if (!s->start()) {
    delete s;
    return nullptr;
  }
  return s;
}

int pt_store_server_port(void* server) {
  return static_cast<StoreServer*>(server)->port();
}

void pt_store_server_stop(void* server) {
  delete static_cast<StoreServer*>(server);
}

void* pt_store_client_connect(const char* host, int port, int timeout_ms) {
  auto* c = new StoreClient();
  if (!c->connect_to(host, port, timeout_ms)) {
    delete c;
    return nullptr;
  }
  return c;
}

void pt_store_client_free(void* client) {
  delete static_cast<StoreClient*>(client);
}

int pt_store_set(void* client, const char* key, const uint8_t* value,
                 int len) {
  return static_cast<StoreClient*>(client)->set(
             key, std::string(reinterpret_cast<const char*>(value),
                              static_cast<size_t>(len)))
             ? 0
             : -1;
}

// returns value length, -1 if missing; caller passes buffer + capacity
int pt_store_get(void* client, const char* key, uint8_t* buf, int cap) {
  std::string value;
  if (!static_cast<StoreClient*>(client)->get(key, &value)) return -1;
  int n = static_cast<int>(value.size());
  if (n > cap) return -2;
  std::memcpy(buf, value.data(), value.size());
  return n;
}

int64_t pt_store_add(void* client, const char* key, int64_t delta) {
  int64_t result = 0;
  if (!static_cast<StoreClient*>(client)->add(key, delta, &result))
    return INT64_MIN;
  return result;
}

int pt_store_wait(void* client, const char* key, int64_t timeout_ms) {
  return static_cast<StoreClient*>(client)->wait(key, timeout_ms) ? 0 : -1;
}

int pt_store_check(void* client, const char* key) {
  return static_cast<StoreClient*>(client)->check(key) ? 1 : 0;
}

int pt_store_delete(void* client, const char* key) {
  return static_cast<StoreClient*>(client)->del(key) ? 1 : 0;
}

}  // extern "C"
