"""On-demand native build for paddle_tpu's C++ runtime components.

Parity note: the reference builds its native core through a CMake
superbuild (SURVEY.md §2.1 build system); here the native surface is small
enough that a direct g++ invocation with a content-hash cache does the job
(rebuilds only when sources change).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_SRC_DIR, "_build")
_LIBS = {
    "pt_store": ["tcp_store.cc"],
    "pt_data": ["token_dataset.cc"],
    "pt_shm": ["shm_ring.cc"],
}
_loaded: dict[str, ctypes.CDLL] = {}
_lock = threading.Lock()


def _hash_sources(sources):
    h = hashlib.sha256()
    for s in sources:
        with open(os.path.join(_SRC_DIR, s), "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def load_library(name: str) -> ctypes.CDLL:
    with _lock:
        if name in _loaded:
            return _loaded[name]
        sources = _LIBS[name]
        tag = _hash_sources(sources)
        os.makedirs(_BUILD_DIR, exist_ok=True)
        so_path = os.path.join(_BUILD_DIR, f"lib{name}-{tag}.so")
        if not os.path.exists(so_path):
            cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                   "-o", so_path] + \
                [os.path.join(_SRC_DIR, s) for s in sources] + ["-lpthread"]
            subprocess.run(cmd, check=True, capture_output=True)
        lib = ctypes.CDLL(so_path)
        _loaded[name] = lib
        return lib
