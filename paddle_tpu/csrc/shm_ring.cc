// Shared-memory ring buffer for DataLoader worker->parent transfer.
//
// Parity: the reference's dataloader moves worker tensors through shared
// memory (python/paddle/io/dataloader/dataloader_iter.py
// `use_shared_memory` + `paddle/fluid/memory/allocation/mmap_allocator.cc`).
// This is the TPU-build equivalent: a POSIX shm segment holding a
// variable-record MPSC ring, synchronized with process-shared pthread
// mutex/condvars so numpy batch payloads never cross a pipe or pickle
// socket.
//
// C ABI (ctypes-bound from paddle_tpu/io/shm_channel.py):
//   shm_ring_create(name, capacity)  -> handle (parent, owns unlink)
//   shm_ring_open(name)              -> handle (workers)
//   shm_ring_write(h, buf, len, timeout_ms) -> 0 ok, -1 timeout, -2 err
//   shm_ring_read_len(h, timeout_ms)        -> next record len, -1/-2
//   shm_ring_read(h, buf, maxlen)           -> record len, -2 err
//   shm_ring_close(h), shm_ring_unlink(name)

#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

namespace {

struct RingHeader {
  pthread_mutex_t mu;
  pthread_cond_t not_empty;
  pthread_cond_t not_full;
  uint64_t capacity;  // data bytes
  uint64_t head;      // read offset  (absolute, monotonically increasing)
  uint64_t tail;      // write offset (absolute)
  uint32_t magic;
};

constexpr uint32_t kMagic = 0x52494e47;  // "RING"

struct Handle {
  RingHeader* hdr;
  uint8_t* data;
  size_t map_len;
  bool owner;
  char name[256];
};

void timeout_to_abs(long timeout_ms, timespec* ts) {
  clock_gettime(CLOCK_REALTIME, ts);
  ts->tv_sec += timeout_ms / 1000;
  ts->tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
}

uint64_t used(const RingHeader* h) { return h->tail - h->head; }

void copy_in(Handle* h, uint64_t at, const uint8_t* src, uint64_t n) {
  uint64_t cap = h->hdr->capacity;
  uint64_t off = at % cap;
  uint64_t first = n < cap - off ? n : cap - off;
  memcpy(h->data + off, src, first);
  if (n > first) memcpy(h->data, src + first, n - first);
}

void copy_out(Handle* h, uint64_t at, uint8_t* dst, uint64_t n) {
  uint64_t cap = h->hdr->capacity;
  uint64_t off = at % cap;
  uint64_t first = n < cap - off ? n : cap - off;
  memcpy(dst, h->data + off, first);
  if (n > first) memcpy(dst + first, h->data, n - first);
}

}  // namespace

extern "C" {

void* shm_ring_create(const char* name, uint64_t capacity) {
  shm_unlink(name);  // stale segment from a crashed run
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  size_t total = sizeof(RingHeader) + capacity;
  if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd,
                   0);
  close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  auto* hdr = reinterpret_cast<RingHeader*>(mem);
  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&hdr->mu, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&hdr->not_empty, &ca);
  pthread_cond_init(&hdr->not_full, &ca);
  hdr->capacity = capacity;
  hdr->head = 0;
  hdr->tail = 0;
  hdr->magic = kMagic;
  auto* h = new Handle;
  h->hdr = hdr;
  h->data = reinterpret_cast<uint8_t*>(mem) + sizeof(RingHeader);
  h->map_len = total;
  h->owner = true;
  strncpy(h->name, name, sizeof(h->name) - 1);
  h->name[sizeof(h->name) - 1] = 0;
  return h;
}

void* shm_ring_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* mem =
      mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* hdr = reinterpret_cast<RingHeader*>(mem);
  if (hdr->magic != kMagic) {
    munmap(mem, st.st_size);
    return nullptr;
  }
  auto* h = new Handle;
  h->hdr = hdr;
  h->data = reinterpret_cast<uint8_t*>(mem) + sizeof(RingHeader);
  h->map_len = st.st_size;
  h->owner = false;
  strncpy(h->name, name, sizeof(h->name) - 1);
  h->name[sizeof(h->name) - 1] = 0;
  return h;
}

static int lock_robust(RingHeader* hdr) {
  int rc = pthread_mutex_lock(&hdr->mu);
  if (rc == EOWNERDEAD) {  // a worker died holding the lock
    pthread_mutex_consistent(&hdr->mu);
    return 0;
  }
  return rc;
}

int shm_ring_write(void* handle, const uint8_t* buf, uint64_t len,
                   long timeout_ms) {
  auto* h = reinterpret_cast<Handle*>(handle);
  RingHeader* hdr = h->hdr;
  uint64_t need = len + 8;
  if (need > hdr->capacity) return -2;
  timespec ts;
  timeout_to_abs(timeout_ms, &ts);
  if (lock_robust(hdr) != 0) return -2;
  while (hdr->capacity - used(hdr) < need) {
    int rc = pthread_cond_timedwait(&hdr->not_full, &hdr->mu, &ts);
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&hdr->mu);
      return -1;
    }
    if (rc != 0 && rc != EOWNERDEAD) {
      pthread_mutex_unlock(&hdr->mu);
      return -2;
    }
  }
  uint64_t lenle = len;
  copy_in(h, hdr->tail, reinterpret_cast<uint8_t*>(&lenle), 8);
  copy_in(h, hdr->tail + 8, buf, len);
  hdr->tail += need;
  pthread_cond_signal(&hdr->not_empty);
  pthread_mutex_unlock(&hdr->mu);
  return 0;
}

long long shm_ring_read_len(void* handle, long timeout_ms) {
  auto* h = reinterpret_cast<Handle*>(handle);
  RingHeader* hdr = h->hdr;
  timespec ts;
  timeout_to_abs(timeout_ms, &ts);
  if (lock_robust(hdr) != 0) return -2;
  while (used(hdr) < 8) {
    int rc = pthread_cond_timedwait(&hdr->not_empty, &hdr->mu, &ts);
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&hdr->mu);
      return -1;
    }
    if (rc != 0 && rc != EOWNERDEAD) {
      pthread_mutex_unlock(&hdr->mu);
      return -2;
    }
  }
  uint64_t len = 0;
  copy_out(h, hdr->head, reinterpret_cast<uint8_t*>(&len), 8);
  pthread_mutex_unlock(&hdr->mu);
  return (long long)len;
}

long long shm_ring_read(void* handle, uint8_t* buf, uint64_t maxlen) {
  auto* h = reinterpret_cast<Handle*>(handle);
  RingHeader* hdr = h->hdr;
  if (lock_robust(hdr) != 0) return -2;
  if (used(hdr) < 8) {
    pthread_mutex_unlock(&hdr->mu);
    return -2;
  }
  uint64_t len = 0;
  copy_out(h, hdr->head, reinterpret_cast<uint8_t*>(&len), 8);
  if (len > maxlen || used(hdr) < 8 + len) {
    pthread_mutex_unlock(&hdr->mu);
    return -2;
  }
  copy_out(h, hdr->head + 8, buf, len);
  hdr->head += 8 + len;
  pthread_cond_signal(&hdr->not_full);
  pthread_mutex_unlock(&hdr->mu);
  return (long long)len;
}

void shm_ring_close(void* handle) {
  auto* h = reinterpret_cast<Handle*>(handle);
  munmap(h->hdr, h->map_len);
  delete h;
}

void shm_ring_unlink(const char* name) { shm_unlink(name); }

}  // extern "C"
