// Out-of-tree custom-op API (parity: the reference's PD_BUILD_OP /
// PD_BUILD_GRAD_OP macros in paddle/phi/api/ext/op_meta_info.h).
//
// TPU-native seam: a custom op is an XLA FFI handler — the same
// custom-call machinery XLA itself uses — so it runs under jit,
// composes with sharding, and needs no framework ABI beyond the
// (stable, versioned) XLA FFI C API. Write the op over ffi::Buffer
// views, bind it, and export it under the pd_op_ prefix; the Python
// side (paddle_tpu.utils.cpp_extension.load_op) discovers every
// exported pd_op_* symbol, registers it with the runtime, and exposes
// a Tensor-in/Tensor-out callable. Exporting pd_op_<name>_grad as
// well wires the backward automatically (inputs... , cotangent) ->
// one gradient per input.
//
//   #include "paddle_ext.h"
//   static ffi::Error ReluImpl(ffi::Buffer<ffi::F32> x,
//                              ffi::ResultBuffer<ffi::F32> y) {
//     for (size_t i = 0; i < x.element_count(); ++i)
//       y->typed_data()[i] = x.typed_data()[i] > 0 ? x.typed_data()[i]
//                                                  : 0.0f;
//     return ffi::Error::Success();
//   }
//   PD_BUILD_OP(my_relu, ReluImpl,
//               ffi::Ffi::Bind().Arg<ffi::Buffer<ffi::F32>>()
//                               .Ret<ffi::Buffer<ffi::F32>>());

#ifndef PADDLE_TPU_EXT_H_
#define PADDLE_TPU_EXT_H_

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;  // NOLINT

#define PD_BUILD_OP(opname, impl, binding) \
  XLA_FFI_DEFINE_HANDLER_SYMBOL(pd_op_##opname, impl, binding)

#define PD_BUILD_GRAD_OP(opname, impl, binding) \
  XLA_FFI_DEFINE_HANDLER_SYMBOL(pd_op_##opname##_grad, impl, binding)

#endif  // PADDLE_TPU_EXT_H_
