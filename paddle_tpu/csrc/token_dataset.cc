// Memory-mapped token dataset reader with threaded prefetch.
//
// Capability parity with the reference's native data pipeline
// (paddle/fluid/framework/data_feed.cc DataFeed / data_set.cc Dataset:
// C++-side file readers feeding trainer threads without the GIL). This is
// the LLM-pretraining IO path: a flat binary file of token ids is mmapped
// and sliced into [batch, seq_len+1] windows (deterministic shuffled order
// per epoch+seed), with a producer thread filling a bounded ring of
// batches so the host->HBM transfer of step N+1 overlaps step N's compute.
//
// Build: g++ -O2 -shared -fPIC -o libpt_data.so token_dataset.cc -lpthread
// ctypes wrapper: paddle_tpu/io/token_dataset.py

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

namespace {

struct Batch {
  std::vector<int32_t> data;  // [batch, seq_len + 1]
};

class TokenDataset {
 public:
  TokenDataset(const char* path, int dtype_bytes, int64_t batch,
               int64_t seq_len, uint64_t seed, int prefetch)
      : dtype_bytes_(dtype_bytes),
        batch_(batch),
        seq_len_(seq_len),
        seed_(seed),
        capacity_(prefetch > 0 ? prefetch : 2) {
    fd_ = ::open(path, O_RDONLY);
    if (fd_ < 0) return;
    struct stat st;
    ::fstat(fd_, &st);
    bytes_ = static_cast<size_t>(st.st_size);
    base_ = ::mmap(nullptr, bytes_, PROT_READ, MAP_PRIVATE, fd_, 0);
    if (base_ == MAP_FAILED) {
      base_ = nullptr;
      return;
    }
    ::madvise(base_, bytes_, MADV_SEQUENTIAL);
    n_tokens_ = static_cast<int64_t>(bytes_ / dtype_bytes_);
    n_windows_ = (n_tokens_ - 1) / seq_len_;
    n_batches_ = n_windows_ / batch_;
    ok_ = n_batches_ > 0;
  }

  bool ok() const { return ok_; }
  int64_t num_batches() const { return n_batches_; }
  int64_t num_tokens() const { return n_tokens_; }

  void start_epoch(int64_t epoch) {
    stop_producer();
    order_.resize(static_cast<size_t>(n_windows_));
    for (int64_t i = 0; i < n_windows_; ++i)
      order_[static_cast<size_t>(i)] = i;
    std::mt19937_64 rng(seed_ + static_cast<uint64_t>(epoch));
    std::shuffle(order_.begin(), order_.end(), rng);
    next_batch_ = 0;
    done_.store(false);
    producer_ = std::thread([this] { produce(); });
  }

  // copies the next [batch, seq_len+1] into out; returns 0 ok, 1 end
  int next(int32_t* out) {
    std::unique_lock<std::mutex> lk(mu_);
    not_empty_.wait(lk, [&] { return !queue_.empty() || done_.load(); });
    if (queue_.empty()) return 1;
    Batch b = std::move(queue_.front());
    queue_.pop_front();
    lk.unlock();
    not_full_.notify_one();
    std::memcpy(out, b.data.data(), b.data.size() * sizeof(int32_t));
    return 0;
  }

  ~TokenDataset() {
    stop_producer();
    if (base_ != nullptr) ::munmap(base_, bytes_);
    if (fd_ >= 0) ::close(fd_);
  }

 private:
  int64_t token_at(int64_t idx) const {
    const char* p = static_cast<const char*>(base_) + idx * dtype_bytes_;
    switch (dtype_bytes_) {
      case 2: {
        uint16_t v;
        std::memcpy(&v, p, 2);
        return v;
      }
      case 4: {
        int32_t v;
        std::memcpy(&v, p, 4);
        return v;
      }
      default: {
        uint8_t v;
        std::memcpy(&v, p, 1);
        return v;
      }
    }
  }

  void produce() {
    const int64_t w = seq_len_ + 1;
    for (int64_t bi = 0; bi < n_batches_ && !quit_.load(); ++bi) {
      Batch b;
      b.data.resize(static_cast<size_t>(batch_ * w));
      for (int64_t r = 0; r < batch_; ++r) {
        int64_t window = order_[static_cast<size_t>(bi * batch_ + r)];
        int64_t start = window * seq_len_;
        for (int64_t t = 0; t < w; ++t)
          b.data[static_cast<size_t>(r * w + t)] =
              static_cast<int32_t>(token_at(start + t));
      }
      std::unique_lock<std::mutex> lk(mu_);
      not_full_.wait(lk, [&] {
        return queue_.size() < capacity_ || quit_.load();
      });
      if (quit_.load()) break;
      queue_.push_back(std::move(b));
      lk.unlock();
      not_empty_.notify_one();
    }
    done_.store(true);
    not_empty_.notify_all();
  }

  void stop_producer() {
    quit_.store(true);
    not_full_.notify_all();
    not_empty_.notify_all();
    if (producer_.joinable()) producer_.join();
    quit_.store(false);
    std::lock_guard<std::mutex> lk(mu_);
    queue_.clear();
  }

  int fd_ = -1;
  void* base_ = nullptr;
  size_t bytes_ = 0;
  int dtype_bytes_;
  int64_t batch_, seq_len_;
  uint64_t seed_;
  size_t capacity_;
  bool ok_ = false;
  int64_t n_tokens_ = 0, n_windows_ = 0, n_batches_ = 0;
  std::vector<int64_t> order_;
  int64_t next_batch_ = 0;
  std::deque<Batch> queue_;
  std::mutex mu_;
  std::condition_variable not_empty_, not_full_;
  std::thread producer_;
  std::atomic<bool> done_{false}, quit_{false};
};

}  // namespace

extern "C" {

void* pt_dataset_open(const char* path, int dtype_bytes, int64_t batch,
                      int64_t seq_len, uint64_t seed, int prefetch) {
  auto* d = new TokenDataset(path, dtype_bytes, batch, seq_len, seed,
                             prefetch);
  if (!d->ok()) {
    delete d;
    return nullptr;
  }
  return d;
}

int64_t pt_dataset_num_batches(void* ds) {
  return static_cast<TokenDataset*>(ds)->num_batches();
}

int64_t pt_dataset_num_tokens(void* ds) {
  return static_cast<TokenDataset*>(ds)->num_tokens();
}

void pt_dataset_start_epoch(void* ds, int64_t epoch) {
  static_cast<TokenDataset*>(ds)->start_epoch(epoch);
}

int pt_dataset_next(void* ds, int32_t* out) {
  return static_cast<TokenDataset*>(ds)->next(out);
}

void pt_dataset_close(void* ds) { delete static_cast<TokenDataset*>(ds); }

}  // extern "C"
