"""`paddle.autograd` surface (reference: python/paddle/autograd/)."""

from ..core.autograd import backward, grad, no_grad, enable_grad  # noqa: F401
from .py_layer import PyLayer, PyLayerContext  # noqa: F401
from .saved_tensors_hooks import saved_tensors_hooks  # noqa: F401
from .functional import jacobian, hessian  # noqa: F401


def is_checkpoint_valid():
    return True


from ..core.autograd import is_grad_enabled  # noqa: F401,E402


class set_grad_enabled:
    """Context manager / function toggling grad recording (reference
    autograd/__init__.py set_grad_enabled)."""

    def __init__(self, mode):
        from ..core import autograd as _ag

        self._prev = _ag.is_grad_enabled()
        _ag._set_grad_enabled(bool(mode))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        from ..core import autograd as _ag

        _ag._set_grad_enabled(self._prev)
        return False
