"""`paddle.autograd` surface (reference: python/paddle/autograd/)."""

from ..core.autograd import backward, grad, no_grad, enable_grad  # noqa: F401
from .py_layer import PyLayer, PyLayerContext  # noqa: F401
from .saved_tensors_hooks import saved_tensors_hooks  # noqa: F401


def is_checkpoint_valid():
    return True
