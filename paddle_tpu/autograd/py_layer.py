"""PyLayer: user-defined autograd ops.

Parity: reference `paddle/fluid/eager/pylayer/` + python
python/paddle/autograd/py_layer.py — static forward/backward with a ctx
carrying saved tensors. The recorded tape Node's vjp_fn simply invokes the
user's backward; saved tensors are real Tensors (and under jit tracing
they hold tracers, so PyLayers compile into the XLA program too — this is
how recompute and the TP comm layers stay jittable).
"""

from __future__ import annotations

from ..core.autograd import Node, is_grad_enabled, no_grad
from ..core.tensor import Tensor


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = tensors

    def saved_tensor(self):
        return self._saved

    # paddle also exposes mark_not_inplace / set_materialize_grads; accept
    def mark_not_inplace(self, *tensors):
        self.not_inplace_tensors = tensors

    def set_materialize_grads(self, value):
        self._materialize = value


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        # record whenever grad is enabled (paddle PyLayer semantics): the
        # user backward may route grads to closed-over parameters even if
        # no direct tensor input requires grad (e.g. recompute)
        recording = is_grad_enabled()

        with no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)

        single = not isinstance(outputs, (tuple, list))
        out_list = [outputs] if single else list(outputs)

        if recording:
            out_meta = [(tuple(o._data.shape), o._data.dtype)
                        for o in out_list if isinstance(o, Tensor)]

            def vjp_fn(cotangents):
                cts = [Tensor(c) for c in cotangents]
                with no_grad():
                    in_grads = cls.backward(ctx, *cts)
                if not isinstance(in_grads, (tuple, list)):
                    in_grads = (in_grads,)
                arrays = []
                gi = iter(in_grads)
                for t in tensor_inputs:
                    g = next(gi, None)
                    arrays.append(None if g is None else
                                  (g._data if isinstance(g, Tensor) else g))
                import jax.numpy as jnp
                return tuple(
                    jnp.zeros(t._data.shape, t._data.dtype) if a is None
                    else a for t, a in zip(tensor_inputs, arrays))

            def tensor_vjp(ct_tensors):
                # create_graph path: run the user's backward with recording
                # ON — differentiable iff the backward is built from
                # differentiable Tensor ops (reference composite-VJP rule)
                in_grads = cls.backward(ctx, *ct_tensors)
                if not isinstance(in_grads, (tuple, list)):
                    in_grads = (in_grads,)
                return list(in_grads)

            node = Node(vjp_fn, tensor_inputs, out_meta, name=cls.__name__,
                        tensor_vjp=tensor_vjp)
            idx = 0
            for o in out_list:
                if isinstance(o, Tensor):
                    from ..core.dtype import is_floating_point
                    if is_floating_point(o.dtype):
                        o.stop_gradient = False
                        o._node = node
                        o._out_idx = idx
                    idx += 1
        return out_list[0] if single else tuple(out_list)


class LegacyPyLayer(PyLayer):
    pass
