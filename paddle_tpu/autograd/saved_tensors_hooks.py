"""saved_tensors_hooks (reference: python/paddle/autograd/
saved_tensors_hooks.py) — pack/unpack hooks for activation memory control
(the reference's offload-recompute building block)."""

from __future__ import annotations

import threading


class _HookState(threading.local):
    def __init__(self):
        self.pack = None
        self.unpack = None


_state = _HookState()


def current_hooks():
    return _state.pack, _state.unpack


class saved_tensors_hooks:
    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        self._prev = (_state.pack, _state.unpack)
        _state.pack = self.pack_hook
        _state.unpack = self.unpack_hook
        return self

    def __exit__(self, *exc):
        _state.pack, _state.unpack = self._prev
        return False
