"""`paddle.autograd.jacobian` / `hessian` (reference:
python/paddle/autograd/autograd.py:450,544 — lazy Jacobian/Hessian
objects over double-grad).

TPU-first: two entry forms.
- ``jacobian(ys, xs)`` with computed Tensors walks the eager tape with
  one-hot cotangents (a row per output element) — exact, first-order.
- ``jacobian(func, xs)`` / ``hessian(func, xs)`` with a CALLABLE traces
  the pure function with jax.jacrev / jax.hessian — the XLA-native way
  to get higher-order derivatives (the reference builds a double-grad
  graph; under JAX, composition of transforms replaces graph surgery).
Tensor-form ``hessian`` runs grad-of-grad on the tape:
``grad(create_graph=True)`` records the first backward differentiably,
then one-hot tape jacobians over the grads build the Hessian blocks.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.autograd import grad as _tape_grad
from ..core.tensor import Tensor

__all__ = ["jacobian", "hessian"]


class _Matrix:
    """Lazy matrix facade (reference returns Jacobian/Hessian objects
    that compute on indexing; here the matrix is materialized eagerly
    and indexing/slicing just views it)."""

    def __init__(self, arr):
        self._arr = arr

    def __getitem__(self, item):
        return Tensor(self._arr[item])

    @property
    def shape(self):
        return list(self._arr.shape)

    def numpy(self):
        return np.asarray(self._arr)

    def __repr__(self):
        return f"Jacobian(shape={list(self._arr.shape)})"


def _as_tuple(x):
    return tuple(x) if isinstance(x, (tuple, list)) else (x,)


def _tape_jacobian_single(y, x, batch_axis):
    # Cotangent seeds must match y's dtype (float64 under x64, bf16 under
    # autocast); hardcoding float32 would hand jax.vjp a mismatched seed.
    y_dt = np.dtype(jnp.asarray(y._data).dtype)
    x_dt = np.dtype(jnp.asarray(x._data).dtype)
    rows = []
    if batch_axis is None:
        y_flat_len = int(np.prod(y.shape)) if y.shape else 1
        for i in range(y_flat_len):
            seed = np.zeros(y.shape or (1,), y_dt)
            seed.reshape(-1)[i] = 1.0
            (g,) = _tape_grad([y], [x],
                              grad_outputs=[Tensor(seed.reshape(
                                  y.shape or ()), dtype=y_dt)],
                              retain_graph=True, allow_unused=True)
            rows.append(np.zeros(x.shape, x_dt)
                        if g is None else np.asarray(g.numpy()))
        arr = np.stack([r.reshape(-1) for r in rows], 0)
        return _Matrix(arr)
    # batch form: xs [B, N], ys [B, M] -> [B, M, N]
    B = y.shape[batch_axis]
    M = int(np.prod(y.shape)) // B
    out = []
    for i in range(M):
        seed = np.zeros((B, M), y_dt)
        seed[:, i] = 1.0
        (g,) = _tape_grad([y], [x],
                          grad_outputs=[Tensor(seed.reshape(y.shape),
                                               dtype=y_dt)],
                          retain_graph=True, allow_unused=True)
        out.append(np.zeros(x.shape, x_dt)
                   if g is None else np.asarray(g.numpy()))
    arr = np.stack([r.reshape(B, -1) for r in out], 1)  # [B, M, N]
    return _Matrix(arr)


def jacobian(ys, xs, batch_axis=None):
    """d(ys)/d(xs) (reference autograd.py:450). ``ys`` may be computed
    Tensors (tape walk) or a callable (jax.jacrev on the pure fn)."""
    if batch_axis not in (None, 0):
        raise ValueError(
            f"batch_axis must be None or 0 (the reference supports only "
            f"leading batch), got {batch_axis}")
    if callable(ys) and not isinstance(ys, Tensor):
        func = ys
        xs_t = _as_tuple(xs)
        arrs = [jnp.asarray(x._data if isinstance(x, Tensor) else x)
                for x in xs_t]

        def pure(*a):
            out = func(*[Tensor(v) for v in a])
            return out._data if isinstance(out, Tensor) else out

        jac = jax.jacrev(pure, argnums=tuple(range(len(arrs))))(*arrs)
        mats = tuple(_Matrix(np.asarray(j)) for j in jac)
        return mats if isinstance(xs, (tuple, list)) else mats[0]

    ys_t, xs_t = _as_tuple(ys), _as_tuple(xs)
    out = tuple(tuple(_tape_jacobian_single(y, x, batch_axis)
                      for x in xs_t) for y in ys_t)
    if not isinstance(ys, (tuple, list)):
        out = out[0]
        if not isinstance(xs, (tuple, list)):
            return out[0]
        return out
    if not isinstance(xs, (tuple, list)):
        return tuple(row[0] for row in out)
    return out


def hessian(ys, xs, batch_axis=None):
    """d²(ys)/d(xs)² (reference autograd.py:544). Pass a CALLABLE to get
    the exact Hessian via jax.hessian; Tensor-form would need the tape to
    record grad-of-grad, which the eager tape does not (raises)."""
    if callable(ys) and not isinstance(ys, Tensor):
        func = ys
        xs_t = _as_tuple(xs)
        arrs = [jnp.asarray(x._data if isinstance(x, Tensor) else x)
                for x in xs_t]

        def pure(*a):
            out = func(*[Tensor(v) for v in a])
            out = out._data if isinstance(out, Tensor) else out
            if out.size != 1:
                raise ValueError(
                    f"hessian expects a scalar-output function (the "
                    f"reference requires a 1-element ys), got output "
                    f"shape {tuple(out.shape)}")
            return jnp.sum(out)

        hes = jax.hessian(pure, argnums=tuple(range(len(arrs))))(*arrs)
        if isinstance(xs, (tuple, list)):
            return tuple(tuple(_Matrix(np.asarray(hes[i][j]))
                               for j in range(len(arrs)))
                         for i in range(len(arrs)))
        return _Matrix(np.asarray(hes[0][0]))

    # Tensor form: double-backward on the eager tape —
    # grad(create_graph=True) records the first backward differentiably,
    # then a one-hot tape jacobian over each grad gives the Hessian rows
    # (reference: GeneralGrad double-grad, fluid/eager/backward.cc:439).
    y = ys[0] if isinstance(ys, (tuple, list)) else ys
    if batch_axis is None:
        if int(np.prod(y.shape)) != 1:
            raise ValueError(
                f"hessian expects a scalar (1-element) ys, got shape "
                f"{y.shape}; for per-sample scalars pass batch_axis=0")
    else:
        n_per = int(np.prod(y.shape)) // y.shape[batch_axis] \
            if y.shape else 1
        if n_per != 1:
            raise ValueError(
                f"batched hessian expects per-sample SCALAR ys "
                f"([B] or [B, 1]), got shape {y.shape}")
    xs_t = _as_tuple(xs)
    seed = None
    if batch_axis is not None and int(np.prod(y.shape)) != 1:
        # per-sample scalars: ones seed (samples are independent, so the
        # batched Hessian blocks are exact)
        seed = [Tensor(np.ones(y.shape,
                               np.dtype(jnp.asarray(y._data).dtype)))]
    grads = _tape_grad([y], list(xs_t), grad_outputs=seed,
                       create_graph=True, retain_graph=True,
                       allow_unused=True)
    rows = []
    for gi, xi in zip(grads, xs_t):
        row = []
        for xj in xs_t:
            if gi is None:
                ni = int(np.prod(xi.shape)) if xi.shape else 1
                nj = int(np.prod(xj.shape)) if xj.shape else 1
                row.append(_Matrix(np.zeros((ni, nj),
                                            np.dtype(xj._data.dtype))))
            else:
                row.append(_tape_jacobian_single(gi, xj, batch_axis))
        rows.append(tuple(row))
    if isinstance(xs, (tuple, list)):
        return tuple(rows)
    return rows[0][0]
