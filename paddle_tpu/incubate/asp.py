"""ASP: 2:4 structured sparsity.

Parity: reference `python/paddle/incubate/asp/` — calculate_density,
prune_model (2:4 masks on Linear weights), `decorate(optimizer)` keeping
masks applied after each update (ASPHelper). TPU note: XLA has no sparse
tensor-core path, so this provides the *workflow* (mask computation and
maintenance); the compressed speedup story on TPU is int8/int4 quant.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import nn
from ..core.tensor import Tensor

__all__ = ["calculate_density", "prune_model", "decorate",
           "reset_excluded_layers", "set_excluded_layers"]

_masks: dict[int, jnp.ndarray] = {}
_excluded: set = set()


def calculate_density(x):
    arr = x.numpy() if isinstance(x, Tensor) else np.asarray(x)
    return float((arr != 0).sum() / arr.size)


def _mask_2_4(w):
    """Keep the 2 largest-|.| of every 4 along the last axis."""
    shape = w.shape
    flat = w.reshape(-1, 4) if shape[-1] % 4 == 0 else None
    if flat is None:
        return jnp.ones_like(w)
    idx = jnp.argsort(jnp.abs(flat), axis=1)
    mask = jnp.ones_like(flat)
    rows = jnp.arange(flat.shape[0])
    mask = mask.at[rows, idx[:, 0]].set(0.0)
    mask = mask.at[rows, idx[:, 1]].set(0.0)
    return mask.reshape(shape)


def set_excluded_layers(param_names, main_program=None):
    _excluded.update(param_names)


def reset_excluded_layers(main_program=None):
    _excluded.clear()


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Apply 2:4 masks to weights of Linear layers (reference
    prune_model)."""
    for lname, layer in model.named_sublayers(include_self=True):
        if not isinstance(layer, nn.Linear):
            continue
        p = layer.weight
        if (p.name or lname + ".weight") in _excluded:
            continue
        mask = _mask_2_4(p._data)
        p._rebind(p._data * mask)
        _masks[id(p)] = mask
    return _masks


def decorate(optimizer):
    """Wrap optimizer.step to re-apply masks after each update (the
    reference's OptimizerWithSparsityGuarantee)."""
    orig_step = optimizer.step

    def step():
        orig_step()
        for p in optimizer._parameter_list:
            mask = _masks.get(id(p))
            if mask is not None:
                p._rebind(p._data * mask)

    optimizer.step = step
    return optimizer
