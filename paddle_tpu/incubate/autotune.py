"""`paddle.incubate.autotune` (reference:
python/paddle/incubate/autotune.py set_config — kernel / layout /
dataloader auto-tuning switches).

TPU mapping:
- kernel: enables the Pallas flash-attention block-size sweep
  (kernels/pallas/flash_attention._AUTOTUNE) — the exhaustive-search
  analogue of the reference's cuDNN algorithm cache.
- layout: XLA's layout assignment already auto-tunes layouts per target;
  the switch is recorded for API parity.
- dataloader: recorded; the multiprocess DataLoader sizes its worker
  pool from num_workers directly.
"""

from __future__ import annotations

import json

__all__ = ["set_config"]

_CONFIG = {"kernel": {"enable": False},
           "layout": {"enable": False},
           "dataloader": {"enable": False}}


def set_config(config=None):
    """Configure auto-tuning (reference incubate/autotune.py:47). Accepts
    a dict, a path to a JSON file, or None (enable everything)."""
    from ..kernels.pallas import flash_attention as _fa
    if config is None:
        cfg = {k: {"enable": True} for k in _CONFIG}
    elif isinstance(config, str):
        with open(config) as f:
            cfg = json.load(f)
    elif isinstance(config, dict):
        cfg = config
    else:
        raise TypeError(
            f"set_config expects dict, json path or None, got "
            f"{type(config).__name__}")
    for key, val in cfg.items():
        if key not in _CONFIG:
            raise ValueError(f"unknown autotune domain {key!r}; "
                             f"expected one of {sorted(_CONFIG)}")
        if isinstance(val, dict):
            _CONFIG[key].update(val)
        else:
            _CONFIG[key]["enable"] = bool(val)
    _fa._AUTOTUNE["enable"] = bool(_CONFIG["kernel"].get("enable"))


def get_config():
    return {k: dict(v) for k, v in _CONFIG.items()}
