"""Incubate optimizers (reference: python/paddle/incubate/optimizer/ —
LookAhead, ModelAverage, GradientMergeOptimizer; DistributedFusedLamb is
plain Lamb under GSPMD sharding)."""

from __future__ import annotations

import jax.numpy as jnp

from ..optimizer import Lamb
from ..optimizer.optimizer import Optimizer

__all__ = ["LookAhead", "ModelAverage", "GradientMerge",
           "DistributedFusedLamb"]


class LookAhead(Optimizer):
    """k steps of the inner optimizer, then interpolate toward the slow
    weights (reference lookahead.py)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._step_count = 0
        # snapshot slow weights when training starts (reference
        # lookahead.py), so the first k-step sync pulls fast weights back
        # toward the initial point instead of being a no-op
        self._slow = {id(p): p._data.astype(jnp.float32)
                      for p in inner_optimizer._parameter_list}
        self._groups = inner_optimizer._groups
        self._grad_clip = None
        self._lr_scheduler = inner_optimizer._lr_scheduler
        self._lr = inner_optimizer._lr
        self._state = inner_optimizer._state
        self._global_step = 0
        self._multi_precision = inner_optimizer._multi_precision

    def step(self):
        self.inner.step()
        self._step_count += 1
        if self._step_count % self.k != 0:
            return
        for p in self.inner._parameter_list:
            key = id(p)
            if key not in self._slow:  # param added after construction
                self._slow[key] = p._data.astype(jnp.float32)
            slow = self._slow[key] + self.alpha * (
                p._data.astype(jnp.float32) - self._slow[key])
            self._slow[key] = slow
            p._rebind(slow.astype(p._data.dtype))

    def clear_grad(self, set_to_zero=False):
        self.inner.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def get_lr(self):
        return self.inner.get_lr()

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()

    def state_dict(self):
        return self.inner.state_dict()

    def set_state_dict(self, sd):
        return self.inner.set_state_dict(sd)


class ModelAverage(Optimizer):
    """EMA-style parameter averaging window (reference
    modelaverage.py); apply()/restore() swap averaged params in and out."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        super().__init__(0.0, parameters)
        self._sums = {id(p): jnp.zeros(p._data.shape, jnp.float32)
                      for p in self._parameter_list}
        self._counts = 0
        self._backup = {}

    def step(self):
        self._counts += 1
        for p in self._parameter_list:
            self._sums[id(p)] = self._sums[id(p)] + \
                p._data.astype(jnp.float32)

    def apply(self, executor=None, need_restore=True):
        for p in self._parameter_list:
            self._backup[id(p)] = p._data
            avg = self._sums[id(p)] / max(self._counts, 1)
            p._rebind(avg.astype(p._data.dtype))

    def restore(self, executor=None):
        for p in self._parameter_list:
            if id(p) in self._backup:
                p._rebind(self._backup.pop(id(p)))


class GradientMerge:
    """Accumulate grads over k micro-steps before the inner step
    (reference gradient_merge.py / meta optimizer)."""

    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        self.inner = inner_optimizer
        self.k = k_steps
        self.avg = avg
        self._count = 0

    def step(self):
        self._count += 1
        if self._count % self.k != 0:
            return  # keep accumulating (.grad already sums)
        if self.avg and self.k > 1:
            for p in self.inner._parameter_list:
                if p.grad is not None:
                    p.grad._rebind(p.grad._data / self.k)
        self.inner.step()
        self.inner.clear_grad()

    def clear_grad(self, set_to_zero=False):
        if self._count % self.k == 0:
            self.inner.clear_grad(set_to_zero)

    def __getattr__(self, name):
        return getattr(self.inner, name)


class DistributedFusedLamb(Lamb):
    """reference distributed_fused_lamb.py: under GSPMD-sharded params and
    grads the plain Lamb update IS distributed+fused — XLA partitions the
    trust-ratio norms with the same collectives the CUDA kernel issues."""

    pass
