"""`paddle.incubate` (reference: python/paddle/incubate/)."""

from . import nn  # noqa: F401
from ..core.autograd import no_grad  # noqa: F401
