"""`paddle.incubate` (reference: python/paddle/incubate/)."""

from . import nn  # noqa: F401
from . import asp  # noqa: F401
from . import optimizer  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401
from ..core.autograd import no_grad  # noqa: F401
