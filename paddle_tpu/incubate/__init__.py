"""`paddle.incubate` (reference: python/paddle/incubate/)."""

from . import nn  # noqa: F401
from . import asp  # noqa: F401
from . import autotune  # noqa: F401
from . import optimizer  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401
from ..core.autograd import no_grad  # noqa: F401
from ..geometric import (  # noqa: F401,E402
    segment_max, segment_mean, segment_min, segment_sum,
)
from ..geometric import (  # noqa: F401,E402
    reindex_graph as graph_reindex,
    sample_neighbors as graph_sample_neighbors,
)
from ..geometric import send_u_recv as _send_u_recv  # noqa: E402


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    """Legacy-name alias (reference incubate.graph_send_recv):
    ``pool_type`` maps to geometric.send_u_recv's ``reduce_op``."""
    return _send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                        out_size=out_size)
from .. import inference  # noqa: F401,E402


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Multi-hop neighbor sampling (reference incubate
    graph_khop_sampler): chain of per-hop sample_neighbors + reindex."""
    import numpy as np

    from ..core.dispatch import unwrap
    from ..core.tensor import Tensor
    from ..geometric import reindex_graph, sample_neighbors

    nodes = input_nodes
    all_src, all_dst = [], []
    frontier = nodes
    for k in sample_sizes:
        nb, cnt = sample_neighbors(row, colptr, frontier, sample_size=k)
        rs, rd, out_nodes = reindex_graph(frontier, nb, cnt)
        all_src.append(np.asarray(unwrap(nb)))
        all_dst.append(np.repeat(
            np.asarray(unwrap(frontier)).reshape(-1),
            np.asarray(unwrap(cnt))))
        frontier = out_nodes
    edge_src = Tensor(np.concatenate(all_src).astype(np.int64)
                      if all_src else np.zeros(0, np.int64))
    edge_dst = Tensor(np.concatenate(all_dst).astype(np.int64)
                      if all_dst else np.zeros(0, np.int64))
    # compact the union of touched nodes
    rs, rd, sample_index = reindex_graph(input_nodes, edge_src,
                                         Tensor(np.asarray(
                                             [len(np.asarray(
                                                 unwrap(edge_src)))],
                                             np.int64)))
    return edge_src, edge_dst, sample_index, None


def identity_loss(x, reduction="none"):
    """Reference incubate.identity_loss (IPU training marker): the value
    passes through (with optional reduction)."""
    if reduction in (0, "sum"):
        return x.sum()
    if reduction in (1, "mean"):
        return x.mean()
    return x


def softmax_mask_fuse(x, mask, name=None):
    """Fused softmax(x + mask) (reference incubate.softmax_mask_fuse)."""
    import jax

    from ..core.dispatch import apply

    return apply(lambda a, m: jax.nn.softmax(a + m, axis=-1), x, mask,
                 name="softmax_mask_fuse")


def softmax_mask_fuse_upper_triangle(x, name=None):
    """Fused causal-masked softmax (reference
    softmax_mask_fuse_upper_triangle)."""
    import jax
    import jax.numpy as jnp

    from ..core.dispatch import apply

    def fn(a):
        s = a.shape[-1]
        mask = jnp.tril(jnp.ones((s, s), bool))
        return jax.nn.softmax(jnp.where(mask, a, -1e30), axis=-1)
    return apply(fn, x, name="softmax_mask_fuse_upper_triangle")
