"""Fused-op functional surface.

Parity: reference `python/paddle/incubate/nn/functional/` — the python API
over the CUDA fusion library (paddle/phi/kernels/fusion/,
paddle/fluid/operators/fused/; SURVEY.md §2.1 "fused LLM mega-ops").

TPU-first: these are NOT separate kernels — each is the composition XLA
already fuses (plus Pallas flash attention where it matters). The API
exists so reference users keep their call sites; the performance parity
comes from the compiler, which is the whole point of the redesign.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ....core.dispatch import apply, unwrap
from ....core.tensor import Tensor
from ....nn import functional as F

__all__ = [
    "fused_rms_norm", "fused_layer_norm", "fused_rotary_position_embedding",
    "swiglu", "fused_bias_act", "fused_linear", "fused_linear_activation",
    "fused_multi_head_attention", "fused_feedforward",
    "variable_length_memory_efficient_attention",
    "masked_multihead_attention", "fused_dropout_add",
    "fused_matmul_bias", "fused_bias_dropout_residual_layer_norm",
    "fused_dot_product_attention", "cudnn_flash_attention",
    "block_multihead_attention", "block_multihead_attention_xpu",
    "fused_multi_transformer",
]


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, bias=None, residual=None,
                   quant_scale=-1, **kw):
    """reference fused_rms_norm.py (phi fused_rms_norm kernel). Supports
    the residual+bias pre-add variant; returns (out, residual_out) when a
    residual is passed (kernel parity)."""
    if bias is not None:
        x = x + bias
    if residual is not None:
        x = x + residual
        residual_out = x
    out = F.rms_norm(x, norm_weight, epsilon)
    if norm_bias is not None:
        out = out + norm_bias
    if residual is not None:
        return out, residual_out
    return out


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=-1, bias=None, residual=None, **kw):
    if bias is not None:
        x = x + bias
    if residual is not None:
        x = x + residual
        residual_out = x
    shape = x.shape[begin_norm_axis:] if begin_norm_axis != -1 \
        else x.shape[-1:]
    out = F.layer_norm(x, list(shape), norm_weight, norm_bias, epsilon)
    if residual is not None:
        return out, residual_out
    return out


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0):
    """reference fused_rope (fused_ops.yaml:408). q/k/v: [b, s, h, d].
    When sin/cos are None they are computed from rotary_emb_base."""

    def rope(x, sin_a, cos_a):
        if use_neox_rotary_style:
            d2 = x.shape[-1] // 2
            x1, x2 = x[..., :d2], x[..., d2:]
            rotated = jnp.concatenate([-x2, x1], axis=-1)
            return x * cos_a + rotated * sin_a
        x1 = x[..., 0::2]
        x2 = x[..., 1::2]
        cos_h = cos_a[..., 0::2]
        sin_h = sin_a[..., 0::2]
        o1 = x1 * cos_h - x2 * sin_h
        o2 = x2 * cos_h + x1 * sin_h
        return jnp.stack([o1, o2], axis=-1).reshape(x.shape)

    def fn(qa, *rest):
        arrs = [qa] + list(rest[:sum(t is not None for t in (k, v))])
        d = qa.shape[-1]
        s = qa.shape[1]
        if sin is None:
            inv = 1.0 / (rotary_emb_base **
                         (jnp.arange(0, d, 2, jnp.float32) / d))
            pos = jnp.arange(s, dtype=jnp.float32)
            freqs = jnp.outer(pos, inv)
            if use_neox_rotary_style:
                emb = jnp.concatenate([freqs, freqs], axis=-1)
            else:
                emb = jnp.repeat(freqs, 2, axis=-1)
            sin_a = jnp.sin(emb)[None, :, None, :]
            cos_a = jnp.cos(emb)[None, :, None, :]
        else:
            sin_a, cos_a = unwrap(sin), unwrap(cos)
            if sin_a.ndim == 2:
                sin_a = sin_a[None, :, None, :]
                cos_a = cos_a[None, :, None, :]
        outs = tuple(rope(a.astype(jnp.float32), sin_a, cos_a).astype(
            a.dtype) for a in arrs)
        return outs if len(outs) > 1 else outs[0]

    args = [q] + [t for t in (k, v) if t is not None]
    out = apply(fn, *args, name="fused_rope")
    if k is None and v is None:
        return out, None, None
    outs = list(out) if isinstance(out, list) else [out]
    while len(outs) < 3:
        outs.append(None)
    return tuple(outs)


def swiglu(x, y=None, name=None):
    return F.swiglu(x, y)


def fused_bias_act(x, bias=None, dequant_scales=None, shift=None,
                   smooth=None, act_method="gelu", **kw):
    if bias is not None:
        x = x + bias
    act = {"gelu": lambda a: F.gelu(a, approximate=True),
           "relu": F.relu, "silu": F.silu,
           "swiglu": lambda a: F.swiglu(a),
           "geglu": lambda a: F.glu(a)}.get(act_method)
    if act is None:
        raise ValueError(f"unknown act_method {act_method!r}")
    return act(x)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """reference fused_gemm_epilogue (cublasLt). XLA fuses bias+epilogue."""
    from .... import ops
    out = ops.matmul(x, weight, transpose_y=transpose_weight)
    if bias is not None:
        out = out + bias
    return out


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation="gelu"):
    from .... import ops
    out = ops.matmul(x, y, transpose_x=trans_x, transpose_y=trans_y) + bias
    if activation == "gelu":
        return F.gelu(out, approximate=True)
    if activation == "relu":
        return F.relu(out)
    return out


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    return F.dropout(x, p=p, training=training, mode=mode) + y


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.0,
                               attn_dropout_rate=0.0, ln_epsilon=1e-5,
                               training=True, mode="upscale_in_train",
                               ring_id=-1, add_residual=True, name=None,
                               num_heads=None, transpose_qkv_wb=False):
    """reference fused_attention_op.cu capability: [pre-LN +] QKV matmul +
    MHA + out proj [+ residual + post-LN] as one call."""
    from .... import ops
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, [x.shape[-1]], pre_ln_scale, pre_ln_bias,
                         pre_ln_epsilon)
    b, s, d = x.shape
    if transpose_qkv_wb:
        qkv = ops.matmul(x, qkv_weight)  # [b,s,3d]
        nh = num_heads
        qkv = ops.reshape(qkv, [b, s, 3, nh, d // nh])
    else:
        # qkv_weight [3, nh, head_dim, d]
        nh = qkv_weight.shape[1]
        w = ops.reshape(qkv_weight, [3 * d, d])
        qkv = ops.matmul(x, w, transpose_y=True)
        qkv = ops.reshape(qkv, [b, s, 3, nh, d // nh])
    if qkv_bias is not None:
        qkv = qkv + ops.reshape(qkv_bias, [1, 1, 3, nh, d // nh])
    q, kk, v = ops.unbind(qkv, axis=2)
    out = F.scaled_dot_product_attention(
        q, kk, v, attn_mask=attn_mask,
        dropout_p=attn_dropout_rate if training else 0.0)
    out = ops.reshape(out, [b, s, d])
    out = ops.matmul(out, linear_weight)
    if linear_bias is not None:
        out = out + linear_bias
    out = F.dropout(out, p=dropout_rate, training=training, mode=mode)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, [out.shape[-1]], ln_scale, ln_bias,
                           ln_epsilon)
    return out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, ring_id=-1,
                      mode="upscale_in_train", name=None):
    """reference fused_feedforward_op.cu capability."""
    from .... import ops
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, [x.shape[-1]], ln1_scale, ln1_bias,
                         ln1_epsilon)
    h = ops.matmul(x, linear1_weight)
    if linear1_bias is not None:
        h = h + linear1_bias
    h = getattr(F, activation)(h)
    h = F.dropout(h, p=dropout1_rate, training=training, mode=mode)
    h = ops.matmul(h, linear2_weight)
    if linear2_bias is not None:
        h = h + linear2_bias
    h = F.dropout(h, p=dropout2_rate, training=training, mode=mode)
    out = residual + h
    if not pre_layer_norm:
        out = F.layer_norm(out, [out.shape[-1]], ln2_scale, ln2_bias,
                           ln2_epsilon)
    return out


def variable_length_memory_efficient_attention(query, key, value, seq_lens=None,
                                               kv_seq_lens=None, mask=None,
                                               scale=None, causal=False,
                                               pre_cache_length=0):
    """reference memory_efficient_attention (cutlass) capability: SDPA on
    [b, h, s, d] layout with optional mask."""
    from .... import ops
    q = ops.transpose(query, [0, 2, 1, 3])
    k = ops.transpose(key, [0, 2, 1, 3])
    v = ops.transpose(value, [0, 2, 1, 3])
    out = F.scaled_dot_product_attention(q, k, v, attn_mask=mask,
                                         is_causal=causal)
    return ops.transpose(out, [0, 2, 1, 3])


def masked_multihead_attention(x, cache_kv=None, bias=None, src_mask=None,
                               sequence_lengths=None, rotary_tensor=None,
                               beam_cache_offset=None, qkv_out_scale=None,
                               out_shift=None, out_smooth=None, seq_len=1,
                               rotary_emb_dims=0, use_neox_rotary_style=False,
                               compute_dtype="default", **kw):
    """reference masked_multihead_attention_kernel.cu: single-token decode
    attention against a [2, b, h, max_s, d] KV cache; returns
    (out, updated_cache)."""

    def fn(xa, cache):
        b = xa.shape[0]
        two, _, h, max_s, d = cache.shape
        qkv = xa.reshape(b, 3, h, d)
        q, knew, vnew = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        if sequence_lengths is not None:
            cur = unwrap(sequence_lengths).reshape(-1)[0]
        else:
            cur = jnp.sum(
                jnp.any(cache[0, 0, 0] != 0, axis=-1).astype(jnp.int32))
        z = jnp.int32(0)
        cur32 = jnp.asarray(cur, jnp.int32)
        cache_k = jax.lax.dynamic_update_slice(
            cache[0], knew[:, :, None, :], (z, z, cur32, z))
        cache_v = jax.lax.dynamic_update_slice(
            cache[1], vnew[:, :, None, :], (z, z, cur32, z))
        scale = 1.0 / jnp.sqrt(jnp.float32(d))
        logits = jnp.einsum("bhd,bhsd->bhs", q.astype(jnp.float32),
                            cache_k.astype(jnp.float32)) * scale
        pos = jnp.arange(max_s)
        mask = pos[None, None, :] <= cur
        logits = jnp.where(mask, logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bhs,bhsd->bhd", p,
                         cache_v.astype(jnp.float32))
        new_cache = jnp.stack([cache_k, cache_v], axis=0)
        return out.reshape(b, h * d).astype(xa.dtype), \
            new_cache.astype(cache.dtype)

    out, new_cache = apply(fn, x, cache_kv, name="masked_mha")
    return out, new_cache


def fused_matmul_bias(x, y, bias=None, transpose_x=False,
                      transpose_y=False, name=None):
    """Reference fused_matmul_bias (cublasLt epilogue): one matmul with
    the bias add fused by XLA."""
    def fn(a, b, *mb):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = a @ b
        if mb:
            out = out + mb[0]
        return out
    args = [x, y] + ([bias] if bias is not None else [])
    return apply(fn, *args, name="fused_matmul_bias")


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", name=None):
    """Reference fused_bias_dropout_residual_layer_norm: out =
    layer_norm(residual + dropout(x + bias))."""
    from ....nn import functional as F

    y = x if bias is None else x + bias
    y = F.dropout(y, p=dropout_rate, training=training, mode=mode)
    y = residual + y
    d = y.shape[-1]
    return F.layer_norm(y, [d], weight=ln_scale, bias=ln_bias,
                        epsilon=ln_epsilon)


def fused_dot_product_attention(
        q, k, v, bias=None, cu_seqlen_q=None, cu_seqlen_kv=None,
        scaling_factor=None, dropout_prob=0.0, training=True,
        is_causal_masking=False, name=None):
    """Reference fused_dot_product_attention (cuDNN FMHA): [b, s, h, d]
    SDPA routed to the Pallas flash kernel."""
    from ....nn import functional as F

    return F.scaled_dot_product_attention(
        q, k, v, attn_mask=bias, dropout_p=dropout_prob,
        is_causal=is_causal_masking, training=training)


# CUDA-library alias: on TPU both land on the Pallas flash kernel
cudnn_flash_attention = fused_dot_product_attention


def block_multihead_attention(
        qkv, key_cache, value_cache, seq_lens_encoder, seq_lens_decoder,
        seq_lens_this_time, padding_offsets, cum_offsets, cu_seqlens_q,
        cu_seqlens_k, block_tables, pre_key_cache=None,
        pre_value_cache=None, cache_k_quant_scales=None,
        cache_v_quant_scales=None, cache_k_dequant_scales=None,
        cache_v_dequant_scales=None, qkv_out_scale=None, qkv_bias=None,
        out_shift=None, out_smooth=None, max_enc_len_this_time=None,
        max_dec_len_this_time=None, rope_emb=None, mask=None,
        tgt_mask=None, max_seq_len=-1, block_size=64,
        use_neox_style=False, use_dynamic_cachekv_quant=False,
        quant_round_type=1, quant_max_bound=127.0,
        quant_min_bound=-127.0, out_scale=-1, compute_dtype="default",
        name=None):
    """Paged (block-table) attention, decode mode (reference
    block_multi_head_attention_kernel.cu surface; the full serving
    engine lives in inference/paged.py — this functional form covers the
    one-token-per-sequence decode step over an external block pool).

    qkv: [tokens, 3*h*d] packed (tokens == batch in decode mode);
    key/value_cache: [num_blocks, block_size, kv_heads, head_dim];
    block_tables: [batch, max_blocks]; seq_lens_decoder: current lengths
    (the new token writes at that position). Returns (out, qkv, updated
    key_cache, updated value_cache) like the reference.
    """
    from ....inference.paged import (paged_decode_attention,
                                     paged_decode_write)

    assert cache_k_quant_scales is None and qkv_out_scale is None, \
        "cache quantization not supported in this build"

    def fn(qkv_a, kc, vc, lens, tables, *maybe_bias):
        nb, bs, hk, hd = kc.shape
        b = tables.shape[0]
        if maybe_bias:
            qkv_a = qkv_a + maybe_bias[0]
        total_h = qkv_a.shape[-1] // hd
        hq = total_h - 2 * hk
        qkv3 = qkv_a.reshape(b, total_h, hd)
        qh = qkv3[:, :hq]
        kh = qkv3[:, hq:hq + hk]
        vh = qkv3[:, hq + hk:]
        lens32 = lens.reshape(-1).astype(jnp.int32)
        active = lens32 >= 0
        kc2, vc2 = paged_decode_write(kc, vc, tables.astype(jnp.int32),
                                      jnp.maximum(lens32, 0), kh, vh,
                                      active)
        out = paged_decode_attention(
            qh, kc2, vc2, tables.astype(jnp.int32),
            jnp.where(active, lens32 + 1, 0))
        return out.reshape(b, hq * hd), kc2, vc2

    args = [qkv, key_cache, value_cache, seq_lens_decoder, block_tables]
    if qkv_bias is not None:
        args.append(qkv_bias)
    out, kc2, vc2 = apply(fn, *args, name="block_multihead_attention")
    return out, qkv, kc2, vc2


def block_multihead_attention_xpu(*args, **kwargs):
    return block_multihead_attention(*args, **kwargs)


def fused_multi_transformer(
        x, ln_scales, ln_biases, qkv_weights, qkv_biases, linear_weights,
        linear_biases, ffn_ln_scales, ffn_ln_biases, ffn1_weights,
        ffn1_biases, ffn2_weights, ffn2_biases, pre_layer_norm=True,
        epsilon=1e-5, cache_kvs=None, pre_caches=None, rotary_embs=None,
        time_step=None, attn_mask=None, dropout_rate=0.0,
        activation="gelu", training=False, mode="upscale_in_train",
        trans_qkvw=True, ring_id=-1, name=None):
    """Functional fused_multi_transformer (reference
    fused_multi_transformer_op.cu surface): a stack of pre-LN decoder
    layers driven by weight lists; one jit-traceable composition."""
    from ....nn import functional as F

    out = x
    new_caches = [] if cache_kvs is not None else None
    for i in range(len(qkv_weights)):
        residual = out
        h = F.layer_norm(out, [out.shape[-1]], weight=ln_scales[i],
                         bias=ln_biases[i], epsilon=epsilon) \
            if pre_layer_norm else out
        attn_out = fused_multi_head_attention_block(
            h, qkv_weights[i], qkv_biases[i] if qkv_biases else None,
            linear_weights[i],
            linear_biases[i] if linear_biases else None,
            trans_qkvw=trans_qkvw, attn_mask=attn_mask)
        out = residual + attn_out
        residual = out
        h = F.layer_norm(out, [out.shape[-1]], weight=ffn_ln_scales[i],
                         bias=ffn_ln_biases[i], epsilon=epsilon) \
            if pre_layer_norm else out
        act = F.gelu if activation == "gelu" else F.relu
        h = fused_linear(h, ffn1_weights[i],
                         ffn1_biases[i] if ffn1_biases else None)
        h = act(h)
        h = fused_linear(h, ffn2_weights[i],
                         ffn2_biases[i] if ffn2_biases else None)
        out = residual + h
    if cache_kvs is not None:
        return out, cache_kvs
    return out


def fused_multi_head_attention_block(x, qkv_weight, qkv_bias,
                                     linear_weight, linear_bias,
                                     trans_qkvw=True, attn_mask=None,
                                     num_heads=None):
    """One attention sublayer over packed qkv weights (helper for
    fused_multi_transformer). qkv_weight: [3, h, hd, d] when trans_qkvw
    (the reference's layout) else [d, 3*h*hd]."""
    from ....nn import functional as F

    b, s, d = x.shape
    if trans_qkvw:
        n_heads = qkv_weight.shape[1]
        head_dim = qkv_weight.shape[2]
    else:
        assert num_heads is not None, "num_heads needed for [d, 3hd] qkv"
        n_heads = num_heads
        head_dim = qkv_weight.shape[-1] // (3 * n_heads)

    def fn(xa, wqkv, *rest):
        w = wqkv
        if trans_qkvw:
            w = jnp.transpose(w.reshape(3 * n_heads * head_dim, d))
        qkv_a = xa @ w
        if rest:
            qkv_a = qkv_a + rest[0].reshape(-1)
        return qkv_a

    qkv = apply(fn, x, qkv_weight,
                *([qkv_bias] if qkv_bias is not None else []),
                name="fmt_qkv")
    total = n_heads * head_dim
    q = qkv[:, :, :total].reshape([b, s, n_heads, head_dim])
    k = qkv[:, :, total:2 * total].reshape([b, s, n_heads, head_dim])
    v = qkv[:, :, 2 * total:].reshape([b, s, n_heads, head_dim])
    o = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                       is_causal=attn_mask is None)
    o = o.reshape([b, s, total])
    return fused_linear(o, linear_weight, linear_bias)
