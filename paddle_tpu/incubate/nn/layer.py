"""FusedMultiTransformer layer.

Parity: reference `python/paddle/incubate/nn/layer/fused_transformer.py`
FusedMultiTransformer over `fused_multi_transformer_op.cu:31` (full
decoder stack: per-layer pre-LN + QKV + cache-KV attention + out-proj +
FFN, with TP allreduce inside via ring id). TPU-first: the same math in
jnp composed per layer — XLA fuses it; TP comes from weight placements
(GSPMD inserts the allreduces the kernel hard-codes).
"""

from __future__ import annotations

from ... import nn
from ...nn import functional as F


class FusedMultiTransformer(nn.Layer):
    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu",
                 normalize_before=True, ln_scale_attrs=None,
                 ln_bias_attrs=None, qkv_weight_attrs=None,
                 qkv_bias_attrs=None, linear_weight_attrs=None,
                 linear_bias_attrs=None, ffn_ln_scale_attrs=None,
                 ffn_ln_bias_attrs=None, ffn1_weight_attrs=None,
                 ffn1_bias_attrs=None, ffn2_weight_attrs=None,
                 ffn2_bias_attrs=None, epsilon=1e-5, num_layers=-1,
                 nranks=1, trans_qkvw=True, ring_id=-1, name=None):
        super().__init__()
        if num_layers == -1:
            num_layers = len(qkv_weight_attrs) if isinstance(
                qkv_weight_attrs, (list, tuple)) else 1
        self.num_layers = num_layers
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self._epsilon = epsilon
        self._trans_qkvw = trans_qkvw
        self.activation = activation

        def attr(attrs, i):
            return attrs[i] if isinstance(attrs, (list, tuple)) else attrs

        self.ln_scales, self.ln_biases = nn.ParameterList(), \
            nn.ParameterList()
        self.qkv_weights, self.qkv_biases = nn.ParameterList(), \
            nn.ParameterList()
        self.linear_weights, self.linear_biases = nn.ParameterList(), \
            nn.ParameterList()
        self.ffn_ln_scales, self.ffn_ln_biases = nn.ParameterList(), \
            nn.ParameterList()
        self.ffn1_weights, self.ffn1_biases = nn.ParameterList(), \
            nn.ParameterList()
        self.ffn2_weights, self.ffn2_biases = nn.ParameterList(), \
            nn.ParameterList()
        ones = nn.initializer.Constant(1.0)
        for i in range(num_layers):
            self.ln_scales.append(self.create_parameter(
                [embed_dim], attr=attr(ln_scale_attrs, i),
                default_initializer=ones))
            self.ln_biases.append(self.create_parameter(
                [embed_dim], attr=attr(ln_bias_attrs, i), is_bias=True))
            qkv_shape = [3, num_heads, self.head_dim, embed_dim] \
                if trans_qkvw else [embed_dim, 3, num_heads, self.head_dim]
            self.qkv_weights.append(self.create_parameter(
                qkv_shape, attr=attr(qkv_weight_attrs, i)))
            self.qkv_biases.append(self.create_parameter(
                [3, num_heads, self.head_dim],
                attr=attr(qkv_bias_attrs, i), is_bias=True))
            self.linear_weights.append(self.create_parameter(
                [embed_dim, embed_dim], attr=attr(linear_weight_attrs, i)))
            self.linear_biases.append(self.create_parameter(
                [embed_dim], attr=attr(linear_bias_attrs, i), is_bias=True))
            self.ffn_ln_scales.append(self.create_parameter(
                [embed_dim], attr=attr(ffn_ln_scale_attrs, i),
                default_initializer=ones))
            self.ffn_ln_biases.append(self.create_parameter(
                [embed_dim], attr=attr(ffn_ln_bias_attrs, i), is_bias=True))
            self.ffn1_weights.append(self.create_parameter(
                [embed_dim, dim_feedforward],
                attr=attr(ffn1_weight_attrs, i)))
            self.ffn1_biases.append(self.create_parameter(
                [dim_feedforward], attr=attr(ffn1_bias_attrs, i),
                is_bias=True))
            self.ffn2_weights.append(self.create_parameter(
                [dim_feedforward, embed_dim],
                attr=attr(ffn2_weight_attrs, i)))
            self.ffn2_biases.append(self.create_parameter(
                [embed_dim], attr=attr(ffn2_bias_attrs, i), is_bias=True))

    def forward(self, src, attn_mask=None, caches=None, pre_caches=None,
                rotary_embs=None, rotary_emb_dims=0, seq_lens=None,
                time_step=None):
        from ... import ops
        x = src
        b, s, d = x.shape
        new_caches = [] if caches is not None else None
        for i in range(self.num_layers):
            residual = x
            h = F.layer_norm(x, [d], self.ln_scales[i], self.ln_biases[i],
                             self._epsilon) if self.normalize_before else x
            if self._trans_qkvw:
                w = ops.reshape(self.qkv_weights[i], [3 * d, d])
                qkv = ops.matmul(h, w, transpose_y=True)
            else:
                w = ops.reshape(self.qkv_weights[i], [d, 3 * d])
                qkv = ops.matmul(h, w)
            qkv = ops.reshape(qkv, [b, s, 3, self.num_heads,
                                    self.head_dim])
            qkv = qkv + ops.reshape(self.qkv_biases[i],
                                    [1, 1, 3, self.num_heads,
                                     self.head_dim])
            q, k, v = ops.unbind(qkv, axis=2)
            if caches is not None:
                k = ops.concat([caches[i][0], k], axis=1)
                v = ops.concat([caches[i][1], v], axis=1)
                new_caches.append((k, v))
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask,
                is_causal=attn_mask is None and caches is None)
            out = ops.reshape(out, [b, s, d])
            out = ops.matmul(out, self.linear_weights[i]) + \
                self.linear_biases[i]
            x = residual + out
            if not self.normalize_before:
                x = F.layer_norm(x, [d], self.ln_scales[i],
                                 self.ln_biases[i], self._epsilon)

            residual = x
            h = F.layer_norm(x, [d], self.ffn_ln_scales[i],
                             self.ffn_ln_biases[i], self._epsilon) \
                if self.normalize_before else x
            h = ops.matmul(h, self.ffn1_weights[i]) + self.ffn1_biases[i]
            h = F.gelu(h, approximate=True) if self.activation == "gelu" \
                else getattr(F, self.activation)(h)
            h = ops.matmul(h, self.ffn2_weights[i]) + self.ffn2_biases[i]
            x = residual + h
            if not self.normalize_before:
                x = F.layer_norm(x, [d], self.ffn_ln_scales[i],
                                 self.ffn_ln_biases[i], self._epsilon)
        if new_caches is not None:
            return x, new_caches
        return x
