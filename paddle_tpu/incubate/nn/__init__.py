"""`paddle.incubate.nn` (reference: python/paddle/incubate/nn/)."""

from . import functional  # noqa: F401
from .layer import FusedMultiTransformer  # noqa: F401
