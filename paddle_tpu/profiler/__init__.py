"""`paddle.profiler`.

Parity: reference python/paddle/profiler/ — `Profiler` (profiler.py:346),
`make_scheduler` (:117) CLOSED→READY→RECORD state machine, chrome-tracing
export (:215), `RecordEvent` RAII spans (phi/api/profiler/
event_tracing.h:32), summary tables (profiler_statistic.py), throughput
timer (timer.py). TPU-first: device-side tracing is delegated to
`jax.profiler` (XPlane/TensorBoard — the CUPTI equivalent); host spans are
recorded in-process and exported as chrome://tracing JSON alongside.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from enum import Enum

__all__ = ["Profiler", "ProfilerState", "ProfilerTarget", "RecordEvent",
           "make_scheduler", "export_chrome_tracing", "export_protobuf",
           "load_profiler_result", "SortedKeys", "SummaryView", "metrics",
           "tracing", "export", "accounting", "alerts", "fleet",
           "scorecard", "summary_text"]


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3


class SortedKeys(Enum):
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView(Enum):
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


class _HostEventRecorder:
    """Lock-free-ish host span store (reference host_event_recorder.h).

    ``record_shapes`` mirrors the armed Profiler's flag: instrumentation
    sites (core/dispatch._post_op_hooks) read it to decide whether to
    collect output shapes/dtypes into span args."""

    def __init__(self):
        self._lock = threading.Lock()
        self.events = []
        self.enabled = False
        self.record_shapes = False

    def record(self, name, start, end, event_type="UserDefined",
               args=None):
        if not self.enabled:
            return
        ev = {"name": name, "ts": start, "dur": end - start,
              "tid": threading.get_ident(), "type": event_type}
        if args:
            ev["args"] = args
        with self._lock:
            self.events.append(ev)

    def drain(self):
        with self._lock:
            ev, self.events = self.events, []
        return ev


_recorder = _HostEventRecorder()

# the always-on metrics registry rides in the profiler package
# (paddle_tpu.profiler.metrics); importing it also installs the
# jax.monitoring XLA-compile listener
from . import metrics  # noqa: E402,F401

# request-scoped tracing (span ring + contextvars TraceContext) and the
# export surface (OpenMetrics text, /metrics HTTP endpoint); importing
# tracing wires the histogram-exemplar probe into the registry
from . import export, tracing  # noqa: E402,F401

# cost attribution / goodput accounting + SLO burn-rate alert rules
# (the serving scheduler drives them; summary() renders their views)
from . import accounting, alerts  # noqa: E402,F401

# fleet observatory: replica registry + cross-replica federation +
# health scoring (ServingEngine.serve_metrics(store=) registers into it)
from . import fleet  # noqa: E402,F401

# scenario scoreboard: loadgen scenarios graded against a multi-replica
# fleet through scenario-scoped Windows (engines only pulled in once a
# FleetHarness is actually built)
from . import scorecard  # noqa: E402,F401


class RecordEvent:
    """RAII/contextmanager host span (reference event_tracing.h:32)."""

    def __init__(self, name, event_type="UserDefined"):
        self.name = name
        self.event_type = event_type
        self._begin = None

    def begin(self):
        self._begin = time.perf_counter_ns() / 1000.0

    def end(self):
        if self._begin is not None:
            _recorder.record(self.name, self._begin,
                             time.perf_counter_ns() / 1000.0,
                             self.event_type)
            self._begin = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    """reference profiler.py:117."""
    total = closed + ready + record

    def scheduler(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat > 0 and s >= repeat * total:
            return ProfilerState.CLOSED
        pos = s % total
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == total - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def _default_on_trace_ready(prof):
    pass


def export_chrome_tracing(dir_name, worker_name=None):
    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"worker_{os.getpid()}"
        path = os.path.join(dir_name,
                            f"{name}_t{prof._export_count}.json")
        prof._export_chrome(path)

    return handler


def export_protobuf(dir_name, worker_name=None):
    """on_trace_ready handler writing a REAL protobuf dump (reference
    exports chrome JSON and a protobuf node tree —
    paddle/fluid/platform/profiler/dump/; schema here is
    profiler_trace.proto, loadable via `load_profiler_result`)."""

    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"worker_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}_t{prof._export_count}.pb")
        prof._export_protobuf(path, name)

    return handler


def load_profiler_result(path):
    """Load an exported trace: .pb (protobuf TraceProto) or chrome .json."""
    if path.endswith(".pb"):
        from . import profiler_trace_pb2 as pb
        t = pb.TraceProto()
        with open(path, "rb") as f:
            t.ParseFromString(f.read())
        return t
    with open(path) as f:
        return json.load(f)


def _slow_requests_view(serving_snap):
    """"Slow requests" summary section: the per-bucket max-latency
    exemplars of the serving SLO histograms (docs/OBSERVABILITY.md),
    worst first — each row names the trace_id to pull from the ring
    (``tracing.export_trace`` / the /traces/<id> endpoint). ``spans``
    says how much of that trace is still exportable."""
    rows = []
    for name in ("serving.ttft_us", "serving.itl_us",
                 "serving.queue_wait_us"):
        v = serving_snap.get(name)
        if isinstance(v, dict):
            for ex in (v.get("exemplars") or {}).values():
                rows.append((ex["value"], name, ex["trace_id"]))
    if not rows:
        return []
    rows.sort(reverse=True)
    lines = ["", "{:-^72}".format(" Slow requests (exemplars) "),
             "{:<24} {:>14}  {:<18} {}".format(
                 "metric", "latency_us", "trace_id", "spans")]
    for value, name, tid in rows[:8]:
        lines.append("{:<24} {:>14.1f}  {:<18} {}".format(
            name, value, tid, len(tracing.get_trace(tid))))
    return lines


def _fmt_bytes(n):
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0


def _capacity_view(snap):
    """"Capacity View" summary section: the KV-pool occupancy breakdown
    (active / shared / cached-free / free blocks, pool HBM footprint)
    plus the live-array HBM sample — the headroom numbers admission and
    eviction decisions are made against (profiler/accounting.py)."""
    # gate on ARMED accounting having stepped, not mere gauge
    # registration — a disarmed serving run (FLAGS_serving_accounting=0)
    # never sets these gauges and must not render a bogus zero pool
    if not snap.get("serving.steps") or not snap.get("accounting.steps"):
        return []
    active = snap.get("serving.kv.active_blocks", 0)
    shared = snap.get("serving.kv.shared_blocks", 0)
    cached = snap.get("serving.kv.cached_blocks", 0)
    free = snap.get("serving.kv.free_blocks", 0)
    usable = active + cached + free
    lines = ["", "{:-^72}".format(" Capacity View (KV pool / HBM) "),
             "{:<26} {:>10} {}".format("resource", "value", "notes")]
    rows = [
        ("kv.active_blocks", active, "pinned by live requests"),
        ("kv.shared_blocks", shared, "backing >1 slot (prefix cache)"),
        ("kv.cached_free_blocks", cached, "reclaimable (LRU-evictable)"),
        ("kv.free_blocks", free, "truly free"),
        ("kv.usable_blocks", usable,
         f"occupancy {active / usable:.1%}" if usable else ""),
    ]
    pool_b = snap.get("serving.kv.pool_bytes", 0)
    if pool_b:
        rows.append(("kv.pool_bytes", _fmt_bytes(pool_b),
                     "static K+V pool footprint"))
    live_b = snap.get("memory.live_bytes", 0)
    if live_b:
        rows.append(("hbm.live_bytes", _fmt_bytes(live_b),
                     f"{snap.get('memory.live_arrays', 0)} live arrays"))
    for name, value, note in rows:
        lines.append("{:<26} {:>10} {}".format(name, value, note))
    # mesh-sharded serving (FLAGS_serving_mesh): the per-slice
    # breakdown rides slice-labeled gauges (serving.kv.*{slice="i"});
    # absent on single-device engines, and per-slice sums equal the
    # aggregates above (tests/framework/test_mesh_serving.py)
    slices = {}
    for key, v in snap.items():
        if key.startswith("serving.kv.") and '{slice="' in key:
            base, _, lab = key.partition("{")
            sid = lab.split('"')[1]
            slices.setdefault(sid, {})[base.rsplit(".", 1)[-1]] = v
    for sid in sorted(slices, key=lambda s: (len(s), s)):
        d = slices[sid]
        lines.append(
            "{:<26} {:>10} {}".format(
                f"kv.slice[{sid}]",
                d.get("active_blocks", 0),
                f"active (free {d.get('free_blocks', 0)}, shared "
                f"{d.get('shared_blocks', 0)}, cached "
                f"{d.get('cached_blocks', 0)})"))
    return lines


def _goodput_view(snap):
    """"Goodput" summary section: engine-level cost attribution rollup
    — deadline-met tokens per attributed device-second, raw tokens/s,
    MFU estimate, and where non-serving time went (compile, preemption
    re-prefill waste, idle steps)."""
    device_us = snap.get("accounting.device_us", 0)
    if not device_us:
        return []
    device_s = device_us / 1e6
    tokens = snap.get("accounting.tokens_emitted", 0)
    good = snap.get("accounting.goodput_tokens", 0)
    lines = ["", "{:-^72}".format(" Goodput (cost attribution) "),
             "{:<30} {}".format("metric", "value")]
    rows = [
        ("goodput tokens/device-s", f"{good / device_s:.1f}"),
        ("raw tokens/device-s", f"{tokens / device_s:.1f}"),
        ("deadline-met tokens", f"{good} / {tokens} emitted"),
        ("processed tokens (padded)",
         f"{snap.get('accounting.tokens_processed', 0)}"),
        ("device seconds", f"{device_s:.3f}"),
        ("attributed_us", f"{snap.get('accounting.attributed_us', 0):.0f}"),
        ("compile_us (billed direct)",
         f"{snap.get('accounting.compile_us', 0):.0f}"),
        ("reprefill_us (preempt waste)",
         f"{snap.get('accounting.reprefill_us', 0):.0f}"),
        ("idle_us (empty steps)",
         f"{snap.get('accounting.idle_us', 0):.0f}"),
    ]
    mfu = snap.get("accounting.mfu", 0)
    if mfu:
        rows.insert(2, ("mfu estimate", f"{mfu:.3f}"))
    for name, value in rows:
        lines.append("{:<30} {}".format(name, value))
    return lines


def _overload_view(snap):
    """"Overload" summary section: the admission/shed/brownout control
    plane (serving/overload.py) plus the router's per-replica circuit
    breakers — what the engine refused, dropped, or degraded to keep
    the surviving traffic inside its SLOs. Renders only once any of it
    acted (armed runs under pressure); a disarmed or uncontended
    process shows nothing."""
    shed = snap.get("serving.shed", 0)
    rejected = snap.get("serving.admission.rejected", 0)
    stage = snap.get("serving.brownout.stage", 0)
    transitions = snap.get("serving.brownout.transitions", 0)
    clamped = snap.get("serving.brownout.clamped", 0)
    opened = snap.get("router.breaker.opened", 0)
    skipped = snap.get("router.breaker.skipped", 0)
    if not (shed or rejected or stage or transitions or clamped
            or opened):
        return []
    lines = ["",
             "{:-^72}".format(" Overload (admission / shed / brownout) "),
             "{:<30} {}".format("metric", "value")]
    rows = [
        ("brownout stage", f"{stage} (transitions {transitions})"),
        ("shed requests", f"{shed}"),
        ("admission rejected", f"{rejected}"),
        ("max_new_tokens clamped", f"{clamped}"),
    ]
    pred = snap.get("admission.predicted_ttft_us")
    if isinstance(pred, dict) and pred.get("count"):
        rows.append(("predicted TTFT p50/p95",
                     f"{pred['p50']:.0f}us / {pred['p95']:.0f}us "
                     f"({pred['count']} predictions)"))
    if opened or skipped:
        rows.append(("breaker opened / closed",
                     f"{opened} / "
                     f"{snap.get('router.breaker.closed', 0)}"))
        rows.append(("breaker short-circuits", f"{skipped}"))
    for name, value in rows:
        lines.append("{:<30} {}".format(name, value))
    return lines


def _cold_start_view(snap):
    """"Cold start" summary section: the persistent AOT compile cache
    (serving/aot_cache.py) — hits/misses/stores against the on-disk
    executable store, payload bytes moved, deserialize latency, and
    the compile seconds hits did NOT pay. Renders only once the cache
    touched disk (armed runs); a disarmed process shows nothing."""
    hits = snap.get("jit.aot.hits", 0)
    misses = snap.get("jit.aot.misses", 0)
    stores = snap.get("jit.aot.stores", 0)
    if not (hits or misses or stores):
        return []
    lines = ["", "{:-^72}".format(" Cold start (AOT compile cache) "),
             "{:<30} {}".format("metric", "value")]
    rows = [
        ("aot hits / misses", f"{hits} / {misses}"),
        ("aot stores", f"{stores}"),
        ("compile seconds saved",
         f"{snap.get('jit.aot.saved_us', 0) / 1e6:.3f}s"),
        ("payload bytes moved",
         _fmt_bytes(snap.get("jit.aot.bytes", 0))),
    ]
    q = snap.get("jit.aot.quarantined", 0)
    if q:
        rows.append(("entries quarantined", f"{q} (see *.corrupt-N)"))
    load = snap.get("jit.aot.load_us")
    if isinstance(load, dict) and load.get("count"):
        rows.append(("load latency p50/p95",
                     f"{load['p50']:.0f}us / {load['p95']:.0f}us"))
    comp = snap.get("xla.compile.seconds")
    if isinstance(comp, dict):
        rows.append(("xla compiles this process",
                     f"{snap.get('xla.compile.count', 0)} "
                     f"({comp.get('sum', 0.0):.3f}s)"))
    for name, value in rows:
        lines.append("{:<30} {}".format(name, value))
    return lines


def _scorecard_view():
    """"Scenario scorecard" summary section: the latest fleet-invariant
    scoreboard published by profiler/scorecard.py (run_scenario /
    record) — per-phase arrivals, goodput, windowed TTFT p95, prefix
    hit-rate, and each invariant's verdict. Empty until a scenario ran
    in this process. Lazy import: scorecard pulls serving modules the
    summary must not force-load."""
    try:
        from . import scorecard
        return scorecard.summary_lines()
    except Exception:  # noqa: BLE001 — summary must render regardless
        return []


def summary_text():
    """The registry-driven half of :meth:`Profiler.summary` — the
    serving/SLO table plus every always-on section (capacity, goodput,
    overload, cold start, scenario scorecard, incidents) — WITHOUT a
    Profiler instance or op events. This is what the MetricsServer's
    ``/summary`` endpoint serves, so an operator reads the human view
    with curl instead of a Python shell."""
    lines = []
    serving = metrics.snapshot("serving.")
    if serving and serving.get("serving.steps"):
        lines.append("{:-^72}".format(" Serving / SLO View "))
        lines.append("{:<36} {}".format("metric", "value"))
        for name in sorted(serving):
            v = serving[name]
            if isinstance(v, dict):
                desc = f"count={v['count']}"
                if v["count"]:
                    desc += (f" avg={v['avg']:.6g} min={v['min']:.6g}"
                             f" max={v['max']:.6g} p50={v['p50']:.6g}"
                             f" p95={v['p95']:.6g} p99={v['p99']:.6g}")
            else:
                desc = str(v)
            lines.append("{:<36} {}".format(name, desc))
        lines.extend(_slow_requests_view(serving))
    full_snap = metrics.snapshot()
    lines.extend(_capacity_view(full_snap))
    lines.extend(_goodput_view(full_snap))
    lines.extend(_overload_view(full_snap))
    lines.extend(_cold_start_view(full_snap))
    lines.extend(_scorecard_view())
    lines.extend(_recent_incidents_view())
    return "\n".join(lines)


def _recent_incidents_view(limit=10):
    """"Recent incidents" summary section: the watchdog flight-recorder
    ring (degrade / preempt / retry / quarantine events recorded by
    core.resilience and the collective watchdog) — recorded since PR 4
    but never surfaced outside a timeout dump until now."""
    try:
        from ..distributed import watchdog
    except Exception:  # noqa: BLE001 — summary must render regardless
        return []
    recs = [r for r in watchdog.flight_recorder().records()
            if r.get("status") not in ("done", "running")]
    if not recs:
        return []
    now = time.time()
    lines = ["", "{:-^72}".format(" Recent incidents (flight ring) "),
             "{:<5} {:>8} {:<28} {:<10} {}".format(
                 "seq", "age_s", "event", "status", "detail")]
    for r in recs[-limit:]:
        meta = {k: v for k, v in r.items()
                if k not in ("seq", "tag", "start", "end", "status")}
        detail = meta.pop("detail", "")
        if meta:
            detail = (detail + " " + json.dumps(meta, default=str)).strip()
        lines.append("{:<5} {:>8.1f} {:<28} {:<10} {}".format(
            r["seq"], now - r["start"], r["tag"][:28], r["status"],
            detail[:60]))
    return lines


class Profiler:
    """reference profiler.py:346."""

    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None,
                 record_shapes=False, profile_memory=False, timer_only=False,
                 emit_nvtx=False, custom_device_types=None, with_flops=False):
        self._scheduler = scheduler if callable(scheduler) else (
            make_scheduler(closed=0, ready=0,
                           record=(scheduler[1] - scheduler[0]),
                           skip_first=scheduler[0])
            if isinstance(scheduler, (tuple, list)) else
            (lambda step: ProfilerState.RECORD))
        self._on_trace_ready = on_trace_ready or _default_on_trace_ready
        self._timer_only = timer_only
        self._record_shapes = record_shapes
        self._profile_memory = profile_memory
        self.step_num = 0
        self._state = ProfilerState.CLOSED
        self._events = []
        self._memory_samples = []
        self._export_count = 0
        self._device_trace_dir = None
        self._step_begin = None
        self._step_info = ""

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self._transition(self._scheduler(self.step_num))

    def stop(self):
        if self._state in (ProfilerState.RECORD,
                           ProfilerState.RECORD_AND_RETURN):
            self._collect()
            self._on_trace_ready(self)
        self._transition(ProfilerState.CLOSED)

    def step(self, num_samples=None):
        if self._step_begin is not None:
            dur = time.perf_counter() - self._step_begin
            if num_samples:
                self._step_info = (
                    f"ips: {num_samples / dur:.3f} samples/s")
        self._step_begin = time.perf_counter()
        self._maybe_sample_memory()
        prev = self._state
        if prev == ProfilerState.RECORD_AND_RETURN:
            self._collect()
            self._on_trace_ready(self)
        self.step_num += 1
        self._transition(self._scheduler(self.step_num))

    def step_info(self, unit=None):
        return self._step_info

    def _transition(self, new):
        if new == self._state:
            return
        recording_states = (ProfilerState.RECORD,
                            ProfilerState.RECORD_AND_RETURN)
        if new in recording_states and self._state not in recording_states:
            _recorder.record_shapes = self._record_shapes
            _recorder.enabled = True
            self._maybe_sample_memory()
            self._maybe_start_device_trace()
        if new not in recording_states and self._state in recording_states:
            self._maybe_sample_memory()
            _recorder.enabled = False
            _recorder.record_shapes = False
            self._maybe_stop_device_trace()
        self._state = new

    def _maybe_sample_memory(self):
        """profile_memory=True: sample live device memory at step
        boundaries — `jax.live_arrays()` (count + bytes; works on every
        backend incl. CPU) plus `device.memory_stats()` where the
        runtime exposes it (TPU/GPU). Gated on the recorder so samples
        accumulate only while a Profiler records (callers on the
        enable/disable edges sequence around the flag flip)."""
        if not self._profile_memory or not _recorder.enabled:
            return
        try:
            import jax
            arrs = [a for a in jax.live_arrays()
                    if getattr(a, "is_deleted", lambda: False)() is False]
            live_bytes = sum(int(getattr(a, "nbytes", 0)) for a in arrs)
            sample = {"ts": time.perf_counter_ns() / 1000.0,
                      "step": self.step_num,
                      "live_arrays": len(arrs),
                      "live_bytes": live_bytes}
            try:
                dev = jax.devices()[0]
                stats = dev.memory_stats() or {}
                if "bytes_in_use" in stats:
                    sample["device_bytes_in_use"] = int(
                        stats["bytes_in_use"])
                if "peak_bytes_in_use" in stats:
                    sample["device_peak_bytes"] = int(
                        stats["peak_bytes_in_use"])
                sample["device"] = f"{dev.platform}:{dev.id}"
            except Exception:  # noqa: BLE001 — CPU backend: no stats
                pass
            self._memory_samples.append(sample)
            metrics.gauge("memory.live_bytes").set(live_bytes)
            metrics.gauge("memory.live_arrays").set(len(arrs))
        except Exception:  # noqa: BLE001 — sampling must never break a step
            pass

    def _maybe_start_device_trace(self):
        if self._timer_only:
            return
        try:
            import jax
            import tempfile
            self._device_trace_dir = tempfile.mkdtemp(prefix="xplane_")
            jax.profiler.start_trace(self._device_trace_dir)
        except Exception:
            self._device_trace_dir = None

    def _maybe_stop_device_trace(self):
        if self._device_trace_dir is not None:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                pass

    def _collect(self):
        self._events.extend(_recorder.drain())

    # -- export / summary --------------------------------------------------
    def _export_chrome(self, path):
        self._export_count += 1
        trace = []
        for e in self._events:
            ce = {"name": e["name"], "ph": "X", "ts": e["ts"],
                  "dur": e["dur"], "pid": os.getpid(), "tid": e["tid"],
                  "cat": e["type"]}
            if e.get("args"):
                ce["args"] = e["args"]
            trace.append(ce)
        pid = os.getpid()
        for s in self._memory_samples:
            # chrome counter events: live memory renders as a graph track
            trace.append({"name": "live_bytes", "ph": "C", "ts": s["ts"],
                          "pid": pid,
                          "args": {"live_bytes": s["live_bytes"]}})
        with open(path, "w") as f:
            json.dump({"traceEvents": trace,
                       "memory_samples": self._memory_samples,
                       "metrics": metrics.snapshot(),
                       "xplane_dir": self._device_trace_dir}, f,
                      default=str)

    def _export_protobuf(self, path, worker_name=""):
        self._export_count += 1
        from . import profiler_trace_pb2 as pb
        t = pb.TraceProto(pid=os.getpid(), worker_name=worker_name,
                          xplane_dir=self._device_trace_dir or "",
                          export_index=self._export_count)
        for e in self._events:
            ev = t.events.add()
            ev.name = e["name"]
            ev.type = e["type"]
            ev.start_us = float(e["ts"])
            ev.dur_us = float(e["dur"])
            ev.tid = int(e["tid"])
            for k, v in (e.get("args") or {}).items():
                kv = ev.args.add()
                kv.key = str(k)
                kv.value = json.dumps(v, default=str)
        for s in self._memory_samples:
            ms = t.memory_samples.add()
            ms.ts_us = float(s["ts"])
            ms.step = int(s["step"])
            ms.live_arrays = int(s["live_arrays"])
            ms.live_bytes = int(s["live_bytes"])
            ms.device_bytes_in_use = int(s.get("device_bytes_in_use", 0))
            ms.device_peak_bytes = int(s.get("device_peak_bytes", 0))
            ms.device = s.get("device", "")
        for k, v in metrics.snapshot().items():
            kv = t.metrics.add()
            kv.key = k
            kv.value = json.dumps(v, default=str)
        with open(path, "wb") as f:
            f.write(t.SerializeToString())

    def export(self, path, format="json"):
        self._collect()
        if format in ("pb", "protobuf") or path.endswith(".pb"):
            self._export_protobuf(path)
        else:
            self._export_chrome(path)

    def summary(self, sorted_by=SortedKeys.CPUTotal, op_detail=True,
                thread_sep=False, time_unit="ms", views=None):
        self._collect()
        # OperatorView: per-op totals + dispatch-path breakdown (the
        # path rides in span args, recorded by core/dispatch)
        agg = {}
        for e in self._events:
            a = agg.setdefault(
                e["name"], {"calls": 0, "total": 0.0, "max": 0.0,
                            "paths": {}})
            a["calls"] += 1
            a["total"] += e["dur"]
            a["max"] = max(a["max"], e["dur"])
            path = (e.get("args") or {}).get("path")
            if path:
                a["paths"][path] = a["paths"].get(path, 0) + 1
        lines = ["{:<40} {:>8} {:>12} {:>12} {:>12}  {}".format(
            "Name", "Calls", "Total(us)", "Avg(us)", "Max(us)",
            "Paths(path=calls)")]
        for name, a in sorted(agg.items(), key=lambda kv: -kv[1]["total"]):
            paths = ",".join(f"{k}={v}"
                             for k, v in sorted(a["paths"].items()))
            lines.append(
                "{:<40} {:>8} {:>12.1f} {:>12.1f} {:>12.1f}  {}".format(
                    name[:40], a["calls"], a["total"],
                    a["total"] / a["calls"], a["max"], paths))
        serving = metrics.snapshot("serving.")
        # the family registers at import time, so gate the section on
        # actual serving activity, not mere registration
        if serving and serving.get("serving.steps"):
            # Serving / SLO view: the always-on serving.* registry
            # family (TTFT / ITL histograms, queue/slot/KV gauges,
            # admit/decode/preempt counters) — docs/SERVING.md
            lines.append("")
            lines.append("{:-^72}".format(" Serving / SLO View "))
            lines.append("{:<36} {}".format("metric", "value"))
            for name in sorted(serving):
                v = serving[name]
                if isinstance(v, dict):
                    desc = f"count={v['count']}"
                    if v["count"]:
                        desc += (f" avg={v['avg']:.6g}"
                                 f" min={v['min']:.6g}"
                                 f" max={v['max']:.6g}"
                                 f" p50={v['p50']:.6g}"
                                 f" p95={v['p95']:.6g}"
                                 f" p99={v['p99']:.6g}")
                else:
                    desc = str(v)
                lines.append("{:<36} {}".format(name, desc))
            lines.extend(_slow_requests_view(serving))
        full_snap = metrics.snapshot()
        lines.extend(_capacity_view(full_snap))
        lines.extend(_goodput_view(full_snap))
        lines.extend(_overload_view(full_snap))
        lines.extend(_cold_start_view(full_snap))
        lines.extend(_scorecard_view())
        lines.extend(_recent_incidents_view())
        if self._memory_samples:
            # MemoryView (reference profiler_statistic.py memory table)
            lines.append("")
            lines.append("{:-^72}".format(" Memory View "))
            lines.append("{:<8} {:>14} {:>14} {:>18} {:>12}".format(
                "Step", "LiveArrays", "LiveBytes", "DeviceInUse", "Peak"))
            for s in self._memory_samples:
                lines.append(
                    "{:<8} {:>14} {:>14} {:>18} {:>12}".format(
                        s["step"], s["live_arrays"], s["live_bytes"],
                        s.get("device_bytes_in_use", "-"),
                        s.get("device_peak_bytes", "-")))
        table = "\n".join(lines)
        print(table)
        return table

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
