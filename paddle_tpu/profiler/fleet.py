"""Fleet observatory: replica registry, metric federation, health
scoring, and the aggregator that serves them.

Every observability surface below this module is per-process
(`/metrics`, `/alerts`, `/traces`); nothing can answer "how is the
FLEET doing" or "which replica should stop taking traffic". This layer
makes a set of serving processes observable as one fleet — the
prerequisite the multi-replica router (ROADMAP "zero-cold-start fleet
serving") consumes:

- **Replica registry** — each replica's ``ServingEngine.
  serve_metrics(store=...)`` self-registers its scrape address +
  identity (replica_id, host, pid, start_ts, git_sha) in the existing
  ``distributed/store.TCPStore`` under a unique slot
  (``fleet/member/<n>``, ``n`` from the atomic ``fleet/seq`` counter —
  no CAS needed), and a :class:`Registrar` heartbeat re-sets the entry
  every ``FLAGS_fleet_ttl_s / 3`` seconds. Heartbeat/registration ride
  ``core/resilience`` retry policies; a dead replica simply stops
  heartbeating and AGES OUT instead of wedging the aggregator.
- **Federation** — :class:`FleetAggregator` scrapes every registered
  replica's ``/metrics`` (``profiler/export.parse_prometheus``, which
  round-trips exemplars), merges counters by sum and histograms
  bucket-wise (:func:`merge_scrapes`), preserves per-replica series
  under ``replica_id`` labels, computes fleet-level SLO percentiles
  (:func:`percentile_from_buckets`) and goodput from the merged
  series, and serves ``/fleet/metrics`` / ``/fleet/replicas`` /
  ``/fleet/alerts`` / ``/fleet/traces/<id>`` from a
  :class:`FleetServer` (MetricsServer-style stdlib HTTP).
- **Health scoring** — :func:`health_score` is a PURE, documented
  function of a replica snapshot (burn rates, queue depth, KV
  headroom, compile-seconds share, heartbeat freshness) returning a
  routable weight in [0, 1] — exactly the weight/drain signal a
  router needs. :func:`snapshot_from_scrape` builds the snapshot from
  a parsed scrape.

Aggregator-side alert rules (edge-triggered, once per episode, flight-
recorded like ``profiler/alerts.py``):

- ``replica.down`` — a registered replica's heartbeat is older than
  the TTL, or its scrape failed: it leaves ``/fleet/replicas`` and the
  scrape set until it heartbeats again (re-registration resolves the
  incident).
- ``fleet.skew`` — one replica's TTFT p95 exceeds
  ``FLAGS_fleet_skew_ratio`` x the fleet median p95 (min-sample
  floored): the slow outlier a router should de-weight.

Disarmed (``FLAGS_fleet=0`` or no store passed) the whole layer is a
byte-for-byte no-op: no threads, no store traffic, every ``fleet.*``
counter silent — the prefix-cache/accounting revert convention
(tools/fleet_gate.py pins it).
"""

from __future__ import annotations

import json
import subprocess
import threading
import time
import urllib.request

from ..core import flags as flags_mod
from ..core import resilience
from ..testing import faults
from . import export as _export
from . import metrics as _metrics
from . import tracing as _tracing

__all__ = ["Registrar", "FleetAggregator", "FleetServer", "armed",
           "read_members", "merge_scrapes", "label_replica",
           "percentile_from_buckets", "health_score",
           "snapshot_from_scrape", "git_sha",
           "SEQ_KEY", "MEMBER_KEY_FMT"]

SEQ_KEY = "fleet/seq"
MEMBER_KEY_FMT = "fleet/member/{}"

_c_registered = _metrics.counter("fleet.registered")
_c_heartbeats = _metrics.counter("fleet.heartbeats")
_c_hb_errors = _metrics.counter("fleet.heartbeat_errors")
_c_deregistered = _metrics.counter("fleet.deregistered")
_c_scrapes = _metrics.counter("fleet.scrapes")
_c_scrape_errors = _metrics.counter("fleet.scrape_errors")
_c_aged_out = _metrics.counter("fleet.aged_out")
_c_fired = _metrics.counter("fleet.alerts.fired")
_c_resolved = _metrics.counter("fleet.alerts.resolved")
_g_live = _metrics.gauge("fleet.replicas.live")


def armed(store):
    """Fleet registration/aggregation is armed iff a store exists AND
    ``FLAGS_fleet`` is on — either missing makes every entry point a
    no-op (counter-silent, thread-free)."""
    return store is not None and bool(flags_mod.flag("FLAGS_fleet"))


_git_sha_cache = None


def git_sha():
    """Short HEAD sha ('unknown' without git) — registry payloads carry
    it so a rolling deploy's mixed-version fleet is visible from
    ``/fleet/replicas``."""
    global _git_sha_cache
    if _git_sha_cache is None:
        try:
            out = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True, timeout=10)
            sha = out.stdout.strip()
            _git_sha_cache = sha if out.returncode == 0 and sha \
                else "unknown"
        except Exception:  # noqa: BLE001 — identity must work without git
            _git_sha_cache = "unknown"
    return _git_sha_cache


# -- replica-side registry -------------------------------------------------

class Registrar:
    """Self-registration + TTL'd heartbeat for one replica.

    ``store`` is a connected TCPStore client; ``url`` the replica's
    scrape base (``http://host:port``); ``status_fn`` an optional
    zero-arg callable whose result (the engine lifecycle state) rides
    every heartbeat payload, so ``/fleet/replicas`` shows DRAINING
    within one beat. Registration claims a unique slot via the atomic
    ``fleet/seq`` counter, then writes ``fleet/member/<slot>``; the
    heartbeat re-writes it (fresh ``heartbeat_ts``) every ``ttl/3``
    seconds under the ``fleet.heartbeat`` retry policy. Beat failures
    degrade (``resilience.degrade('fleet.heartbeat')``) and the loop
    keeps trying — a flaky store must not kill a healthy replica; a
    DEAD replica's entry simply goes stale and ages out aggregator-
    side. ``deregister()`` (ServingEngine.drain/close) deletes the
    entry so routers drop the replica immediately instead of after a
    TTL."""

    def __init__(self, store, url, replica_id=None, ttl_s=None,
                 status_fn=None, role=None, extra_fn=None):
        ident = _metrics.replica_identity()
        self.store = store
        self.url = url
        self.replica_id = str(replica_id) if replica_id is not None \
            else ident["replica_id"]
        # serving role for disaggregated prefill/decode placement
        # (serving/disagg.py): "prefill", "decode", or "mixed". Default
        # "mixed" keeps existing fleets untouched — a mixed replica is a
        # candidate for every stage.
        self.role = "mixed" if role is None else str(role)
        self.ttl_s = float(flags_mod.flag("FLAGS_fleet_ttl_s")
                           if ttl_s is None else ttl_s)
        self._status_fn = status_fn
        # extra_fn: zero-arg callable whose dict merges into every
        # heartbeat payload (reserved keys win). Post-construction
        # contributors COMPOSE via add_extra — the remote handoff
        # plane (lease state), pool geometry, and the fleet cache
        # digest advertisement all ride the same beat
        self.extra_fn = extra_fn
        self._extra_fns = []
        self._ident = ident
        self._slot = None
        self._stop = threading.Event()
        self._thread = None
        self._adopted_identity = False
        self._beat_hooks = []

    def add_extra(self, fn):
        """Register another payload contributor: ``fn()``'s dict merges
        into every heartbeat after ``extra_fn`` (reserved keys and
        earlier contributors win — ``setdefault`` semantics, so
        contributors cannot clobber each other). Failures are dropped
        per-contributor and never stop beats. This is how several
        planes share one registrar: lease state
        (serving/disagg.register_rpc_engine), pool geometry
        (serving/fleet_cache.geometry_payload), digest advertisements
        (DigestPublisher.payload)."""
        self._extra_fns.append(fn)
        return fn

    def add_beat_hook(self, fn):
        """Run ``fn()`` once per heartbeat (best-effort, after the
        payload write) — periodic maintenance that should ride the
        replica's existing liveness cadence instead of owning a
        thread: serving/disagg.py renews/sweeps remote-handoff leases
        here, so orphan reclamation happens even with zero relay
        traffic. Failures degrade; the beat never stops."""
        self._beat_hooks.append(fn)
        return fn

    def _payload(self):
        p = {"replica_id": self.replica_id, "host": self._ident["host"],
             "pid": self._ident["pid"],
             "start_ts": self._ident["start_ts"],
             "git_sha": git_sha(), "url": self.url,
             "ttl_s": self.ttl_s, "slot": self._slot,
             "role": self.role,
             "heartbeat_ts": time.time()}
        fns = ([self.extra_fn] if self.extra_fn is not None else []) \
            + list(self._extra_fns)
        for fn in fns:
            try:
                extra = dict(fn())
            except Exception:  # noqa: BLE001 — optional payload axes
                extra = {}     # must never stop beats
            for k, v in extra.items():
                p.setdefault(k, v)
        if self._status_fn is not None:
            try:
                p["state"] = self._status_fn()
            except Exception:  # noqa: BLE001 — a broken view must not stop beats
                p["state"] = "UNKNOWN"
        return p

    def start(self):
        """Register synchronously (retried under the ``fleet.register``
        policy — rendezvous with a store that is still coming up), then
        start the heartbeat thread. Idempotent."""
        if self._thread is not None:
            return self
        def _register():
            self._slot = int(self.store.add(SEQ_KEY, 1))
            self.store.set(MEMBER_KEY_FMT.format(self._slot),
                           json.dumps(self._payload()))
        with _tracing.span("fleet.register", replica=self.replica_id):
            resilience.retry_call(
                _register,
                policy=resilience.policy("fleet.register"))
        _c_registered.inc()
        # adopt the registry name as the process identity (replica_info
        # series, dump() envelope) so scrapes and ledger dumps
        # cross-reference — first explicit name wins; a process hosting
        # SEVERAL replicas keeps the first (process identity is
        # inherently single-valued); deregister restores
        if not _metrics.replica_id_overridden():
            _metrics.set_replica_id(self.replica_id)
            self._adopted_identity = True
        self._thread = threading.Thread(
            target=self._beat_loop, name="paddle-tpu-fleet-heartbeat",
            daemon=True)
        self._thread.start()
        return self

    def _beat_loop(self):
        period = max(self.ttl_s / 3.0, 0.05)
        while not self._stop.wait(period):
            try:
                # two sites: the generic catalog entry, and a
                # per-replica member so a chaos scenario can kill ONE
                # replica's heartbeat in a shared process (the gate's
                # degraded-replica injection)
                faults.site("fleet.heartbeat")
                faults.site(f"fleet.heartbeat.{self.replica_id}")
                resilience.retry_call(
                    self.store.set,
                    MEMBER_KEY_FMT.format(self._slot),
                    json.dumps(self._payload()),
                    policy=resilience.policy("fleet.heartbeat",
                                             max_attempts=2))
                _c_heartbeats.inc()
            except Exception as e:  # noqa: BLE001 — keep beating through store flaps
                _c_hb_errors.inc()
                resilience.degrade("fleet.heartbeat", exc=e)
            for hook in list(self._beat_hooks):
                try:
                    hook()
                except Exception as e:  # noqa: BLE001 — maintenance
                    # riding the beat must never stop the beat
                    resilience.degrade("fleet.beat_hook", exc=e)

    def deregister(self):
        """Stop the heartbeat and delete the registry entry
        (best-effort — a gone store cannot block a drain). Idempotent."""
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5)
        self._thread = None
        try:
            self.store.delete_key(MEMBER_KEY_FMT.format(self._slot))
        except Exception as e:  # noqa: BLE001
            resilience.degrade("fleet.deregister", exc=e)
        if self._adopted_identity:
            _metrics.set_replica_id(None)
            self._adopted_identity = False
        _c_deregistered.inc()


# empty-slot probe backoff cap, in sweeps: a long-gone slot costs
# ~1/16th of a store round trip per sweep instead of one each —
# bounding scan cost by LIVE membership over a fleet's lifetime of
# deploys — while a resurrected slot (a GC'd entry whose replica is
# in fact still heartbeating) is rediscovered within the cap
SCAN_BACKOFF_CAP = 16


def read_members(store, scan_state=None):
    """Every registered member payload, slot order. Gaps (deregistered
    slots, GC'd entries, registrants that crashed between ``add`` and
    ``set``) and unparseable payloads are skipped — a half-written
    entry must not wedge the aggregator.

    ``scan_state`` (a dict the caller keeps across sweeps) applies
    exponential probe backoff to empty slots up to
    ``SCAN_BACKOFF_CAP`` sweeps, so the scan cost of a long-lived
    fleet tracks its live membership, not every registration that
    ever happened; a slot that re-appears (fresh registration is
    always a NEW slot, but a heartbeat can legitimately re-create a
    GC'd entry) resets its backoff on the next probe."""
    try:
        raw = store.try_get(SEQ_KEY)
        n = int(raw) if raw else 0
    except (ValueError, TypeError):
        return []
    if scan_state is None:
        scan_state = {}
    sweep = scan_state["sweep"] = scan_state.get("sweep", 0) + 1
    misses = scan_state.setdefault("misses", {})
    next_probe = scan_state.setdefault("next_probe", {})
    out = []
    for slot in range(1, n + 1):
        nxt = next_probe.get(slot)
        if nxt is not None and sweep < nxt:
            continue
        raw = store.try_get(MEMBER_KEY_FMT.format(slot))
        if raw is None:
            m = misses[slot] = misses.get(slot, 0) + 1
            next_probe[slot] = sweep + min(2 ** m, SCAN_BACKOFF_CAP)
            continue
        misses.pop(slot, None)
        next_probe.pop(slot, None)
        try:
            p = json.loads(raw)
        except ValueError:
            continue
        if isinstance(p, dict) and p.get("replica_id") and p.get("url"):
            out.append(p)
    return out


# -- federation (pure merge helpers) ---------------------------------------

def _deep_hist(e):
    return {**e, "buckets": dict(e.get("buckets") or {}),
            "exemplars": {le: dict(ex) for le, ex in
                          (e.get("exemplars") or {}).items()}}


def merge_scrapes(by_replica):
    """Merge parsed per-replica scrapes into one fleet-level parsed
    dict: counters and gauges sum (ratio-like gauges are better read
    per-replica — the labeled series keep them), histograms merge
    BUCKET-WISE (cumulative counts add le-by-le, so fleet percentiles
    come out of the merged buckets), ``sum``/``count`` add, and each
    bucket keeps the max-value exemplar across replicas (tagged with
    its origin ``replica_id``). Labeled series and ``replica_info``
    are per-origin by definition and do not aggregate — that covers
    both replica-labeled series AND the mesh-serving per-slice series
    (``serving.kv.*{slice="i"}``), which :func:`label_replica`
    instead re-labels with their origin replica for the federated
    exposition."""
    merged = {}
    for rid in sorted(by_replica):
        for key, e in by_replica[rid].items():
            if e.get("labels") or e.get("name", key) == "replica_info":
                continue
            kind = e.get("type", "gauge")
            m = merged.get(key)
            if m is None:
                merged[key] = _deep_hist(e) if kind == "histogram" \
                    else dict(e)
                if kind == "histogram":
                    for ex in merged[key]["exemplars"].values():
                        ex.setdefault("replica_id", rid)
                continue
            if kind == "histogram":
                for le, c in (e.get("buckets") or {}).items():
                    m["buckets"][le] = m["buckets"].get(le, 0) + c
                for f in ("sum", "count"):
                    if e.get(f) is not None:
                        m[f] = (m[f] or 0) + e[f]
                for le, ex in (e.get("exemplars") or {}).items():
                    cur = m["exemplars"].get(le)
                    if cur is None or ex.get("value", 0) > \
                            cur.get("value", 0):
                        m["exemplars"][le] = {**ex, "replica_id": rid}
            else:
                m["value"] = m.get("value", 0) + e.get("value", 0)
    return merged


def label_replica(parsed, rid):
    """Re-key one replica's parsed scrape with its ``replica_id``
    label: unlabeled series gain ``{replica_id="rid"}``; series that
    already carry labels (the mesh-serving per-slice KV gauges,
    ``serving.kv.*{slice="i"}``) keep their own labels and gain
    ``replica_id`` beside them — without this, two replicas' slice
    series would collide in the federated exposition. ``replica_info``
    rides as-is (its labels ARE the identity)."""
    out = {}
    for key, e in parsed.items():
        name = e.get("name", key)
        if name == "replica_info":
            out[key] = e
            continue
        labels = {**(e.get("labels") or {}), "replica_id": rid}
        e2 = _deep_hist(e) if e.get("type") == "histogram" else dict(e)
        e2["labels"] = labels
        out[name + _export._labelblock(labels)] = e2
    return out


# the bucket-interpolation math lives in profiler/metrics.py now (the
# scenario Window needs it too, and metrics is the import-cycle-safe
# home); re-exported here because the fleet observatory published it
# first and callers/tests pin this name
percentile_from_buckets = _metrics.percentile_from_buckets


# -- health scoring (pure) -------------------------------------------------

# component weights — sum to 1.0 (docs/OBSERVABILITY.md "Fleet
# observatory" documents the formula; change them there too)
W_BURN = 0.35       # SLO burn dominates: a burning replica is failing users
W_QUEUE = 0.25      # queue depth: backlog = admission latency
W_KV = 0.25         # KV headroom: a full pool preempts next
W_COMPILE = 0.15    # compile share: warming replicas serve jittery tails
QUEUE_SCALE = 8.0   # queue depth at which the queue component halves


def health_score(snap):
    """Routable health weight in ``[0, 1]`` — PURE and deterministic on
    a fixed snapshot dict (all keys optional, missing reads healthy)::

        score = freshness * ( W_BURN    * 1/(1 + max(ttft_burn, itl_burn))
                            + W_QUEUE   * 1/(1 + queue_depth/QUEUE_SCALE)
                            + W_KV      * (1 - kv_utilization)
                            + W_COMPILE * (1 - compile_share) )

    ``freshness`` is 1.0 while the heartbeat is within one beat period
    (``ttl/3``), decays linearly to 0.0 at the TTL, and is 0.0 past it
    — a silent replica routes to zero BEFORE it formally ages out.
    This is the router's weight/drain signal: 1.0 = idle healthy
    replica, 0.0 = do not send traffic."""
    burn = max(float(snap.get("ttft_burn", 0.0)),
               float(snap.get("itl_burn", 0.0)))
    h_burn = 1.0 / (1.0 + max(burn, 0.0))
    depth = max(float(snap.get("queue_depth", 0.0)), 0.0)
    h_queue = 1.0 / (1.0 + depth / QUEUE_SCALE)
    util = min(max(float(snap.get("kv_utilization", 0.0)), 0.0), 1.0)
    h_kv = 1.0 - util
    share = min(max(float(snap.get("compile_share", 0.0)), 0.0), 1.0)
    h_compile = 1.0 - share
    score = (W_BURN * h_burn + W_QUEUE * h_queue + W_KV * h_kv
             + W_COMPILE * h_compile)
    ttl = float(snap.get("ttl_s") or 0.0)
    age = max(float(snap.get("heartbeat_age_s", 0.0)), 0.0)
    if ttl > 0.0:
        beat = ttl / 3.0
        if age >= ttl:
            return 0.0
        if age > beat:
            score *= 1.0 - (age - beat) / (ttl - beat)
    return round(score, 6)


def _lifetime_bad_fraction(hist, budget_us):
    """Fraction of a scraped latency histogram's observations over the
    budget (cumulative buckets; budget snapped UP to the nearest bound,
    mirroring profiler/alerts.BurnRateRule)."""
    buckets = (hist or {}).get("buckets") or {}
    count = (hist or {}).get("count") or 0
    if not count:
        return 0.0
    bounds = sorted((_export._le_sort_key(le), c)
                    for le, c in buckets.items())
    cutoff_cum = None
    for bound, cum in bounds:
        if bound >= budget_us:
            cutoff_cum = cum
            break
    if cutoff_cum is None:
        return 0.0
    return max(0.0, 1.0 - cutoff_cum / count)


def snapshot_from_scrape(parsed, heartbeat_age_s=0.0, ttl_s=None,
                         uptime_s=None):
    """Build the :func:`health_score` input from a parsed ``/metrics``
    scrape. Burn rates are LIFETIME bad-fraction / error-budget (the
    aggregator is stateless across scrapes; windowed burn lives
    replica-side in /alerts), compile share is cumulative XLA compile
    seconds over the replica's uptime."""
    def g(key, default=0.0):
        e = parsed.get(key)
        return e.get("value", default) if e else default

    target = float(flags_mod.flag("FLAGS_slo_target"))
    denom = max(1.0 - target, 1e-9)
    ttft_bad = _lifetime_bad_fraction(
        parsed.get("serving_ttft_us"),
        float(flags_mod.flag("FLAGS_slo_ttft_budget_us")))
    itl_bad = _lifetime_bad_fraction(
        parsed.get("serving_itl_us"),
        float(flags_mod.flag("FLAGS_slo_itl_budget_us")))
    compile_s = (parsed.get("xla_compile_seconds") or {}).get("sum") or 0.0
    share = compile_s / uptime_s if uptime_s else 0.0
    return {"queue_depth": g("serving_queue_depth"),
            "running": g("serving_slots_running"),
            "kv_utilization": g("serving_kv_utilization"),
            "ttft_burn": ttft_bad / denom,
            "itl_burn": itl_bad / denom,
            "compile_share": share,
            "heartbeat_age_s": float(heartbeat_age_s),
            "ttl_s": ttl_s}


# -- the aggregator --------------------------------------------------------

SKEW_MIN_COUNT = 32   # per-replica TTFT observations before skew judges


class FleetAggregator:
    """Scrape + merge + judge the registered fleet. Discovery comes
    from ``store`` (the TTL'd registry) or a static ``replicas`` list
    of member dicts (``{"replica_id", "url"}``) for storeless setups.
    ``refresh()`` is rate-limited (``min_interval_s``) and try-locked
    like the /alerts nudge — N concurrent ``/fleet/*`` GETs cost one
    scrape sweep. All reads (:meth:`replicas_view`,
    :meth:`metrics_text`, :meth:`alerts_view`) serve the last
    refreshed state."""

    def __init__(self, store=None, replicas=None, ttl_s=None,
                 timeout_s=None, min_interval_s=1.0):
        self.store = store if store is not None \
            and bool(flags_mod.flag("FLAGS_fleet")) else None
        self.static = list(replicas or [])
        self.ttl_s = float(flags_mod.flag("FLAGS_fleet_ttl_s")
                           if ttl_s is None else ttl_s)
        self.timeout_s = float(
            flags_mod.flag("FLAGS_fleet_scrape_timeout_s")
            if timeout_s is None else timeout_s)
        self.min_interval_s = float(min_interval_s)
        self._lock = threading.Lock()        # state reads/writes
        self._refresh_lock = threading.Lock()  # one sweep at a time
        self._last_refresh = None
        self._state = {"replicas": [], "merged": {}, "per_replica": {},
                       "fleet": {}, "ts": None}
        self._active = {}       # incident key -> incident dict
        self._history = []
        self._scan_state = {}   # read_members dead/populated slot memo

    # -- discovery + scrape ---------------------------------------------

    def _members(self):
        if self.store is not None:
            return read_members(self.store, self._scan_state)
        return [dict(p) for p in self.static]

    def _gc_member(self, p):
        """Delete an entry stale beyond 3x its TTL so a crashed
        replica's slot does not linger in the scan forever. Runs AFTER
        the entry classified as down — even an entry first seen this
        stale fires its replica.down before aging out of the store."""
        try:
            self.store.delete_key(MEMBER_KEY_FMT.format(p.get("slot")))
            _c_aged_out.inc()
        except Exception:  # noqa: BLE001 — GC is best-effort
            pass

    def _http_json(self, url):
        with urllib.request.urlopen(url, timeout=self.timeout_s) as r:
            return json.loads(r.read().decode())

    def _scrape(self, member):
        faults.site("fleet.scrape")
        url = member["url"].rstrip("/") + "/metrics"
        with urllib.request.urlopen(url, timeout=self.timeout_s) as r:
            return _export.parse_prometheus(r.read().decode())

    def refresh(self, force=False):
        """One discovery + scrape + merge + judge sweep (rate-limited;
        ``force=True`` bypasses — tests and gates drive deterministic
        sweeps with it)."""
        now = time.monotonic()
        if not force and self._last_refresh is not None \
                and now - self._last_refresh < self.min_interval_s:
            return self._state
        if not self._refresh_lock.acquire(blocking=False):
            return self._state  # a concurrent GET is already sweeping
        try:
            return self._refresh_locked()
        finally:
            self._refresh_lock.release()

    def _refresh_locked(self):
        now_wall = time.time()
        members = self._members()
        live, parsed_by, down = [], {}, []
        for p in members:
            rid = p["replica_id"]
            hb = float(p.get("heartbeat_ts", now_wall))
            age = max(now_wall - hb, 0.0) if "heartbeat_ts" in p else 0.0
            ttl = float(p.get("ttl_s", self.ttl_s))
            if age > ttl:
                down.append((p, age, "heartbeat stale "
                             f"{age:.1f}s > ttl {ttl:.1f}s"))
                if self.store is not None and age > 3.0 * ttl:
                    self._gc_member(p)
                continue
            try:
                parsed = self._scrape(p)
                _c_scrapes.inc()
            except Exception as e:  # noqa: BLE001 — one bad replica must not kill the sweep
                _c_scrape_errors.inc()
                down.append((p, age, f"scrape failed: "
                             f"{type(e).__name__}: {e}"))
                continue
            snap = snapshot_from_scrape(
                parsed, heartbeat_age_s=age, ttl_s=ttl,
                uptime_s=max(now_wall - float(p.get("start_ts",
                                                    now_wall)), 1e-3))
            live.append({**p, "heartbeat_age_s": round(age, 3),
                         # serving role for disaggregated placement —
                         # pre-role payloads (old replicas) read "mixed"
                         "role": p.get("role", "mixed"),
                         "health": health_score(snap),
                         "health_snapshot": snap})
            parsed_by[rid] = parsed
        # per-replica /alerts union rides the SAME rate-limited sweep
        # (one nudge of each replica's AlertManager per refresh) so N
        # concurrent /fleet/alerts GETs serve cached state instead of
        # N serial HTTP fan-outs
        replica_alerts = {}
        for p in live:
            rid, url = p["replica_id"], p["url"]
            try:
                replica_alerts[rid] = self._http_json(
                    url.rstrip("/") + "/alerts")
            except Exception as e:  # noqa: BLE001 — one wedged replica, not the union
                replica_alerts[rid] = {
                    "error": f"{type(e).__name__}: {e}"}
        merged = merge_scrapes(parsed_by)
        fleet = self._fleet_summary(live, merged)
        self._judge(live, parsed_by, down)
        state = {"replicas": live, "merged": merged,
                 "per_replica": parsed_by, "fleet": fleet,
                 "replica_alerts": replica_alerts, "ts": now_wall}
        with self._lock:
            self._state = state
            self._last_refresh = time.monotonic()
        _g_live.set(len(live))
        return state

    @staticmethod
    def _fleet_summary(live, merged):
        out = {"replicas_live": len(live)}
        for name, key in (("ttft", "serving_ttft_us"),
                          ("itl", "serving_itl_us")):
            h = merged.get(key)
            if h and h.get("count"):
                for q, lbl in ((0.50, "p50"), (0.95, "p95"),
                               (0.99, "p99")):
                    v = percentile_from_buckets(h["buckets"], q)
                    if v is not None:
                        out[f"slo_{name}_{lbl}_us"] = round(v, 1)
        good = (merged.get("accounting_goodput_tokens") or {}).get(
            "value", 0.0)
        dev_us = (merged.get("accounting_device_us") or {}).get(
            "value", 0.0)
        if dev_us:
            out["goodput_tokens_per_device_s"] = round(
                good / (dev_us / 1e6), 3)
        return out

    # -- aggregator-side alert rules ------------------------------------

    def _judge(self, live, parsed_by, down):
        """Edge-triggered incidents, once per episode per replica."""
        for p, age, reason in down:
            self._fire(f"replica.down:{p['replica_id']}", "replica.down",
                       "page", {"replica_id": p["replica_id"],
                                "detail": reason,
                                "heartbeat_age_s": round(age, 3)})
        # resolve only on LIVE reappearance (a fresh heartbeat), never
        # on mere disappearance: a permanently-dead replica that the
        # registry GC'd past 3x TTL must keep its incident active —
        # the fleet is still short a replica until someone acts
        live_ids = {r["replica_id"] for r in live}
        for key in list(self._active):
            if key.startswith("replica.down:") and \
                    key.split(":", 1)[1] in live_ids:
                self._resolve(key)
        # fleet.skew: a replica's TTFT p95 far off the fleet median
        ratio = float(flags_mod.flag("FLAGS_fleet_skew_ratio"))
        p95s = {}
        for rid, parsed in parsed_by.items():
            h = parsed.get("serving_ttft_us")
            if h and (h.get("count") or 0) >= SKEW_MIN_COUNT:
                v = percentile_from_buckets(h["buckets"], 0.95)
                if v is not None:
                    p95s[rid] = v
        skewed = set()
        if len(p95s) >= 2:
            vals = sorted(p95s.values())
            median = vals[len(vals) // 2] if len(vals) % 2 else \
                0.5 * (vals[len(vals) // 2 - 1] + vals[len(vals) // 2])
            for rid, v in p95s.items():
                if median > 0 and v > ratio * median:
                    skewed.add(rid)
                    self._fire(
                        f"fleet.skew:{rid}", "fleet.skew", "warn",
                        {"replica_id": rid, "value": round(v, 1),
                         "threshold": round(ratio * median, 1),
                         "detail": (f"ttft p95 {v:.0f}us > {ratio}x "
                                    f"fleet median {median:.0f}us")})
        for key in list(self._active):
            if key.startswith("fleet.skew:") and \
                    key.split(":", 1)[1] not in skewed:
                self._resolve(key)

    def _fire(self, key, rule, severity, info):
        with self._lock:
            active = self._active.get(key)
            if active is not None:
                active.update(info)
                active["count"] += 1
                return
            inc = {"rule": rule, "severity": severity,
                   "since": time.time(), "count": 1, **info}
            self._active[key] = inc
        _c_fired.inc()
        try:
            from ..distributed import watchdog
            watchdog.record_event(
                f"alert.{rule}",
                meta={k: v for k, v in inc.items()
                      if k in ("severity", "detail", "replica_id",
                               "value", "threshold")},
                status="alert")
        except Exception:  # noqa: BLE001 — alerting must not break the sweep
            pass

    def _resolve(self, key):
        with self._lock:
            inc = self._active.pop(key, None)
            if inc is None:
                return
            inc["resolved"] = time.time()
            self._history.append(inc)
            del self._history[:-256]
        _c_resolved.inc()

    # -- endpoint bodies ------------------------------------------------

    def replicas_view(self):
        """/fleet/replicas body: live replicas (identity, state,
        heartbeat age, health score) + the fleet summary. Down
        replicas have aged out of this list — their incident is in
        /fleet/alerts."""
        with self._lock:
            st = self._state
            reps = [{k: v for k, v in r.items()
                     if k != "health_snapshot"} for r in st["replicas"]]
            return {"replicas": reps, "fleet": dict(st["fleet"]),
                    "ts": st["ts"]}

    def metrics_text(self):
        """/fleet/metrics body: one exposition holding the per-replica
        series (labeled ``replica_id``), the fleet-merged unlabeled
        aggregates, and the fleet summary gauges — everything
        ``parse_prometheus`` round-trips."""
        with self._lock:
            st = self._state
            per_replica = {rid: dict(parsed) for rid, parsed in
                           st["per_replica"].items()}
            merged = dict(st["merged"])
            fleet = dict(st["fleet"])
        expo = {}
        for rid in sorted(per_replica):
            expo.update(label_replica(per_replica[rid], rid))
        expo.update(merged)
        for k, v in fleet.items():
            expo[f"fleet_{k}"] = {"type": "gauge", "name": f"fleet_{k}",
                                  "value": v}
        return _export.render_parsed(expo)

    def alerts_view(self):
        """/fleet/alerts body: aggregator incidents (replica.down,
        fleet.skew) + the union of every live replica's own /alerts,
        both from the last rate-limited refresh sweep (a replica that
        could not answer reports ``error`` instead of wedging the
        union)."""
        with self._lock:
            agg = {"active": [dict(i) for i in self._active.values()],
                   "history": [dict(i) for i in self._history]}
            union = {rid: dict(body) for rid, body in
                     (self._state.get("replica_alerts") or {}).items()}
        return {"aggregator": agg, "replicas": union,
                "rules": [{"name": "replica.down", "severity": "page"},
                          {"name": "fleet.skew", "severity": "warn"}]}

    def trace(self, trace_id):
        """/fleet/traces/<id>: federated lookup — every live replica's
        ring is asked and the surviving spans merge into ONE
        Chrome/Perfetto dict, so a cross-replica request (rpc-stitched
        trace ids) is debuggable from one place. None when no replica
        holds the trace."""
        with self._lock:
            reps = [(r["replica_id"], r["url"])
                    for r in self._state["replicas"]]
        events, holders = [], []
        for rid, url in reps:
            try:
                body = self._http_json(
                    url.rstrip("/") + f"/traces/{trace_id}")
            except Exception:  # noqa: BLE001 — 404s and dead replicas both skip
                continue
            evs = body.get("traceEvents") or []
            if evs:
                for ev in evs:
                    ev.setdefault("args", {})["replica_id"] = rid
                events.extend(evs)
                holders.append(rid)
        if not events:
            return None
        events.sort(key=lambda ev: ev.get("ts", 0))
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "trace_id": trace_id, "replicas": holders}

    def active_alerts(self):
        with self._lock:
            return [dict(i) for i in self._active.values()]


class FleetServer:
    """Stdlib HTTP endpoint over a :class:`FleetAggregator`
    (MetricsServer-style: ephemeral ``port=0`` default — read ``.port``
    / ``url()``; ``close()`` stops it). Every GET nudges a rate-limited
    refresh, so a dashboard polling ``/fleet/metrics`` keeps the view
    fresh without an extra control loop."""

    def __init__(self, aggregator, port=0, host="127.0.0.1"):
        import http.server

        server = self
        self.aggregator = aggregator

        class _Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, body, ctype):
                data = body.encode() if isinstance(body, str) else body
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                try:
                    path = self.path.split("?", 1)[0].rstrip("/") or "/"
                    agg = server.aggregator
                    if path == "/fleet/metrics":
                        agg.refresh()
                        self._send(
                            200, agg.metrics_text(),
                            "application/openmetrics-text; "
                            "version=1.0.0; charset=utf-8")
                    elif path == "/fleet/replicas":
                        agg.refresh()
                        self._send(200, json.dumps(agg.replicas_view()),
                                   "application/json")
                    elif path == "/fleet/alerts":
                        agg.refresh()
                        self._send(200, json.dumps(agg.alerts_view()),
                                   "application/json")
                    elif path.startswith("/fleet/traces/"):
                        agg.refresh()
                        tid = path[len("/fleet/traces/"):]
                        trace = agg.trace(tid)
                        if trace is None:
                            self._send(404, json.dumps(
                                {"error": f"no replica holds trace "
                                          f"{tid!r}"}),
                                "application/json")
                        else:
                            self._send(200, json.dumps(trace),
                                       "application/json")
                    elif path == "/healthz":
                        st = agg.refresh()
                        self._send(200, json.dumps(
                            {"status": "ok", "ts": time.time(),
                             "replicas_live": len(st["replicas"])}),
                            "application/json")
                    else:
                        self._send(404, json.dumps(
                            {"error": f"no route {path!r}"}),
                            "application/json")
                except BrokenPipeError:
                    pass

        self._httpd = http.server.ThreadingHTTPServer((host, port),
                                                      _Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="paddle-tpu-fleet-http", daemon=True)
        self._thread.start()

    @property
    def address(self):
        return (self.host, self.port)

    def url(self, path="/fleet/replicas"):
        return f"http://{self.host}:{self.port}{path}"

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
