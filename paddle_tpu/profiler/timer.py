"""Throughput benchmark timer (reference: python/paddle/profiler/timer.py
— `Benchmark`, `benchmark()` reporting reader_cost/batch_cost/ips)."""

from __future__ import annotations

import time


class _Event:
    def __init__(self):
        self.reader_cost = 0.0
        self.batch_cost = 0.0
        self.total_samples = 0
        self.total_time = 0.0
        self._batch_start = None
        self._reader_start = None
        self.steps = 0

    @property
    def ips(self):
        return self.total_samples / self.total_time if self.total_time \
            else 0.0


class Benchmark:
    def __init__(self):
        self.current_event = _Event()
        self._enabled = False

    def begin(self):
        self._enabled = True
        self.current_event = _Event()

    def before_reader(self):
        self.current_event._reader_start = time.perf_counter()

    def after_reader(self):
        ev = self.current_event
        if ev._reader_start is not None:
            ev.reader_cost += time.perf_counter() - ev._reader_start
        if ev._batch_start is None:
            ev._batch_start = time.perf_counter()

    def after_step(self, num_samples=1):
        ev = self.current_event
        if ev._batch_start is not None:
            dt = time.perf_counter() - ev._batch_start
            ev.batch_cost += dt
            ev.total_time += dt
        ev.total_samples += num_samples
        ev.steps += 1
        ev._batch_start = time.perf_counter()

    def step_info(self, unit="samples"):
        ev = self.current_event
        steps = max(ev.steps, 1)
        return (f"reader_cost: {ev.reader_cost / steps:.5f} s, "
                f"batch_cost: {ev.batch_cost / steps:.5f} s, "
                f"ips: {ev.ips:.3f} {unit}/s")

    def end(self):
        self._enabled = False


_benchmark = Benchmark()


def benchmark():
    return _benchmark
