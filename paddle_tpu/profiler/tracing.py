"""Request-scoped tracing: always-on, sampled, bounded-overhead spans.

The metrics registry answers "how often / how slow on aggregate"; this
layer answers the question that follows every p99 spike: *which*
request, and where did its time go. A ``TraceContext`` (trace_id /
span_id) rides a ``contextvars.ContextVar`` through the code path that
serves one request; every instrumented slice (queue wait, prefill,
decode step, deferred flush, rpc dial, checkpoint write) records a
**span** — trace/span/parent ids, name, wall-clock start, duration,
thread, attrs — into a fixed-size per-process ring buffer.

Design rules (the ``testing/faults.py`` school):

- **Nearly free when disabled.** Every entry point gates on ONE module
  global (refreshed only when the flags epoch moves); a disabled
  ``span()`` is a flag read returning a preallocated null object.
- **Sampled at the root.** The sampling decision is made once per
  trace, at ``start_trace`` (``FLAGS_trace_sample`` fraction of
  requests); children of an unsampled root cost the same null path as
  disabled tracing, so steady-state overhead scales with the sample
  rate, not the traffic.
- **Bounded memory.** Spans land in a ring of ``FLAGS_trace_ring``
  slots; old traces age out instead of growing the host heap. Exports
  (`export_trace` / `export_ring`) render Chrome/Perfetto trace-event
  JSON from whatever the ring still holds.

Wire propagation: ``current_context()`` returns a small picklable dict
and ``attach(ctx)`` adopts it, so ``distributed/rpc.py`` can carry the
context across hosts — spans recorded on every host share one
trace_id and stitch into a single trace at export time.

Usage::

    from paddle_tpu.profiler import tracing

    root = tracing.start_trace("serving.request", rid=7)   # samples
    with tracing.span("prefill", parent=root, tokens=128):
        ...                                # nested spans auto-parent
    root.end("DONE")

    tracing.export_trace(root.trace_id)    # {"traceEvents": [...]}

The span catalog lives in docs/OBSERVABILITY.md; histograms link back
here via exemplars (profiler/metrics.py) and the /metrics endpoint
(profiler/export.py) serves ``/traces/<id>``.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import random
import threading
import time

from ..core import flags as flags_mod
from . import metrics as _metrics

__all__ = ["Span", "start_trace", "span", "record_span", "attach",
           "current_context", "current_trace_id", "get_trace",
           "trace_ids", "export_trace", "export_ring", "records",
           "enabled", "reset"]

# (trace_id, span_id) of the innermost active span on this
# thread/task; None = no sampled trace active
_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "paddle_tpu_trace", default=None)

_C_TRACES = _metrics.counter("trace.traces")
_C_SPANS = _metrics.counter("trace.spans")
_C_UNSAMPLED = _metrics.counter("trace.unsampled")


class _Ring:
    """Fixed-size span store: append overwrites the oldest slot. The
    lock guards only an index bump + one slot write (~same cost as a
    Counter.inc)."""

    __slots__ = ("cap", "_buf", "_n", "_lock")

    def __init__(self, cap):
        self.cap = max(int(cap), 1)
        self._buf = [None] * self.cap
        self._n = 0
        self._lock = threading.Lock()

    def append(self, rec):
        with self._lock:
            self._buf[self._n % self.cap] = rec
            self._n += 1

    def records(self):
        with self._lock:
            n, cap = self._n, self.cap
            if n <= cap:
                return list(self._buf[:n])
            i = n % cap
            return self._buf[i:] + self._buf[:i]

    def clear(self):
        with self._lock:
            self._buf = [None] * self.cap
            self._n = 0


# the disabled-path contract: span()/start_trace() read _ENABLED (one
# module global) after a one-int epoch compare; everything else is
# refreshed only when core.flags mutates
_ENABLED = True
_SAMPLE = 1.0
_EPOCH_SEEN = -1
_ring = _Ring(4096)
_refresh_lock = threading.Lock()


def _gate():
    if flags_mod.epoch() != _EPOCH_SEEN:
        _refresh()
    return _ENABLED


def _refresh():
    global _ENABLED, _SAMPLE, _EPOCH_SEEN, _ring
    with _refresh_lock:
        ep = flags_mod.epoch()
        sample = float(flags_mod.flag("FLAGS_trace_sample"))
        cap = int(flags_mod.flag("FLAGS_trace_ring"))
        if cap > 0 and cap != _ring.cap:
            _ring = _Ring(cap)  # resize drops history (rare, ops-only)
        _SAMPLE = sample
        _ENABLED = bool(flags_mod.flag("FLAGS_trace_enable")) \
            and sample > 0.0
        _EPOCH_SEEN = ep


def enabled():
    """True iff tracing is armed (flag on and sample rate > 0)."""
    return _gate()


# private RNG (urandom-seeded): user random.seed(k) — typically the
# SAME k on every host of a reproducible distributed launch — must not
# make hosts mint colliding trace ids or correlated sampling decisions,
# and tracing must not consume draws from the user's seeded stream
_rng = random.Random()


def _new_id():
    return f"{_rng.getrandbits(64):016x}"


class _NullSpan:
    """Preallocated no-op span: what every entry point returns when
    tracing is disabled or the trace was not sampled."""

    __slots__ = ()
    trace_id = None
    span_id = None
    recording = False

    def annotate(self, **attrs):
        pass

    def end(self, status="ok"):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __bool__(self):
        return False


NULL = _NullSpan()


class Span:
    """One recorded slice. Use as a context manager (sets the ambient
    context so nested spans auto-parent) or hold it and call ``end()``
    manually — the serving root span lives from submit to terminal
    status across threads, so it is held on the request."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "args",
                 "_wall_us", "_start_ns", "_ended", "_token")

    recording = True

    def __init__(self, trace_id, span_id, parent_id, name, args):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.args = args
        self._wall_us = time.time_ns() / 1000.0
        self._start_ns = time.perf_counter_ns()
        self._ended = False
        self._token = None

    def annotate(self, **attrs):
        """Attach attrs to the span (merged into args at record time)."""
        if self.args:
            self.args.update(attrs)
        else:
            self.args = attrs

    def end(self, status="ok"):
        """Record the span into the ring. Idempotent; ``status`` is a
        free-form label ("ok", "error", a terminal request status)."""
        if self._ended:
            return
        self._ended = True
        rec = {"trace": self.trace_id, "span": self.span_id,
               "parent": self.parent_id, "name": self.name,
               "ts": self._wall_us,
               "dur": (time.perf_counter_ns() - self._start_ns) / 1000.0,
               "tid": threading.get_ident(), "status": status}
        if self.args:
            rec["args"] = self.args
        _ring.append(rec)
        _C_SPANS.inc()

    def context(self):
        """Picklable propagation dict (rpc wire / cross-thread)."""
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    def __enter__(self):
        self._token = _CURRENT.set((self.trace_id, self.span_id))
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._token is not None:
            _CURRENT.reset(self._token)
            self._token = None
        self.end("ok" if exc_type is None else "error")
        return False


def start_trace(name, **attrs):
    """Open a ROOT span: mints a fresh trace_id and applies the
    sampling decision. Returns the null span when tracing is off or
    the trace lost the sample draw — children of an unsampled root
    no-op for free. Does NOT set the ambient context (roots are held
    across threads); use it as a ``with`` block or pass it as
    ``parent=`` explicitly."""
    if not _gate():
        return NULL
    if _SAMPLE < 1.0 and _rng.random() >= _SAMPLE:
        _C_UNSAMPLED.inc()
        return NULL
    _C_TRACES.inc()
    return Span(_new_id(), _new_id(), None, name, attrs or None)


def span(name, parent=None, **attrs):
    """Open a child span. Parent resolution: an explicit ``parent``
    (a Span or a propagation dict), else the ambient context; no
    parent anywhere -> the null span (a slice outside any trace is
    never recorded — that is what keeps disabled overhead flat)."""
    if not _gate():
        return NULL
    if parent is None:
        cur = _CURRENT.get()
        if cur is None:
            return NULL
        tid, psid = cur
    elif isinstance(parent, Span):
        tid, psid = parent.trace_id, parent.span_id
    elif isinstance(parent, dict):
        tid = parent.get("trace_id")
        if tid is None:
            return NULL
        psid = parent.get("span_id")
    else:  # NULL or anything non-recording
        return NULL
    return Span(tid, _new_id(), psid, name, attrs or None)


def record_span(name, parent, dur_us, **attrs):
    """Record a RETROACTIVE slice of ``dur_us`` ending now, under
    ``parent`` (a Span). Used where the duration is known only after
    the fact — queue wait, the per-request share of a batched decode
    step. No-op unless the parent is recording."""
    if not getattr(parent, "recording", False) or not _gate():
        return
    rec = {"trace": parent.trace_id, "span": _new_id(),
           "parent": parent.span_id, "name": name,
           "ts": time.time_ns() / 1000.0 - dur_us, "dur": float(dur_us),
           "tid": threading.get_ident(), "status": "ok"}
    if attrs:
        rec["args"] = attrs
    _ring.append(rec)
    _C_SPANS.inc()


@contextlib.contextmanager
def attach(ctx):
    """Adopt a propagated context for the duration of the block: the
    rpc server wraps remote-fn execution so multi-host spans stitch
    into the caller's trace, and the scheduler wraps per-request SLO
    observations so histogram exemplars capture the right trace_id.
    ``ctx`` is a Span, a ``current_context()`` dict, or None (no-op)."""
    if ctx is None or not _gate():
        yield
        return
    if isinstance(ctx, Span):
        pair = (ctx.trace_id, ctx.span_id)
    elif isinstance(ctx, dict) and ctx.get("trace_id"):
        pair = (ctx["trace_id"], ctx.get("span_id"))
    else:
        yield
        return
    token = _CURRENT.set(pair)
    try:
        yield
    finally:
        _CURRENT.reset(token)


def current_context():
    """Propagation dict for the ambient context, or None. Picklable —
    this is what rides the rpc wire."""
    cur = _CURRENT.get()
    if cur is None:
        return None
    return {"trace_id": cur[0], "span_id": cur[1]}


def current_trace_id():
    """The ambient trace_id or None — the exemplar source for
    profiler.metrics histograms (installed below) and the stamp on
    resilience/watchdog flight records."""
    cur = _CURRENT.get()
    return cur[0] if cur is not None else None


# -- reading the ring ------------------------------------------------------

def records():
    """Every span still in the ring, oldest first."""
    return [r for r in _ring.records() if r is not None]


def get_trace(trace_id):
    """All ring spans of one trace, by start time. A long-lived trace
    may have aged out partially — callers that need completeness
    export promptly (the /traces endpoint) or raise FLAGS_trace_ring."""
    return sorted((r for r in records() if r["trace"] == trace_id),
                  key=lambda r: r["ts"])


def trace_ids():
    """Distinct trace ids currently in the ring (most recent last)."""
    out, seen = [], set()
    for r in records():
        if r["trace"] not in seen:
            seen.add(r["trace"])
            out.append(r["trace"])
    return out


def _chrome_event(r):
    ev = {"name": r["name"], "ph": "X", "ts": r["ts"], "dur": r["dur"],
          "pid": os.getpid(), "tid": r["tid"], "cat": "trace",
          "args": {"trace_id": r["trace"], "span_id": r["span"],
                   "parent_id": r["parent"], "status": r["status"]}}
    if r.get("args"):
        ev["args"].update(r["args"])
    return ev


def export_trace(trace_id):
    """One trace as Chrome/Perfetto trace-event JSON (a plain dict —
    ``json.dump`` it, or serve it via the /traces/<id> endpoint)."""
    return {"traceEvents": [_chrome_event(r) for r in
                            get_trace(trace_id)],
            "displayTimeUnit": "ms", "trace_id": trace_id}


def export_ring():
    """The whole ring as one Chrome/Perfetto trace-event JSON dict —
    the post-mortem dump (every recent trace interleaved)."""
    return {"traceEvents": [_chrome_event(r) for r in records()],
            "displayTimeUnit": "ms"}


def reset():
    """Clear the ring (tests / between benchmark runs)."""
    _ring.clear()


# histograms capture the ambient trace_id as a bucket exemplar — wire
# the probe here so metrics.py never imports tracing (no cycle)
_metrics._set_trace_id_source(current_trace_id)
