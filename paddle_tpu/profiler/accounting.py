"""Per-request cost attribution, engine goodput, and capacity accounting.

Iteration-level batching bills ONE device step to many concurrent
requests (the decode dispatch is a single jitted program for every live
slot), so "how much did this request cost" is not a measurement — it is
an *attribution policy*. This module implements the policy the serving
scheduler applies every step:

- **Token-proportional apportionment.** Each step's measured wall time
  is split across the requests that did work that step, in proportion
  to the tokens they prefilled/decoded. A prefill of 64 computed tokens
  weighs 64; a decode weighs 1. The split is exact by construction:
  per-step attributed shares + directly-billed compile time + the idle
  remainder of empty steps always sum to the measured step time
  (``tools/accounting_gate.py`` and tests pin this closure property).
- **Compile billed to the trigger.** XLA compile seconds observed
  during a request's prefill (a fresh bucket) bill to THAT request's
  ``compile_us``, not the batch — the first request of a bucket pays
  for warming it. Decode-program compiles split across that step's
  decode participants.
- **Re-prefill billed to the preemption.** A preempted victim's
  re-prefill work lands in ``reprefill_us`` (and the engine-level
  ``accounting.reprefill_us`` waste counter), not ``prefill_us`` — the
  cost of the preemption event stays visible instead of inflating the
  request's apparent prefill price.
- **Prefix hits billed at extend-only cost.** A cache-hitting request's
  prefill note carries only its computed (uncovered, bucketed) tokens,
  so covered tokens are free in the apportionment — exactly mirroring
  the zero-FLOPs-for-covered-blocks contract of the prefix cache.

Each request accumulates a :class:`CostReport` (exposed as
``RequestHandle.cost()``); the engine aggregates **goodput** —
deadline-met tokens per measured device-second of engine stepping
(attributed + compile + idle) — plus tokens/s and an
MFU estimate from model-config FLOPs. Capacity accounting folds the KV
pool occupancy breakdown (active/shared/cached-free/free) and live-array
HBM sampling into gauges and the "Capacity View" / "Goodput" sections of
``profiler.summary()``.

Disarmed (``FLAGS_serving_accounting=0``, read at Scheduler
construction) the scheduler holds the preallocated :data:`NULL`
accountant whose methods are no-ops — the per-step overhead is a few
attribute lookups (``tools/accounting_gate.py`` pins the budget, the
``testing/faults.py``/tracing school of nearly-free-when-off).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from . import metrics as _metrics

__all__ = ["CostReport", "Accountant", "NULL", "flops_per_token",
           "matmul_params", "detect_peak_flops"]

# engine-level aggregates (registry: rendered by the summary "Goodput"
# section, scraped from /metrics; multiple engines sum into one family)
_c_steps = _metrics.counter("accounting.steps")
_c_device_us = _metrics.counter("accounting.device_us")
_c_attributed_us = _metrics.counter("accounting.attributed_us")
_c_compile_us = _metrics.counter("accounting.compile_us")
_c_reprefill_us = _metrics.counter("accounting.reprefill_us")
_c_idle_us = _metrics.counter("accounting.idle_us")
_c_tokens = _metrics.counter("accounting.tokens_emitted")
_c_processed = _metrics.counter("accounting.tokens_processed")
_c_goodput = _metrics.counter("accounting.goodput_tokens")
_c_missed = _metrics.counter("accounting.deadline_missed_tokens")
# compile seconds the AOT cache saved (serving/aot_cache.py): an
# INFORMATIONAL axis beside the closure — saved time never happened,
# so it is not part of attributed + compile + idle == step_us
_c_aot_saved = _metrics.counter("accounting.aot_saved_us")
_g_mfu = _metrics.gauge("accounting.mfu")
_g_active = _metrics.gauge("serving.kv.active_blocks")
_g_free = _metrics.gauge("serving.kv.free_blocks")
_g_pool_bytes = _metrics.gauge("serving.kv.pool_bytes")
_g_live_bytes = _metrics.gauge("memory.live_bytes")
_g_live_arrays = _metrics.gauge("memory.live_arrays")


class CostReport:
    """One request's accumulated cost attribution. All time fields are
    microseconds of *attributed device-step wall time* (they sum across
    concurrent requests to the engine's measured step time — see module
    docstring), except ``queue_us``/``ttft_us`` which are this
    request's own wall-clock latencies."""

    __slots__ = ("rid", "status", "queue_us", "prefill_us",
                 "reprefill_us", "decode_us", "compile_us",
                 "aot_saved_us", "ttft_us", "transfer_us",
                 "transfer_bytes", "relay_us",
                 "tokens_prefilled", "tokens_decoded", "tokens_emitted",
                 "covered_tokens", "spec_proposed", "spec_accepted",
                 "preempts", "steps", "deadline_met")

    def __init__(self, rid):
        self.rid = rid
        self.status = None          # terminal RequestStatus, set at finish
        self.queue_us = 0.0
        self.prefill_us = 0.0       # attributed first-prefill share
        self.reprefill_us = 0.0     # attributed preemption re-prefill share
        self.decode_us = 0.0        # attributed decode-step shares
        self.compile_us = 0.0       # XLA compiles this request triggered
        self.aot_saved_us = 0.0     # compile time an AOT-cache hit avoided
        #                             (informational: NOT in attributed_us —
        #                             saved time was never on the device)
        self.ttft_us = None
        self.transfer_us = 0.0      # disaggregated KV handoff wall time
        #                             (informational, like aot_saved_us:
        #                             fabric time, not device-step time)
        self.transfer_bytes = 0     # KV bytes moved for the handoff
        self.relay_us = 0.0         # cross-process token-relay serve time
        #                             (remote handoffs, serving/disagg.py:
        #                             decode-side pull handling — another
        #                             informational fabric axis, NEVER in
        #                             attributed_us; transfer_us semantics
        #                             are unchanged by it)
        self.tokens_prefilled = 0   # computed (padded) prefill tokens
        self.tokens_decoded = 0     # batched decode steps participated in
        self.tokens_emitted = 0     # tokens streamed (prefill + decode)
        self.covered_tokens = 0     # prefix-cache tokens served for free
        self.spec_proposed = 0      # draft tokens verified for this request
        self.spec_accepted = 0      # ...of which greedy decode accepted
        self.preempts = 0
        self.steps = 0              # scheduler steps this request was billed
        self.deadline_met = None    # None: no deadline; else bool

    @property
    def attributed_us(self):
        """Total device time billed to this request."""
        return (self.prefill_us + self.reprefill_us + self.decode_us
                + self.compile_us)

    def as_dict(self):
        return {k: getattr(self, k) for k in self.__slots__} | {
            "attributed_us": self.attributed_us}

    def clone(self):
        c = CostReport(self.rid)
        for k in self.__slots__:
            setattr(c, k, getattr(self, k))
        return c

    def summary(self):
        """One human line: the per-request bill."""
        dl = "" if self.deadline_met is None else \
            f" deadline_met={self.deadline_met}"
        ttft = f"{self.ttft_us / 1000.0:.1f}ms" \
            if self.ttft_us is not None else "n/a"
        return (f"rid={self.rid} status={self.status} "
                f"queue={self.queue_us / 1000.0:.1f}ms ttft={ttft} | "
                f"attributed={self.attributed_us / 1000.0:.2f}ms "
                f"(prefill={self.prefill_us / 1000.0:.2f} "
                f"decode={self.decode_us / 1000.0:.2f} "
                f"compile={self.compile_us / 1000.0:.2f} "
                f"reprefill={self.reprefill_us / 1000.0:.2f}) | "
                f"tokens={self.tokens_emitted} "
                f"prefilled={self.tokens_prefilled} "
                f"covered={self.covered_tokens} "
                + (f"spec={self.spec_accepted}/{self.spec_proposed} "
                   if self.spec_proposed else "")
                + f"preempts={self.preempts}{dl}")

    def __repr__(self):
        return f"CostReport({self.summary()})"


# -- model FLOPs / MFU ------------------------------------------------------

def matmul_params(config):
    """Matmul-participating parameter count from a transformer config
    (attention projections + MLP + LM head; norms/embeddings excluded
    as they do no per-token matmul FLOPs). Works for any config with
    the Llama/GPT field names; returns None if fields are missing."""
    try:
        h = config.hidden_size
        head_dim = h // config.num_heads
        per_layer = (2 * h * config.num_heads * head_dim          # q, o
                     + 2 * h * config.num_kv_heads * head_dim     # k, v
                     + 3 * h * config.intermediate_size)          # mlp
        return (config.num_layers * per_layer
                + config.vocab_size * h)                          # lm head
    except AttributeError:
        return None


def flops_per_token(config):
    """Forward FLOPs per generated token: 2 x matmul params (the
    standard lower-bound estimate; attention-score FLOPs grow with
    context and are excluded, so the MFU derived from this is slightly
    optimistic at long context). None when the config is unknown."""
    p = matmul_params(config)
    return None if p is None else 2.0 * p


# bf16 peak FLOPs by device kind substring (lowercase); an estimate for
# the MFU gauge, overridable via ACCOUNTING_PEAK_FLOPS
_PEAK_FLOPS = (
    ("v5 lite", 197e12), ("v5e", 197e12),
    ("v5p", 459e12), ("v5", 459e12),
    ("v6", 918e12), ("trillium", 918e12),
    ("v4", 275e12), ("v3", 123e12),
)


def detect_peak_flops():
    """Peak device FLOPs for the MFU estimate: the
    ``ACCOUNTING_PEAK_FLOPS`` env override, else a device-kind table;
    None (MFU unreported) on CPU or unknown hardware."""
    env = os.environ.get("ACCOUNTING_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    try:
        import jax
        dev = jax.devices()[0]
        if dev.platform == "cpu":
            return None
        kind = getattr(dev, "device_kind", "").lower()
        for sub, peak in _PEAK_FLOPS:
            if sub in kind:
                return peak
    except Exception:  # noqa: BLE001 — accounting must never break serving
        pass
    return None


class _Note:
    """One unit of per-step work awaiting apportionment."""

    __slots__ = ("req", "kind", "tokens", "compile_us", "aot_saved_us",
                 "emitted")

    def __init__(self, req, kind, tokens, compile_us=0.0,
                 aot_saved_us=0.0, emitted=1):
        self.req = req
        self.kind = kind          # "prefill" | "reprefill" | "decode"
        self.tokens = tokens      # apportionment weight (computed positions)
        self.compile_us = compile_us
        self.aot_saved_us = aot_saved_us
        self.emitted = emitted    # tokens streamed to the caller


# how often (seconds) update_capacity re-scans jax.live_arrays() — the
# scan is O(live arrays), so it is time-throttled, not per-step
_HBM_SAMPLE_S = 2.0


class Accountant:
    """Per-engine cost attribution state machine. The scheduler drives
    it: ``step_begin`` -> ``note_*`` during the step -> ``step_end``
    (apportionment) and ``on_finish`` at each terminal status. NOT
    thread-safe by itself — the scheduler's caller serializes steps
    (serving.frontend holds the engine lock)."""

    armed = True

    def __init__(self, config=None, peak_flops=None, step_log_cap=2048):
        self.flops_per_token = flops_per_token(config) \
            if config is not None else None
        self.peak_flops = peak_flops if peak_flops is not None \
            else detect_peak_flops()
        # engine-local totals (registry counters aggregate engines)
        self.device_us = 0.0
        self.attributed_us = 0.0
        self.compile_us = 0.0
        self.aot_saved_us = 0.0
        self.reprefill_us = 0.0
        self.idle_us = 0.0
        self.tokens_emitted = 0    # tokens streamed to callers
        self.tokens_processed = 0  # computed (padded) prefill + decode
        self.goodput_tokens = 0
        self.missed_tokens = 0
        self.requests_finished = 0
        # per-step closure log (tests + the accounting gate read it)
        self.step_log = deque(maxlen=step_log_cap)
        self._notes = []
        self._decode_compile_us = 0.0
        self._decode_aot_saved_us = 0.0
        self._last_hbm_sample = 0.0
        self._lock = threading.Lock()  # guards engine_report vs step_end

    # -- scheduler hooks (one step = begin .. notes .. end) ---------------

    def attach(self, req):
        """Bind a fresh CostReport at submit time."""
        req.cost = CostReport(req.rid)

    def step_begin(self):
        self._notes = []
        self._decode_compile_us = 0.0
        self._decode_aot_saved_us = 0.0

    def note_queue_wait(self, req, wait_us):
        if req.cost is not None:
            req.cost.queue_us = float(wait_us)

    def note_prefill(self, req, computed_tokens, covered, compile_us,
                     reprefill, aot_saved_us=0.0):
        """A prefill ran for ``req`` this step: ``computed_tokens`` is
        the padded tail it actually computed (covered prefix tokens are
        NOT in it — they are free), ``compile_us`` any XLA compile its
        dispatch triggered (billed direct to this request), and
        ``aot_saved_us`` any compile time an AOT-cache hit AVOIDED
        (credited to this request, kept outside the closure sum —
        saved time never ran on the device)."""
        kind = "reprefill" if reprefill else "prefill"
        self._notes.append(_Note(req, kind, max(int(computed_tokens), 1),
                                 float(compile_us),
                                 float(aot_saved_us)))
        c = req.cost
        if c is not None:
            c.tokens_prefilled += int(computed_tokens)
            c.covered_tokens += int(covered)
            c.tokens_emitted += 1

    def note_decode(self, req):
        """``req`` received one token from this step's batched decode."""
        self._notes.append(_Note(req, "decode", 1))
        c = req.cost
        if c is not None:
            c.tokens_decoded += 1
            c.tokens_emitted += 1

    def note_spec(self, req, emitted, proposed, accepted):
        """``req`` participated in this step's speculative verify sweep
        (scheduler ``_decode_spec``): the device computed ``1 +
        proposed`` positions for it — THE apportionment weight, so
        wasted (rejected) draft positions bill real device time to the
        request that speculated them — and ``emitted`` tokens (1 +
        accepted drafts, eos-truncated) streamed to the caller. A
        spec step with zero proposals never reaches here (the
        scheduler falls back to the plain decode note)."""
        self._notes.append(_Note(req, "decode", 1 + int(proposed),
                                 emitted=int(emitted)))
        c = req.cost
        if c is not None:
            c.tokens_decoded += int(emitted)
            c.tokens_emitted += int(emitted)
            c.spec_proposed += int(proposed)
            c.spec_accepted += int(accepted)

    def note_transfer(self, req, transfer_us, transfer_bytes):
        """``req`` arrived via a disaggregated KV handoff
        (``Scheduler.admit_handoff``): bill the fabric time and bytes
        to its cost report. Informational like ``aot_saved_us`` — the
        transfer ran on the wire, not the device, so it stays outside
        the step-closure sum; the decode replica carries it because
        that is where the handed-off request lands."""
        c = req.cost
        if c is not None:
            c.transfer_us += float(transfer_us)
            c.transfer_bytes += int(transfer_bytes)

    def note_relay(self, req, relay_us):
        """``req`` is being served to a REMOTE caller over the token
        relay (disagg ``_rpc_pull``): bill this pull's decode-side
        handling time. Informational like ``transfer_us`` — wire
        bookkeeping, not device time, outside the step-closure sum."""
        c = req.cost
        if c is not None:
            c.relay_us += float(relay_us)

    def note_decode_compile(self, compile_us):
        """XLA compile observed around the batched decode dispatch
        (engine warmup): split across this step's decode participants."""
        if compile_us > 0.0:
            self._decode_compile_us += float(compile_us)

    def note_decode_aot_saved(self, saved_us):
        """Compile time an AOT-cache hit avoided around the batched
        decode dispatch: split across this step's decode participants,
        like :meth:`note_decode_compile` (informational axis)."""
        if saved_us > 0.0:
            self._decode_aot_saved_us += float(saved_us)

    def step_end(self, step_us):
        """Apportion the measured step wall time: direct compile bills
        first (clamped to the step), the remainder splits across notes
        in proportion to tokens. The closure invariant — attributed +
        compile + idle == step_us exactly (modulo float) — holds by
        construction and is what the tests/gate pin."""
        step_us = float(step_us)
        notes = self._notes
        dec_notes = sum(1 for n in notes if n.kind == "decode")
        if dec_notes and self._decode_compile_us > 0.0:
            share = self._decode_compile_us / dec_notes
            for n in notes:
                if n.kind == "decode":
                    n.compile_us += share
        if dec_notes and self._decode_aot_saved_us > 0.0:
            share = self._decode_aot_saved_us / dec_notes
            for n in notes:
                if n.kind == "decode":
                    n.aot_saved_us += share
        if not dec_notes and self._decode_compile_us > 0.0:
            # no decode participants (can't happen today): keep closure
            # by treating it as part of the idle remainder
            pass
        total_compile = sum(n.compile_us for n in notes)
        total_saved = sum(n.aot_saved_us for n in notes)
        scale = 1.0
        if total_compile > step_us:
            # jax's compile clock can disagree with our step clock at
            # the edge; scale bills down so attribution never exceeds
            # the measured step (scale 0 when the step clock floored)
            scale = step_us / total_compile
        direct = min(total_compile * scale, step_us)
        remainder = step_us - direct
        total_tokens = sum(n.tokens for n in notes)
        attributed = 0.0
        reprefill = 0.0
        stepped = set()  # a request billed twice this step (prefill +
        #                  decode) still participated in ONE step
        for n in notes:
            share = remainder * (n.tokens / total_tokens) \
                if total_tokens else 0.0
            bill = n.compile_us * scale
            c = n.req.cost
            if c is not None:
                if n.kind == "prefill":
                    c.prefill_us += share
                elif n.kind == "reprefill":
                    c.reprefill_us += share
                else:
                    c.decode_us += share
                c.compile_us += bill
                # savings bill UNSCALED: they are not wall time of this
                # step, so the closure clamp never applies to them
                c.aot_saved_us += n.aot_saved_us
                if id(c) not in stepped:
                    stepped.add(id(c))
                    c.steps += 1
            attributed += share
            if n.kind == "reprefill":
                reprefill += share
        idle = step_us - attributed - direct if not notes else 0.0
        # emitted counts tokens STREAMED to callers (a speculative
        # decode note streams 1 + accepted per request); the token-
        # proportional weights (padded prefill tails, computed spec
        # positions) are a different axis, tracked as "processed"
        emitted = sum(n.emitted for n in notes)
        with self._lock:
            self.device_us += step_us
            self.attributed_us += attributed
            self.compile_us += direct
            self.aot_saved_us += total_saved
            self.reprefill_us += reprefill
            self.idle_us += idle
            self.tokens_emitted += emitted
            self.tokens_processed += total_tokens
        self.step_log.append({"step_us": step_us,
                              "attributed_us": attributed,
                              "compile_us": direct, "idle_us": idle,
                              "aot_saved_us": total_saved,
                              "notes": len(notes)})
        _c_steps.inc()
        _c_device_us.inc(step_us)
        _c_attributed_us.inc(attributed)
        _c_compile_us.inc(direct)
        if total_saved:
            _c_aot_saved.inc(total_saved)
        _c_reprefill_us.inc(reprefill)
        _c_idle_us.inc(idle)
        if notes:
            _c_tokens.inc(emitted)
            _c_processed.inc(total_tokens)
        self._notes = []
        self._decode_compile_us = 0.0
        self._decode_aot_saved_us = 0.0

    def on_finish(self, req, status):
        """Finalize the request's report at its terminal status and
        fold it into goodput: deadline-met tokens count toward the
        numerator (no deadline + DONE counts as met)."""
        c = req.cost
        if c is None:
            return
        c.status = status
        c.preempts = req.preempts
        if req.first_token_at is not None:
            c.ttft_us = (req.first_token_at - req.submitted_at) * 1e6
        tokens = len(req.generated)
        met = None
        if status == "DONE":
            met = True if req.deadline is None \
                else not req.deadline.expired()
        elif req.deadline is not None and req.deadline.expired():
            # a cancel/error BEFORE the deadline passed is not a miss —
            # the outcome stays None (undefined), like deadline-less
            met = False
        c.deadline_met = met
        with self._lock:
            self.requests_finished += 1
            if status == "DONE" and met is not False:
                self.goodput_tokens += tokens
                _c_goodput.inc(tokens)
            elif met is False:
                # only genuine deadline outcomes land here — tokens of
                # deadline-LESS cancels/errors are simply not goodput,
                # they are not "missed deadlines"
                self.missed_tokens += tokens
                _c_missed.inc(tokens)

    # -- capacity accounting ----------------------------------------------

    def update_capacity(self, cache):
        """Refresh the KV-occupancy gauges from the pool's host
        metadata (cheap, every step) and — time-throttled — sample
        live-array HBM. Also keeps the MFU gauge live (a scraped
        engine must not need someone to call engine_report() first).
        Returns the occupancy dict."""
        occ = cache.occupancy()
        _g_active.set(occ["active"])
        _g_free.set(occ["free"])
        _g_pool_bytes.set(cache.pool_bytes())
        if self.flops_per_token and self.peak_flops and self.device_us:
            _g_mfu.set(round(
                (self.tokens_processed / (self.device_us / 1e6))
                * self.flops_per_token / self.peak_flops, 6))
        now = time.monotonic()
        if now - self._last_hbm_sample >= _HBM_SAMPLE_S:
            self._last_hbm_sample = now
            self._sample_hbm()
        return occ

    @staticmethod
    def _sample_hbm():
        try:
            import jax
            arrs = [a for a in jax.live_arrays()
                    if getattr(a, "is_deleted", lambda: False)() is False]
            _g_live_arrays.set(len(arrs))
            _g_live_bytes.set(sum(int(getattr(a, "nbytes", 0))
                                  for a in arrs))
        except Exception:  # noqa: BLE001 — sampling must never break a step
            pass

    # -- aggregates -------------------------------------------------------

    def engine_report(self):
        """Engine-level goodput: deadline-met tokens per MEASURED
        device-second (the denominator includes direct compile and
        idle steps — they are real engine cost), raw tokens/s, and the
        model-FLOPs MFU estimate (None without a known peak). Safe to
        call from any thread."""
        with self._lock:
            device_s = self.device_us / 1e6
            tokens = self.tokens_emitted
            goodput_tokens = self.goodput_tokens
            rep = {"device_s": device_s,
                   "tokens": tokens,
                   "tokens_processed": self.tokens_processed,
                   "goodput_tokens": goodput_tokens,
                   "missed_tokens": self.missed_tokens,
                   "requests_finished": self.requests_finished,
                   "attributed_us": self.attributed_us,
                   "compile_us": self.compile_us,
                   "aot_saved_us": self.aot_saved_us,
                   "reprefill_us": self.reprefill_us,
                   "idle_us": self.idle_us}
        tps = tokens / device_s if device_s > 0 else 0.0
        rep["tokens_per_device_s"] = tps
        rep["goodput_tokens_per_device_s"] = \
            goodput_tokens / device_s if device_s > 0 else 0.0
        mfu = None
        if self.flops_per_token and self.peak_flops and device_s > 0:
            # MFU measures COMPUTE utilization, so it runs on the
            # processed-token axis (padded prefill tails included) —
            # emitted tokens/s would undercount prefill FLOPs entirely
            mfu = (rep["tokens_processed"] / device_s) \
                * self.flops_per_token / self.peak_flops
            _g_mfu.set(round(mfu, 6))
        rep["mfu"] = mfu
        return rep

    def goodput_line(self):
        """The one-line engine summary (examples print it at exit)."""
        r = self.engine_report()
        mfu = f"{r['mfu']:.3f}" if r["mfu"] is not None else "n/a"
        return (f"goodput: {r['goodput_tokens_per_device_s']:.1f} "
                f"deadline-met tok/s over {r['device_s']:.2f} device-s "
                f"({r['tokens_per_device_s']:.1f} tok/s raw, "
                f"mfu~{mfu}; compile {r['compile_us'] / 1000:.1f}ms, "
                f"reprefill waste {r['reprefill_us'] / 1000:.1f}ms, "
                f"idle {r['idle_us'] / 1000:.1f}ms)")


class _NullAccountant(Accountant):
    """Disarmed accounting: every scheduler hook is a no-op (the
    nearly-free-when-off contract, pinned by tools/accounting_gate.py).
    ``req.cost`` stays None, so ``RequestHandle.cost()`` returns None."""

    armed = False

    def __init__(self):  # no registry traffic, no config math
        pass

    def attach(self, req):
        pass

    def step_begin(self):
        pass

    def note_queue_wait(self, req, wait_us):
        pass

    def note_prefill(self, req, computed_tokens, covered, compile_us,
                     reprefill, aot_saved_us=0.0):
        pass

    def note_decode(self, req):
        pass

    def note_spec(self, req, emitted, proposed, accepted):
        pass

    def note_transfer(self, req, transfer_us, transfer_bytes):
        pass

    def note_relay(self, req, relay_us):
        pass

    def note_decode_compile(self, compile_us):
        pass

    def note_decode_aot_saved(self, saved_us):
        pass

    def step_end(self, step_us):
        pass

    def on_finish(self, req, status):
        pass

    def update_capacity(self, cache):
        pass

    def engine_report(self):
        return None

    def goodput_line(self):
        return "goodput: accounting disarmed (FLAGS_serving_accounting=0)"


NULL = _NullAccountant()
