"""SLO burn-rate alerts over the metrics registry.

The registry answers "what is the level"; dashboards answer "what was
the trend"; neither pages anyone. This layer turns the existing
``serving.*`` family into **incidents**: rolling-window rules evaluated
over per-second :class:`~paddle_tpu.profiler.export.DeltaRates` (each
evaluation diffs against the previous one, so a window is simply the
time between evaluations — the scheduler nudges ``maybe_evaluate``
every step, rate-limited by ``FLAGS_alert_interval_s``).

Rule catalog (docs/OBSERVABILITY.md "Alerts"):

- ``slo.ttft_burn`` / ``slo.itl_burn`` — error-budget burn rate: with
  an SLO of "``FLAGS_slo_target`` of observations under
  ``FLAGS_slo_{ttft,itl}_budget_us``", the burn rate is
  bad-fraction / (1 - target); >= ``FLAGS_alert_burn_threshold`` fires
  (1.0 = consuming the whole budget exactly as fast as it accrues).
  Fractions come from histogram bucket deltas, so the budget snaps to
  the nearest bucket bound at or above it.
- ``queue.growth`` — the admission queue is at least
  ``FLAGS_alert_queue_depth`` deep AND grew over the window (positive
  gauge derivative): demand is outrunning capacity.
- ``decode.stall`` — live slots exist and the scheduler is stepping,
  yet zero tokens decoded over the window: a livelocked engine. (An
  engine that stopped stepping entirely reads as idle here — driver
  death is /healthz's signal.) Fires exactly once per stall episode —
  the incident stays active until progress resumes, then resolves; a
  later stall opens a fresh incident.
- ``shed.rate`` — the overload controller (serving/overload.py) shed
  load over the window (``serving.shed`` moved): capacity is being
  exceeded and low-priority traffic dropped. Once per shedding
  episode, worst recent queue-wait exemplar stamped.

Firing is edge-triggered: an incident is recorded ONCE at the
transition into firing (a watchdog flight record tagged
``alert.<rule>``, stamped with the worst-offender trace_id from the
histogram exemplars where one exists), stays in ``active()`` while the
condition holds, and moves to history with a ``resolved`` timestamp on
recovery. ``MetricsServer`` serves the whole state from ``/alerts``.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ..core import flags as flags_mod
from . import export as _export
from . import metrics as _metrics
from . import tracing as _tracing

__all__ = ["AlertRule", "BurnRateRule", "QueueGrowthRule", "StallRule",
           "ShedRateRule", "AlertManager", "default_rules"]

_c_fired = _metrics.counter("alerts.fired")
_c_resolved = _metrics.counter("alerts.resolved")
_c_errors = _metrics.counter("alerts.rule_errors")
_c_evals = _metrics.counter("alerts.evaluations")


class AlertRule:
    """One named condition. ``evaluate(ctx)`` returns ``(firing,
    info)`` where info carries at least ``detail`` (human line) and
    optionally ``value``/``threshold``/``trace_id``. ``ctx`` is
    ``{"rates", "snap", "dt"}`` — per-second delta rates (histogram
    buckets included), the current snapshot, and the window seconds."""

    name = "rule"
    severity = "warn"

    def evaluate(self, ctx):  # pragma: no cover — interface
        raise NotImplementedError


def _worst_exemplar(snap, hist, max_age_s=None):
    """trace_id of the worst RECENT exemplar of ``hist`` — the concrete
    offender an incident should point at. Exemplars are max-value-ever
    per bucket and never age, so without the freshness filter an
    incident could name a cold-start trace from hours ago whose spans
    already rotated out of the ring (a /traces 404 for the operator)."""
    exs = (snap.get(hist) or {}).get("exemplars") or {}
    floor = time.time() - max_age_s if max_age_s else None
    worst = None
    for ex in exs.values():
        if not ex.get("trace_id"):
            continue
        if floor is not None and ex.get("ts", 0) < floor:
            continue
        if worst is None or ex["value"] > worst["value"]:
            worst = ex
    return worst["trace_id"] if worst else None


def _exemplar_age(ctx):
    """Freshness horizon for incident trace stamps: a couple of
    evaluation windows (floored so short test windows still resolve)."""
    return max(2.0 * ctx["dt"], 60.0)


class BurnRateRule(AlertRule):
    """Error-budget burn over one latency histogram."""

    severity = "page"

    def __init__(self, name, hist, budget_flag, min_samples=3):
        self.name = name
        self.hist = hist
        self.budget_flag = budget_flag
        self.min_samples = min_samples

    def evaluate(self, ctx):
        rates, dt = ctx["rates"], ctx["dt"]
        crate = rates.get(self.hist + ".count", 0.0)
        if crate * dt < self.min_samples:
            return False, {}
        budget = float(flags_mod.flag(self.budget_flag))
        target = float(flags_mod.flag("FLAGS_slo_target"))
        threshold = float(flags_mod.flag("FLAGS_alert_burn_threshold"))
        prefix = self.hist + ".le."
        buckets = []
        for key, r in rates.items():
            if key.startswith(prefix):
                label = key[len(prefix):]
                buckets.append((float("inf") if label == "+inf"
                                else float(label), r))
        # snap the budget UP to the nearest bucket bound at or above it
        # (bucket counts can't split below their bound; snapping down
        # would count in-SLO observations as budget burn)
        cutoff = min((b for b, _ in buckets if b >= budget),
                     default=float("inf"))
        # +inf <= cutoff only when the budget itself snapped to +inf —
        # then everything is within budget by definition
        good = sum(r for b, r in buckets if b <= cutoff)
        bad_frac = max(0.0, 1.0 - good / crate)
        burn = bad_frac / max(1.0 - target, 1e-9)
        if burn < threshold:
            return False, {}
        return True, {
            "value": round(burn, 3), "threshold": threshold,
            "trace_id": _worst_exemplar(ctx["snap"], self.hist,
                                        _exemplar_age(ctx)),
            "detail": (f"{bad_frac:.1%} of {self.hist} over "
                       f"{budget:.0f}us budget (burn {burn:.2f}x, "
                       f"target {target})")}


class QueueGrowthRule(AlertRule):
    """Admission queue deep AND growing over the window."""

    name = "queue.growth"

    def evaluate(self, ctx):
        depth = ctx["snap"].get("serving.queue.depth", 0)
        floor = int(flags_mod.flag("FLAGS_alert_queue_depth"))
        growth = ctx["rates"].get("serving.queue.depth", 0.0)
        if depth < floor or growth <= 0.0:
            return False, {}
        return True, {
            "value": depth, "threshold": floor,
            "trace_id": _worst_exemplar(ctx["snap"],
                                        "serving.queue_wait_us",
                                        _exemplar_age(ctx)),
            "detail": (f"queue depth {depth} >= {floor} and growing "
                       f"{growth:+.2f}/s — demand outrunning capacity")}


class StallRule(AlertRule):
    """Live slots, the scheduler IS stepping, yet zero decode progress
    across the window: a genuine livelock (admission churn, device
    returning without tokens). A driver that stopped stepping entirely
    is a different failure — /healthz engine liveness catches that —
    and a caller-driven engine paused between step() calls is healthy,
    so zero steps in the window must read as idle, not wedged."""

    name = "decode.stall"
    severity = "page"

    def evaluate(self, ctx):
        running = ctx["snap"].get("serving.slots.running", 0)
        if running < 1 or ctx["dt"] <= 0.0:
            return False, {}
        if ctx["rates"].get("serving.steps", 0.0) <= 0.0:
            return False, {}  # not being driven: idle, not stalled
        if ctx["rates"].get("serving.decoded_tokens", 0.0) > 0.0:
            return False, {}
        return True, {
            "value": running,
            "trace_id": _worst_exemplar(ctx["snap"], "serving.itl_us",
                                        _exemplar_age(ctx)),
            "detail": (f"{running} running slot(s) decoded ZERO tokens "
                       f"over {ctx['dt']:.1f}s — engine stalled")}


class ShedRateRule(AlertRule):
    """The overload controller is actively shedding load
    (serving/overload.py): the ``serving.shed`` counter moved over the
    window. Any nonzero rate pages — shedding is correct behavior
    under overload, but an operator must know capacity is being
    exceeded while it happens. Edge-triggered like every rule: one
    incident per shedding episode (the flight record stamps the worst
    RECENT queue-wait exemplar's trace — the concrete request class
    that was waiting while sheds ran), resolved when sheds stop."""

    name = "shed.rate"
    severity = "page"

    def evaluate(self, ctx):
        rate = ctx["rates"].get("serving.shed", 0.0)
        if rate <= 0.0:
            return False, {}
        return True, {
            "value": round(rate, 3),
            "trace_id": _worst_exemplar(ctx["snap"],
                                        "serving.queue_wait_us",
                                        _exemplar_age(ctx)),
            "detail": (f"shedding {rate:.2f} req/s over "
                       f"{ctx['dt']:.1f}s — demand exceeds capacity, "
                       "low-priority traffic is being dropped")}


def default_rules():
    return [
        BurnRateRule("slo.ttft_burn", "serving.ttft_us",
                     "FLAGS_slo_ttft_budget_us"),
        BurnRateRule("slo.itl_burn", "serving.itl_us",
                     "FLAGS_slo_itl_budget_us"),
        QueueGrowthRule(),
        StallRule(),
        ShedRateRule(),
    ]


class AlertManager:
    """Edge-triggered rule evaluation + incident store. Thread-safe.

    Scope: rules read the PROCESS-GLOBAL ``serving.*`` registry family
    (like every serving metric since the SLO telemetry landed), so with
    several engines in one process the incidents describe the process
    aggregate, not one engine — e.g. a stalled engine is masked while a
    sibling keeps decoding. One manager per engine exists only so each
    engine's scheduler/endpoint has something to nudge/serve; per-engine
    attribution needs labeled metrics (a known limitation, see
    docs/OBSERVABILITY.md). The scheduler nudges ``maybe_evaluate``
    each step; ``/alerts`` serves ``as_dict()``."""

    def __init__(self, rules=None, history_cap=256):
        self.rules = list(rules) if rules is not None else default_rules()
        self._delta = _export.DeltaRates("serving.", include_buckets=True)
        self._active = {}             # rule name -> incident dict
        self._history = deque(maxlen=history_cap)
        self._last = None             # monotonic ts of last evaluation
        self._lock = threading.Lock()

    def maybe_evaluate(self):
        """Evaluate iff at least ``FLAGS_alert_interval_s`` elapsed
        since the previous evaluation (the per-step nudge: one clock
        read + compare when it's not time yet). The interval re-checks
        UNDER the lock — two racing nudges (a /alerts GET + a scheduler
        step) must not produce a near-zero window whose empty rates
        would spuriously resolve active incidents. The nudge acquires
        the lock NON-blocking: concurrent scrapers (the fleet
        aggregator + a human + a gate all polling /alerts) must not
        convoy behind one evaluation and each pay — whoever loses the
        race skips, the winner's evaluation covered the window."""
        interval = float(flags_mod.flag("FLAGS_alert_interval_s"))
        last = self._last
        if last is not None and time.monotonic() - last < interval:
            return []  # cheap unlocked fast path (per-step cost)
        if not self._lock.acquire(blocking=False):
            return []  # a concurrent nudge is already evaluating
        try:
            return self._evaluate_locked(min_interval=interval)
        finally:
            self._lock.release()

    def evaluate(self, min_interval=0.0):
        """Run every rule over the window since the last evaluation.
        Returns the incidents that NEWLY fired (empty on the priming
        call, while incidents merely stay active, and when
        ``min_interval`` has not elapsed — the race-free rate limit)."""
        with self._lock:
            return self._evaluate_locked(min_interval)

    def _evaluate_locked(self, min_interval=0.0):
        now = time.monotonic()
        dt = (now - self._last) if self._last is not None else 0.0
        if min_interval and self._last is not None \
                and dt < min_interval:
            return []  # lost the race to a concurrent evaluation
        rates = self._delta.rates()
        self._last = now
        _c_evals.inc()  # an actual window consumed (incl. priming)
        if not rates:
            return []  # priming call: no window to judge yet
        snap = _metrics.snapshot("serving.")
        ctx = {"rates": rates, "snap": snap, "dt": dt}
        fired = []
        for rule in self.rules:
            try:
                firing, info = rule.evaluate(ctx)
            except Exception:  # noqa: BLE001 — a broken rule must not kill serving
                _c_errors.inc()
                firing, info = False, {}
            active = self._active.get(rule.name)
            if firing and active is None:
                inc = {"rule": rule.name, "severity": rule.severity,
                       "since": time.time(), "count": 1, **info}
                self._active[rule.name] = inc
                fired.append(inc)
                _c_fired.inc()
                self._record(inc)
            elif firing:
                active.update(info)
                active["count"] += 1
            elif active is not None:
                active["resolved"] = time.time()
                self._history.append(active)
                del self._active[rule.name]
                _c_resolved.inc()
        return fired

    @staticmethod
    def _record(inc):
        """Flight-record the incident (once, at the firing edge),
        stamped with the offender trace_id where the rule found one."""
        try:
            from ..distributed import watchdog
        except Exception:  # noqa: BLE001 — alerting must never break serving
            return
        meta = {k: v for k, v in inc.items()
                if k in ("severity", "value", "threshold", "detail")}
        ctx = {"trace_id": inc["trace_id"]} if inc.get("trace_id") \
            else None
        with _tracing.attach(ctx):
            watchdog.record_event(f"alert.{inc['rule']}", meta=meta,
                                  status="alert")

    def active(self):
        with self._lock:
            return [dict(i) for i in self._active.values()]

    def history(self):
        with self._lock:
            return [dict(i) for i in self._history]

    def as_dict(self):
        """The /alerts endpoint body."""
        with self._lock:
            return {"active": [dict(i) for i in self._active.values()],
                    "history": [dict(i) for i in self._history],
                    "rules": [{"name": r.name, "severity": r.severity}
                              for r in self.rules],
                    "window_s": float(flags_mod.flag(
                        "FLAGS_alert_interval_s"))}
