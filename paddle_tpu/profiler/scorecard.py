"""Fleet-invariant scoreboard: run a loadgen scenario against an
in-process multi-replica fleet and grade each phase through
scenario-scoped metric Windows.

The control planes each shipped with their own contract — drains drop
nothing (PR 11), failover lands every request exactly once (PR 12),
the shed ladder protects high-priority goodput (PR 13), the prefix
cache turns shared openings into block hits (PR 8) — but every gate
proved its contract in isolation, on a hand-rolled corpus. This module
composes them: a :class:`FleetHarness` (N ``ServingEngine`` replicas
behind the ``Router``, overload plane armed) is driven by a
``serving.loadgen`` scenario, each phase measured by its own
``metrics.Window`` (never a registry reset — phases see exactly their
own slice), and the invariants are evaluated per phase:

- ``all_terminal``   every accepted request reaches a terminal status
                     (nothing is ever silently lost) — every phase;
- ``goodput_floor``  HIGH-class DONE fraction >= floor — any phase
                     that carried HIGH arrivals;
- ``zero_drop``      no accepted request ends ERROR or unresolved —
                     phases with a ``drain:<rid>`` action;
- ``exactly_once``   failover count == requests that moved replicas,
                     each landing DONE — phases with a ``kill:<rid>``
                     action;
- ``prefix_hit_rate`` windowed block hit-rate >= floor — phases whose
                     workload has shared-prefix locality.

Plus per-phase TTFT/ITL windowed percentiles and SLO burn (the same
bad-fraction/(1-target) math as profiler/alerts.py, over the window's
bucket deltas). The result is a structured per-phase scorecard dict:
:func:`record` keeps the latest for ``profiler.summary()``'s
"Scenario scorecard" section, :func:`fleet_load_metrics` flattens it
for the ``fleet_load`` ledger kind (tools/bench_ledger.py), and
``tools/fleet_load_gate.py`` turns it into CI pass/fail.
"""

from __future__ import annotations

import threading

from ..core import flags as flags_mod
from . import metrics

__all__ = ["FleetHarness", "run_scenario", "record", "latest",
           "fleet_load_metrics", "summary_lines", "slo_burn",
           "DEFAULT_FLOORS"]

# pass/fail floors the gate (and any caller) can override per run
DEFAULT_FLOORS = {
    "high_goodput": 0.9,      # HIGH-class DONE fraction under shed
    "prefix_hit_rate": 0.3,   # windowed block hit-rate under locality
}

_TERMINAL = ("DONE", "CANCELLED", "TIMEOUT", "SHED", "ERROR")
_CLEAN = ("DONE", "CANCELLED", "TIMEOUT", "SHED")

_c_runs = metrics.counter("scorecard.runs")
_c_failed = metrics.counter("scorecard.invariant_failures")
_g_last_ok = metrics.gauge("scorecard.last_ok")

_lock = threading.Lock()
_last_card = None


class FleetHarness:
    """N in-process replicas behind one Router — the PR 11-13 stack as
    a test fixture. Engines run in BACKGROUND mode (failover and drain
    need a live driver thread under each replica); greedy decode keeps
    outputs deterministic regardless of thread interleaving."""

    def __init__(self, model, n_replicas=2, rid_prefix="sc", **engine_kw):
        from ..serving import Router, ServingEngine

        engine_kw.setdefault("max_batch", 2)
        engine_kw.setdefault("block_size", 8)
        engine_kw.setdefault("max_seq_len", 64)
        engine_kw.setdefault("temperature", 0.0)
        engine_kw.setdefault("bucket_cap", 32)
        engine_kw.setdefault("max_queue", 64)
        engine_kw.setdefault("background", True)
        self.router = Router()
        self.engines = {}
        for i in range(int(n_replicas)):
            rid = f"{rid_prefix}{i}"
            eng = ServingEngine(model, **engine_kw)
            self.engines[rid] = eng
            self.router.add_replica(rid, engine=eng)
        self._killed = set()
        self._pending = []

    def shed_tune(self, min_queue=3, queue_frac=0.125):
        """Drop every replica's shed trip-point so a storm actually
        sheds at test scale (the defaults are sized for production
        queues) — same knobs tools/overload_gate.py turns."""
        for eng in self.engines.values():
            ov = eng.scheduler.overload
            ov.min_queue = min_queue
            ov.queue_frac = queue_frac

    def prime(self, prompt_lens=(5, 9), max_new_tokens=2, seed=97):
        """Warm every replica's jit programs and the overload plane's
        service-time model, so phase windows measure steady-state
        serving rather than first-compile noise."""
        import numpy as np

        rng = np.random.default_rng(seed)
        for eng in self.engines.values():
            for n in prompt_lens:
                h = eng.submit(rng.integers(0, 255, (n,)).astype("int64"),
                               max_new_tokens=max_new_tokens)
                h.result(timeout=300)

    def kill(self, rid):
        """Replica death the way a crashed device manifests: the next
        scheduler step raises, the driver thread dies, in-flight
        requests terminate ERROR — and RoutedHandle failover takes it
        from there (the same injection tests/framework/test_router.py
        pins)."""
        eng = self.engines[rid]
        self._killed.add(rid)
        eng._sched.step = lambda: (_ for _ in ()).throw(
            RuntimeError(f"injected replica death: {rid}"))

    def drain(self, rid, timeout=120):
        """Graceful drain through the Router (PR 11 contract: in-flight
        finishes, readiness flips, new traffic redistributes)."""
        self.router.drain(rid, timeout=timeout)

    def drain_async(self, rid, timeout=120):
        """Start a drain WITHOUT blocking the arrival stream — the
        "drain mid-storm" shape: readiness flips immediately (the
        drained replica stops taking new traffic) while the storm's
        remaining arrivals keep submitting and redistribute live.
        :meth:`join_pending` collects the outcome."""
        box = {}

        def _run():
            try:
                self.drain(rid, timeout=timeout)
            except Exception as e:  # noqa: BLE001 — reported at join
                box["error"] = e

        t = threading.Thread(target=_run, daemon=True,
                             name=f"scorecard-drain-{rid}")
        t.start()
        self._pending.append((t, box))

    def join_pending(self, timeout=300):
        """Wait for async actions; returns their errors (empty =
        every pending drain completed cleanly)."""
        errs = []
        for t, box in self._pending:
            t.join(timeout)
            if t.is_alive():
                errs.append(TimeoutError(
                    f"pending action {t.name} still running"))
            elif "error" in box:
                errs.append(box["error"])
        self._pending = []
        return errs

    def close(self):
        for rid, eng in self.engines.items():
            try:
                eng.close()
            except RuntimeError:
                if rid not in self._killed:
                    raise

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def slo_burn(hist_delta, budget_us, target=None):
    """Error-budget burn over ONE window's histogram delta — the
    alerts math (bad-fraction / (1 - target), budget snapped UP to the
    nearest bucket bound) applied to a scenario slice instead of a
    rate window. None when the window saw no observations."""
    if not hist_delta or not hist_delta.get("count"):
        return None
    if target is None:
        target = float(flags_mod.flag("FLAGS_slo_target"))
    cum = metrics.cumulative_buckets(hist_delta["buckets"])
    total = hist_delta["count"]
    bounds = sorted((metrics._le_sort_key(le), c) for le, c in cum.items())
    cutoff = min((b for b, _ in bounds if b >= float(budget_us)),
                 default=float("inf"))
    good = max((c for b, c in bounds if b <= cutoff), default=0)
    bad_frac = max(0.0, 1.0 - good / total)
    return bad_frac / max(1.0 - target, 1e-9)


def _do_action(harness, action):
    if not action:
        return
    verb, _, rid = str(action).partition(":")
    if verb == "kill":
        harness.kill(rid)
    elif verb == "drain":
        harness.drain_async(rid)
    else:
        raise ValueError(f"unknown scenario action {action!r}")


def _pct_block(win, name):
    h = win.hist(name)
    if not h or not h.get("count"):
        return None
    return {"count": h["count"], "p50": h["p50"], "p95": h["p95"],
            "p99": h["p99"]}


def _run_phase(harness, phase, precs, floors, vocab, timeout_s):
    """Drive one phase's records through the router, firing the
    phase action at the arrival midpoint, then wait every accepted
    handle to its terminal status BEFORE freezing the window — the
    window covers the phase's decode work, not just its arrivals."""
    from ..serving import loadgen

    win = metrics.Window(label=phase.name)
    placed, submitted = {}, [0]
    midpoint = max(len(precs) // 2, 1)

    def _submit(rec):
        h = harness.router.submit(
            loadgen.prompt_ids(rec, vocab),
            max_new_tokens=rec.max_new_tokens,
            priority=rec.priority, deadline_s=rec.deadline_s)
        placed[id(h)] = h.replica_id
        return h

    def _between():
        submitted[0] += 1
        if submitted[0] == midpoint:
            _do_action(harness, phase.action)

    outcomes = loadgen.replay(precs, _submit, between=_between)
    handles = [(rec, out) for rec, out in outcomes
               if not isinstance(out, Exception)]
    rejected = [(rec, out) for rec, out in outcomes
                if isinstance(out, Exception)]
    for _, h in handles:
        try:
            h.result(timeout=timeout_s)
        except Exception:  # noqa: BLE001 — a SHED/TIMEOUT/exhausted-
            # failover terminal is an OUTCOME the scorecard grades,
            # never a harness crash
            pass
    action_errors = harness.join_pending()
    win.freeze()
    return _grade_phase(phase, win, handles, rejected, placed, floors,
                        action_errors)


def _grade_phase(phase, win, handles, rejected, placed, floors,
                 action_errors=()):
    statuses = {}
    for _, h in handles:
        statuses[h.status] = statuses.get(h.status, 0) + 1
    moved = sum(1 for _, h in handles
                if placed.get(id(h)) not in (None, h.replica_id))
    high = [(rec, h) for rec, h in handles if rec.priority == 0]
    high_done = sum(1 for _, h in high if h.status == "DONE")
    goodput = (high_done / len(high)) if high else None
    hits = win.value("serving.prefix.hit_blocks")
    misses = win.value("serving.prefix.miss_blocks")
    hit_rate = hits / (hits + misses) if (hits + misses) else None
    ttft = win.hist("serving.ttft_us")
    itl = win.hist("serving.itl_us")
    card = {
        "phase": phase.name,
        "action": phase.action,
        "arrivals": len(handles) + len(rejected),
        "accepted": len(handles),
        "rejected": len(rejected),
        "statuses": statuses,
        "shed": win.value("serving.shed"),
        "failover": win.value("router.failover"),
        "moved": moved,
        "high_goodput": goodput,
        "prefix_hit_rate": hit_rate,
        "prefix_hits": hits,
        "prefix_misses": misses,
        "ttft_us": _pct_block(win, "serving.ttft_us"),
        "itl_us": _pct_block(win, "serving.itl_us"),
        "ttft_burn": slo_burn(
            ttft, flags_mod.flag("FLAGS_slo_ttft_budget_us")),
        "itl_burn": slo_burn(
            itl, flags_mod.flag("FLAGS_slo_itl_budget_us")),
        "elapsed_s": round(win.elapsed_s(), 4),
        "action_errors": [repr(e) for e in action_errors],
    }
    inv = {}
    lost = sum(1 for _, h in handles if h.status not in _TERMINAL)
    inv["all_terminal"] = {"ok": lost == 0, "value": lost, "floor": 0}
    if high:
        floor = floors["high_goodput"]
        inv["goodput_floor"] = {"ok": goodput >= floor,
                                "value": round(goodput, 4),
                                "floor": floor}
    verb = str(phase.action or "").partition(":")[0]
    if verb == "drain":
        # zero-drop: every accepted request ends clean AND the drain
        # itself completed gracefully (a died-mid-drain engine raises)
        dropped = sum(1 for _, h in handles if h.status not in _CLEAN)
        inv["zero_drop"] = {"ok": dropped == 0 and not action_errors,
                            "value": dropped, "floor": 0}
    if verb == "kill":
        errors = sum(1 for _, h in handles if h.status == "ERROR")
        ok = (card["failover"] == moved and moved >= 1 and errors == 0)
        inv["exactly_once"] = {
            "ok": ok, "value": {"failover": card["failover"],
                                "moved": moved, "errors": errors},
            "floor": "failover == moved >= 1, no ERROR terminals"}
    if phase.workload.locality > 0:
        floor = floors["prefix_hit_rate"]
        inv["prefix_hit_rate"] = {
            "ok": hit_rate is not None and hit_rate >= floor,
            "value": None if hit_rate is None else round(hit_rate, 4),
            "floor": floor}
    card["invariants"] = inv
    card["ok"] = all(v["ok"] for v in inv.values())
    return card


def run_scenario(harness, scenario, seed=0, *, floors=None, vocab=255,
                 timeout_s=300):
    """Schedule ``scenario`` at ``seed``, drive it phase by phase
    through ``harness``, and return the structured scorecard::

        {"scenario", "seed", "ok", "phases": [per-phase cards],
         "invariants": {name: worst-case verdict across phases}}

    The card is also :func:`record`-ed so ``profiler.summary()`` shows
    it and the ``scorecard.*`` counters move."""
    floors = {**DEFAULT_FLOORS, **(floors or {})}
    records = scenario.schedule(seed)
    by_phase = {}
    for r in records:
        by_phase.setdefault(r.phase, []).append(r)
    phase_cards = [
        _run_phase(harness, phase, by_phase.get(phase.name, []),
                   floors, vocab, timeout_s)
        for phase in scenario.phases]
    rollup = {}
    for pc in phase_cards:
        for name, v in pc["invariants"].items():
            cur = rollup.get(name)
            if cur is None or (cur["ok"] and not v["ok"]):
                rollup[name] = {**v, "phase": pc["phase"]}
    card = {"scenario": scenario.name, "seed": int(seed),
            "floors": floors, "phases": phase_cards,
            "invariants": rollup,
            "ok": all(pc["ok"] for pc in phase_cards)}
    record(card)
    return card


def record(card):
    """Publish a scorecard: keep it for :func:`latest` /
    ``profiler.summary()`` and move the always-on ``scorecard.*``
    counters (runs, invariant failures, last-run verdict)."""
    global _last_card
    with _lock:
        _last_card = card
    _c_runs.inc()
    failed = sum(1 for pc in card.get("phases", [])
                 for v in pc.get("invariants", {}).values()
                 if not v["ok"])
    if failed:
        _c_failed.inc(failed)
    _g_last_ok.set(1 if card.get("ok") else 0)
    return card


def latest():
    """The most recent scorecard published in this process (None
    before any :func:`run_scenario`/:func:`record`)."""
    with _lock:
        return _last_card


def fleet_load_metrics(card):
    """Flatten a scorecard into the ``fleet_load`` ledger shape
    (tools/bench_ledger.py): floors-facing numbers only, worst-case
    across phases, all flat floats so regression medians work."""
    phases = card.get("phases", [])
    goodputs = [pc["high_goodput"] for pc in phases
                if pc.get("high_goodput") is not None]
    # only phases GRADED on locality count toward the ledger floor: a
    # no-locality phase legitimately reads 0.0 (all cold misses) and
    # would poison the min
    hit_rates = [pc["prefix_hit_rate"] for pc in phases
                 if "prefix_hit_rate" in pc.get("invariants", {})
                 and pc.get("prefix_hit_rate") is not None]
    p95s = [pc["ttft_us"]["p95"] for pc in phases
            if pc.get("ttft_us") and pc["ttft_us"].get("p95") is not None]
    dropped = sum(pc["invariants"].get("zero_drop", {}).get("value", 0)
                  for pc in phases)
    out = {"scenario_ok": 1.0 if card.get("ok") else 0.0,
           "phases": float(len(phases)),
           "arrivals": float(sum(pc["arrivals"] for pc in phases)),
           "accepted": float(sum(pc["accepted"] for pc in phases)),
           "shed": float(sum(pc["shed"] for pc in phases)),
           "failover": float(sum(pc["failover"] for pc in phases)),
           "dropped": float(dropped)}
    if goodputs:
        out["high_goodput_frac"] = round(min(goodputs), 4)
    if hit_rates:
        out["prefix_hit_rate"] = round(min(hit_rates), 4)
    if p95s:
        out["ttft_p95_us"] = round(max(p95s), 1)
    return out


def _fmt(v):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.3g}"
    return str(v)


def summary_lines():
    """The "Scenario scorecard" section for ``profiler.summary()`` —
    empty (section hidden) until a scorecard ran in this process."""
    card = latest()
    if not card:
        return []
    lines = ["", "{:-^72}".format(" Scenario scorecard "),
             "scenario {!r} seed {} — {}".format(
                 card["scenario"], card["seed"],
                 "PASS" if card["ok"] else "FAIL"),
             "{:<10} {:>5} {:>5} {:>5} {:>8} {:>9} {:>9}  {}".format(
                 "phase", "arr", "acc", "shed", "goodput", "ttft_p95",
                 "hit_rate", "invariants")]
    for pc in card["phases"]:
        inv = " ".join(
            f"{name}={'ok' if v['ok'] else 'FAIL'}"
            for name, v in pc["invariants"].items())
        ttft = pc.get("ttft_us") or {}
        lines.append(
            "{:<10} {:>5} {:>5} {:>5} {:>8} {:>9} {:>9}  {}".format(
                pc["phase"][:10], pc["arrivals"], pc["accepted"],
                pc["shed"], _fmt(pc["high_goodput"]),
                _fmt(ttft.get("p95")), _fmt(pc["prefix_hit_rate"]),
                inv))
    return lines
