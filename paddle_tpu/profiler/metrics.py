"""Always-on runtime metrics registry.

The reference ships a profiler that must be armed to see anything; the
questions that actually come up in production ("is the lazy-vjp cache
hitting", "how often do deferred chains flush", "is jit recompiling every
step") need counters that are ALWAYS live, cost ~a dict hit + int add per
event, and can be snapshotted at any moment without pausing the program.

Three instrument kinds, Prometheus-shaped:

- ``Counter``   — monotone event count (``inc``)
- ``Gauge``     — last-write-wins level (``set`` / ``add``)
- ``Histogram`` — value distribution (``observe``): count / sum / min /
  max plus fixed-bound bucket counts

All mutation is lock-guarded (instrumented code runs from worker threads
— e.g. DataLoader workers dispatching ops), and ``snapshot()`` returns a
deep copy so a reader never observes later mutation.

Usage::

    from paddle_tpu.profiler import metrics
    metrics.counter("my.events").inc()
    metrics.histogram("my.latency_us").observe(dt)
    print(metrics.dump())          # human-readable table
    metrics.snapshot()             # {name: value | dict} plain data

XLA compile telemetry rides on ``jax.monitoring``: importing this module
subscribes a listener that folds ``/jax/core/compile/*`` durations into
``xla.compile.count`` / ``xla.compile.seconds`` — every backend compile
is counted no matter which layer (deferred chains, lazy-vjp jits, user
``jax.jit``) triggered it.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time

__all__ = ["Counter", "Gauge", "Histogram", "counter", "gauge",
           "histogram", "snapshot", "dump", "reset", "registry",
           "thread_compile_seconds", "replica_identity",
           "set_replica_id", "label_key", "Window", "window_delta",
           "cumulative_buckets", "percentile_from_buckets"]


def _esc_label_value(v):
    """Label-value escaping per the exposition format (backslash,
    double quote, newline). The canonical implementation lives here —
    ``profiler.export`` aliases it (export depends on this module, so
    the reverse import would cycle)."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _label_body(labels):
    """Sorted-key, escaped ``k="v",...`` body of a label block — the
    one canonical form shared by :func:`label_key` and the exposition
    renderer (``profiler.export._labelblock``)."""
    return ",".join(f'{k}="{_esc_label_value(v)}"'
                    for k, v in sorted(labels.items()))


def label_key(name, labels):
    """Canonical registry key for a labeled series:
    ``name{k="v",...}`` with sorted keys and escaped values — the same
    label-block canonicalization ``profiler.export`` renders and
    parses (modulo its dot->underscore metric-name mangling), so a
    labeled gauge round-trips through a scrape with its labels
    intact."""
    if not labels:
        return name
    return name + "{" + _label_body(labels) + "}"


# -- replica identity ------------------------------------------------------
# once more than one serving process exists, a metrics dump or a scrape
# is meaningless without knowing WHICH replica produced it. The identity
# is process-scoped (the registry is process-global); fleet registration
# (profiler/fleet.py) reuses it and may override replica_id per
# registration when several replicas share a process (tests, gates).

_START_TS = time.time()
try:
    _HOST = socket.gethostname()
except Exception:  # noqa: BLE001 — identity must never break import
    _HOST = "localhost"
_replica_id = None
_identity_lock = threading.Lock()


def set_replica_id(replica_id):
    """Override the process replica id (None restores the default
    ``<host>-<pid>``). Fleet registration (profiler/fleet.Registrar)
    adopts its replica_id here when nothing set one yet, so the
    ``replica_info`` series and ``dump()`` envelope agree with the
    registry name in the one-replica-per-process case."""
    global _replica_id
    with _identity_lock:
        _replica_id = str(replica_id) if replica_id is not None else None


def replica_id_overridden():
    """True iff an explicit replica id is set (vs the host-pid
    default) — fleet registration only adopts its name when not."""
    with _identity_lock:
        return _replica_id is not None


def replica_identity():
    """This process's replica identity: ``{replica_id, host, pid,
    start_ts}`` — stamped into ``dump()``'s JSON envelope and exported
    as the ``replica_info`` OpenMetrics series (profiler/export.py), so
    ledger entries and scrapes stay attributable across a fleet."""
    with _identity_lock:
        rid = _replica_id
    pid = os.getpid()
    return {"replica_id": rid if rid is not None else f"{_HOST}-{pid}",
            "host": _HOST, "pid": pid, "start_ts": _START_TS}


# -- histogram exemplars ---------------------------------------------------
# profiler.tracing installs the ambient-trace probe at import; until
# then (or with tracing disabled) observations pay one call returning
# None. Keeping the hook here (instead of importing tracing) avoids an
# import cycle: tracing needs counters from this module.

def _no_trace():
    return None


_trace_id_fn = _no_trace


def _set_trace_id_source(fn):
    global _trace_id_fn
    _trace_id_fn = fn


class Counter:
    """Monotonically increasing event count."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n=1):
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value

    def _snap(self):
        return self._value

    def _reset(self):
        with self._lock:
            self._value = 0


class Gauge:
    """Last-write-wins level (cache sizes, live bytes, ...).

    ``labels`` (optional, a flat str dict) makes this a LABELED series:
    the registry keys it as ``name{k="v",...}`` (the exposition-format
    key ``profiler.export.parse_prometheus`` produces), the exporter
    renders the label block on the sample line, and fleet federation
    treats it like a replica-labeled series — per-origin by definition,
    never summed into a fleet aggregate. The mesh-sharded serving
    layer's per-slice KV gauges (``serving.kv.*{slice="i"}``) are the
    first user (docs/OBSERVABILITY.md)."""

    __slots__ = ("name", "labels", "_value", "_lock")

    def __init__(self, name, labels=None):
        self.name = name
        self.labels = dict(labels) if labels else None
        self._value = 0
        self._lock = threading.Lock()

    def set(self, v):
        with self._lock:
            self._value = v

    def add(self, n=1):
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value

    def _snap(self):
        return self._value

    def _reset(self):
        with self._lock:
            self._value = 0


# default bounds suit the two native uses: chain lengths (1..64) and
# microsecond-scale latencies — override per-histogram at creation
_DEFAULT_BOUNDS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


class Histogram:
    """Fixed-bucket distribution: bucket[i] counts observations
    <= bounds[i]; one overflow bucket catches the rest.

    Each bucket retains one **exemplar** — the max-value observation
    seen while a trace was active, with its trace_id and wall time —
    so an SLO histogram (``serving.ttft_us``) points at an exportable
    trace for exactly the sample that defined its tail
    (profiler/tracing.py; rendered as OpenMetrics exemplars by
    profiler/export.py)."""

    __slots__ = ("name", "bounds", "_buckets", "_count", "_sum", "_min",
                 "_max", "_exemplars", "_lock")

    def __init__(self, name, bounds=_DEFAULT_BOUNDS):
        self.name = name
        self.bounds = tuple(sorted(bounds))
        self._buckets = [0] * (len(self.bounds) + 1)
        self._count = 0
        self._sum = 0.0
        self._min = None
        self._max = None
        self._exemplars = [None] * (len(self.bounds) + 1)
        self._lock = threading.Lock()

    def observe(self, v):
        tid = _trace_id_fn()
        with self._lock:
            i = 0
            for b in self.bounds:
                if v <= b:
                    break
                i += 1
            self._buckets[i] += 1
            self._count += 1
            self._sum += v
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v
            if tid is not None:
                ex = self._exemplars[i]
                if ex is None or v >= ex[0]:
                    self._exemplars[i] = (v, tid, time.time())

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    def percentile(self, q):
        """Estimate the q-quantile (0..1) from bucket counts: linear
        interpolation inside the covering bucket, edge buckets clamped
        to the observed min/max. Exact at the bucket bounds; off by at
        most one bucket width inside — good enough to see a tail move
        without hand math over the bucket table."""
        with self._lock:
            return self._pct_locked(q)

    def _pct_locked(self, q):
        if not self._count:
            return None
        target = q * self._count
        cum = 0
        for i, n in enumerate(self._buckets):
            if not n:
                continue
            # interpolate inside THIS bucket's own bounds (clamped to
            # the observed range) — the previous non-empty bucket's
            # upper edge is not a valid floor across empty buckets
            lo = self.bounds[i - 1] if i > 0 else self._min
            hi = self.bounds[i] if i < len(self.bounds) else self._max
            lo = min(max(lo, self._min), self._max)
            hi = min(max(hi, lo), self._max)
            if cum + n >= target:
                frac = (target - cum) / n
                return lo + (hi - lo) * frac
            cum += n
        return self._max

    def _snap(self):
        with self._lock:
            labels = [*map(str, self.bounds), "+inf"]
            exemplars = {
                labels[i]: {"value": ex[0], "trace_id": ex[1],
                            "ts": ex[2]}
                for i, ex in enumerate(self._exemplars)
                if ex is not None}
            return {"count": self._count, "sum": self._sum,
                    "min": self._min, "max": self._max,
                    "avg": (self._sum / self._count) if self._count else None,
                    "p50": self._pct_locked(0.50),
                    "p95": self._pct_locked(0.95),
                    "p99": self._pct_locked(0.99),
                    "buckets": dict(zip(labels, list(self._buckets))),
                    "exemplars": exemplars}

    def _reset(self):
        with self._lock:
            self._buckets = [0] * (len(self.bounds) + 1)
            self._count = 0
            self._sum = 0.0
            self._min = None
            self._max = None
            self._exemplars = [None] * (len(self.bounds) + 1)


class Registry:
    """Name -> instrument. Get-or-create is locked; the returned objects
    are cached at call sites so steady-state cost is one ``inc``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}
        self._dump_seq = 0

    def _get(self, name, cls, **kw):
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.get(name)
                if m is None:
                    m = self._metrics[name] = cls(name, **kw)
        if not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(m).__name__}, not {cls.__name__}")
        return m

    def counter(self, name):
        return self._get(name, Counter)

    def gauge(self, name, labels=None):
        """Get-or-create a gauge; ``labels`` (flat str dict) registers
        a LABELED series keyed ``name{k="v",...}`` — the canonical form
        ``profiler.export`` renders and parses, so a snapshot/scrape of
        a labeled gauge round-trips with its labels intact. The
        instrument's ``.name`` stays the BASE name; only the registry
        key carries the label block."""
        if not labels:
            return self._get(name, Gauge)
        key = label_key(name, labels)
        m = self._metrics.get(key)
        if m is None:
            with self._lock:
                m = self._metrics.get(key)
                if m is None:
                    m = self._metrics[key] = Gauge(name, labels=labels)
        if not isinstance(m, Gauge):
            raise TypeError(
                f"metric {key!r} already registered as "
                f"{type(m).__name__}, not Gauge")
        return m

    def histogram(self, name, bounds=_DEFAULT_BOUNDS):
        return self._get(name, Histogram, bounds=bounds)

    def snapshot(self, prefix=None):
        """Plain-data copy of every metric, isolated from later updates.
        ``prefix`` restricts to one metric family (``"passes."``,
        ``"deferred."``, ...) — what gates and tests diff against."""
        with self._lock:
            items = list(self._metrics.items())
        return {name: m._snap() for name, m in items
                if prefix is None or name.startswith(prefix)}

    def kinds(self, prefix=None):
        """{name: instrument class} for registered metrics — the public
        way for consumers (export.DeltaRates) to tell counters from
        gauges without reaching into registry internals."""
        with self._lock:
            return {name: type(m) for name, m in self._metrics.items()
                    if prefix is None or name.startswith(prefix)}

    def dump(self, path=None, prefix=None):
        """Human-readable table; optionally also written to ``path`` as
        JSON for machine consumption. The JSON envelope carries a
        wall-clock ``ts``, a process-monotone ``seq``, and the process
        ``replica`` identity (:func:`replica_identity`) so successive
        dumps from a gate or watcher diff/order cleanly AND stay
        attributable once more than one process exists; the metric map
        itself sits under ``"metrics"``."""
        snap = self.snapshot(prefix)
        lines = ["{:<48} {}".format("metric", "value")]
        for name in sorted(snap):
            v = snap[name]
            if isinstance(v, dict):
                desc = (f"count={v['count']} sum={v['sum']:.6g}"
                        + (f" avg={v['avg']:.6g} min={v['min']:.6g}"
                           f" max={v['max']:.6g} p50={v['p50']:.6g}"
                           f" p95={v['p95']:.6g} p99={v['p99']:.6g}"
                           if v["count"] else ""))
            else:
                desc = str(v)
            lines.append("{:<48} {}".format(name, desc))
        text = "\n".join(lines)
        if path is not None:
            with self._lock:
                self._dump_seq += 1
                seq = self._dump_seq
            with open(path, "w") as f:
                json.dump({"ts": time.time(), "seq": seq,
                           "replica": replica_identity(),
                           "metrics": snap}, f, indent=1, sort_keys=True)
        return text

    def reset(self):
        """Zero every registered metric (tests / between benchmark runs).
        Instrument objects stay valid: call sites keep cached references."""
        with self._lock:
            items = list(self._metrics.values())
        for m in items:
            m._reset()


registry = Registry()

counter = registry.counter
gauge = registry.gauge
histogram = registry.histogram
snapshot = registry.snapshot
dump = registry.dump
reset = registry.reset


# -- scenario-scoped measurement: Window over the always-on registry -------
# The registry is process-global and always on; a load scenario that
# wants "TTFT p95 during THIS burst phase" must not reset() it (other
# phases, gates, and the exporter read the same counters). A Window is
# a snapshot-diff: open it at phase start, freeze it at phase end, and
# every read sees exactly the slice of activity between the two — the
# measurement primitive profiler/scorecard.py and the fleet-load gate
# are built on (docs/OBSERVABILITY.md "Scenario observatory").


def _le_sort_key(le):
    """Numeric sort key for a bucket's ``le`` label. Canonical home —
    ``profiler.export`` and ``profiler.fleet`` alias this (both depend
    on this module, so the reverse import would cycle)."""
    return float("inf") if le in ("+Inf", "+inf") else float(le)


def cumulative_buckets(buckets):
    """Per-bucket ``{le: count}`` (the snapshot form) to CUMULATIVE
    ``{le: cum_count}`` (the exposition/merged form
    :func:`percentile_from_buckets` consumes), ordered by bound."""
    items = sorted((_le_sort_key(le), le, c)
                   for le, c in (buckets or {}).items())
    out, cum = {}, 0
    for _, le, c in items:
        cum += c
        out[le] = cum
    return out


def percentile_from_buckets(buckets, q):
    """q-quantile (0..1) from a CUMULATIVE bucket map ``{le_label:
    cumulative_count}`` (the exposition/merged form): linear
    interpolation inside the covering bucket, 0-floored (an exposition
    carries no observed min) and clamped to the last finite bound for
    the +inf bucket. None on an empty histogram. Pure — fleet SLO
    percentiles, the skew rule, and Window percentiles are
    deterministic on fixed bucket maps. (Hoisted from profiler/fleet.py
    — the ONE bucket-interpolation implementation; fleet re-exports
    it.)"""
    items = sorted((_le_sort_key(le), c)
                   for le, c in (buckets or {}).items())
    if not items:
        return None
    total = items[-1][1]
    if not total:
        return None
    target = q * total
    prev_bound, prev_cum, last_finite = 0.0, 0, 0.0
    for bound, cum in items:
        finite = bound != float("inf")
        if cum >= target:
            n = cum - prev_cum
            frac = (target - prev_cum) / n if n else 1.0
            hi = bound if finite else max(prev_bound, last_finite)
            return prev_bound + (hi - prev_bound) * frac
        if finite:
            last_finite = bound
        prev_bound, prev_cum = (bound if finite else prev_bound), cum
    return last_finite


def _hist_delta(cur, prev):
    """Windowed slice of one histogram snapshot dict. Buckets/count/sum
    are exact diffs (closure: window + pre-window == total, bucket by
    bucket); min/max are not recoverable from two snapshots so the
    delta reports the window's bucket-derived percentiles instead and
    leaves min/max None. A reset() between the snapshots makes a diff
    go negative — the window then treats ``cur`` as a fresh start."""
    pb = prev.get("buckets") if isinstance(prev, dict) else None
    buckets = {le: c - (pb.get(le, 0) if pb else 0)
               for le, c in cur["buckets"].items()}
    count = cur["count"] - (prev["count"] if isinstance(prev, dict) else 0)
    total = cur["sum"] - (prev["sum"] if isinstance(prev, dict) else 0)
    if count < 0 or any(v < 0 for v in buckets.values()):
        buckets = dict(cur["buckets"])
        count, total = cur["count"], cur["sum"]
    cum = cumulative_buckets(buckets)
    return {"count": count, "sum": total,
            "avg": (total / count) if count else None,
            "min": None, "max": None,
            "p50": percentile_from_buckets(cum, 0.50),
            "p95": percentile_from_buckets(cum, 0.95),
            "p99": percentile_from_buckets(cum, 0.99),
            "buckets": buckets}


def window_delta(before, after):
    """Pure snapshot diff ``after - before`` over two :func:`snapshot`
    maps: scalars (counters AND gauges) become numeric deltas,
    histograms become windowed dicts (:func:`_hist_delta` — bucket-wise
    diffs plus window percentiles). Metrics born after ``before`` diff
    against zero. Scalar deltas are SIGNED (gauges legitimately fall;
    a counter going negative means a reset() landed between the
    snapshots — the one case where closure cannot hold, because data
    was destroyed). Exemplars are point-in-time, not diffable, and are
    dropped."""
    out = {}
    for name, cur in after.items():
        prev = before.get(name)
        if isinstance(cur, dict):
            out[name] = _hist_delta(cur, prev)
        else:
            prev_v = prev if isinstance(prev, (int, float)) else 0
            out[name] = cur - prev_v
    return out


class Window:
    """Scenario-scoped view of the registry: ``Window(prefix)`` pins a
    base snapshot; :meth:`freeze` pins the end; every read diffs the
    two (or diffs live against the base while unfrozen). Global state
    is never reset — any number of overlapping windows observe the
    same registry, each seeing exactly its own slice.

        w = metrics.Window("serving.")
        ... drive one scenario phase ...
        w.freeze()
        w.value("serving.admitted")            # counter delta
        w.percentile("serving.ttft_us", 0.95)  # windowed p95
    """

    def __init__(self, prefix=None, label=None):
        self.prefix = prefix
        self.label = label
        self.start_ts = time.time()
        self.end_ts = None
        self._base = registry.snapshot(prefix)
        self._end = None

    def freeze(self):
        """Pin the window's end snapshot (idempotent); reads stop
        tracking the live registry. Returns self for chaining."""
        if self._end is None:
            self._end = registry.snapshot(self.prefix)
            self.end_ts = time.time()
        return self

    @property
    def frozen(self):
        return self._end is not None

    def elapsed_s(self):
        return (self.end_ts or time.time()) - self.start_ts

    def base(self):
        """The base snapshot (plain data, already isolated)."""
        return self._base

    def delta(self):
        """``window_delta(base, end-or-live)`` — the full windowed
        view: scalar deltas + histogram slices."""
        end = self._end if self._end is not None \
            else registry.snapshot(self.prefix)
        return window_delta(self._base, end)

    def value(self, name, default=0):
        """Scalar delta of one counter/gauge (``default`` when the
        metric never appeared)."""
        v = self.delta().get(name, default)
        return v if isinstance(v, (int, float)) else default

    def hist(self, name):
        """Windowed histogram dict for ``name`` (None when absent or
        not a histogram)."""
        v = self.delta().get(name)
        return v if isinstance(v, dict) else None

    def percentile(self, name, q):
        """Windowed q-quantile of histogram ``name`` — exactly the
        observations that landed inside this window. None when the
        window saw none."""
        h = self.hist(name)
        if not h:
            return None
        return percentile_from_buckets(cumulative_buckets(h["buckets"]), q)


# -- XLA compile telemetry (jax.monitoring) --------------------------------

_monitoring_installed = False

# per-thread cumulative backend-compile seconds: XLA compiles run
# synchronously on the dispatching thread, so a delta of THIS value
# around a dispatch attributes exactly the compiles that dispatch
# triggered — unlike the process-global histogram sum, which would
# bill a concurrent engine's compile to whoever read the delta
# (profiler/accounting.py relies on this for per-request billing)
_thread_compile = threading.local()


def thread_compile_seconds():
    """Cumulative backend-compile seconds observed on the calling
    thread (0.0 where the jax.monitoring listener is unavailable)."""
    return getattr(_thread_compile, "seconds", 0.0)


def _install_jax_monitoring():
    """Fold jax's own compile events into the registry. Idempotent; the
    listener is module-global and permanent (jax has no unsubscribe), so
    it filters cheaply by prefix."""
    global _monitoring_installed
    if _monitoring_installed:
        return
    try:
        import jax.monitoring as jm

        c_count = counter("xla.compile.count")
        h_secs = histogram(
            "xla.compile.seconds",
            bounds=(0.01, 0.05, 0.1, 0.5, 1, 5, 10, 60, 300))
        c_trace = counter("xla.trace.count")

        def _on_duration(event, duration, **kw):
            # /jax/core/compile/backend_compile_duration is the real XLA
            # compile; jaxpr_trace_duration counts python traces
            if event.endswith("backend_compile_duration"):
                c_count.inc()
                h_secs.observe(duration)
                _thread_compile.seconds = getattr(
                    _thread_compile, "seconds", 0.0) + duration
            elif event.endswith("jaxpr_trace_duration"):
                c_trace.inc()

        jm.register_event_duration_secs_listener(_on_duration)
        _monitoring_installed = True
    except Exception:  # noqa: BLE001 — telemetry must never break dispatch
        _monitoring_installed = True


_install_jax_monitoring()
